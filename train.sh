#!/usr/bin/env bash
# Launcher — reference-compatible surface (reference: train.sh:1-14).
# One controller process per TPU host; on a multi-host pod run this script on
# every host with RANK=<host index> and the shared coordinator --dist-url.
export PYTHONPATH=./:${PYTHONPATH}

python train_distributed.py \
    --num-nodes 1 \
    --rank 0 \
    --multiprocessing \
    --dist-backend tpu \
    --dist-url tcp://localhost:9001 \
    --log-dir run/distributed-with-syncbn \
    --file-name-cfg ResNet50 \
    --cfg-filepath config/ResNet50.yml \
    --seed 1029 &
