"""TPU-native distributed ImageNet training — CLI entry point.

The user-facing surface of the reference (train_distributed.py:38-86) kept
intact: the same 9 flags, the same YAML configs, the same log/TensorBoard
layout — with ``--dist-backend tpu`` selecting the JAX/XLA runtime (the
``nccl`` default is accepted as a compat alias).  ``--multiprocessing`` is a
no-op under the single-controller-per-host design (SURVEY.md §7 deviations).

Crash handling reproduces the reference's *intent*, not its bug: on failure
only the TensorBoard event subdir (``<log-dir>/tf-board-logs``) is removed —
the reference's ``shutil.rmtree(log_dir, "tf-board-logs")`` (:82) passes the
subdir name as ``ignore_errors`` and would delete the whole log dir.
"""
import argparse
import os
import shutil
import time
import traceback
from functools import partial

from pytorch_distributed_training_tpu.config_parsing import (
    TB_SUBDIR,
    get_cfg,
    get_tb_writer,
    get_train_logger,
)
from pytorch_distributed_training_tpu.engine import Runner
from pytorch_distributed_training_tpu.logger import MultiProcessLoggerListener
from pytorch_distributed_training_tpu.utils import make_deterministic

START_METHOD = "spawn"


def main():
    parser = argparse.ArgumentParser(description="TPU ImageNet Training")
    parser.add_argument("--num-nodes", default=-1, type=int,
                        help="number of hosts for distributed training")
    parser.add_argument("--rank", default=-1, type=int,
                        help="host rank for distributed training")
    parser.add_argument("--dist-url", default="tcp://127.0.0.1:9876", type=str,
                        help="coordinator address (maps to jax.distributed.initialize)")
    parser.add_argument("--dist-backend", default="tpu", type=str,
                        help="distributed backend (tpu/xla; nccl accepted as alias)")
    parser.add_argument("--seed", default=None, type=int,
                        help="seed for initializing training.")
    parser.add_argument("--multiprocessing", action="store_true",
                        help="compat no-op: one controller process drives all local devices")
    parser.add_argument("--file-name-cfg", type=str)
    parser.add_argument("--log-dir", type=str)
    parser.add_argument("--cfg-filepath", type=str)
    args = parser.parse_args()

    if args.seed is not None:
        print("Set seed:", args.seed)
        make_deterministic(args.seed)

    logger_constructor = partial(
        get_train_logger, logdir=args.log_dir, filename=args.file_name_cfg
    )
    logger_listener = MultiProcessLoggerListener(logger_constructor, START_METHOD)
    logger = logger_listener.get_logger()

    global_cfg = get_cfg(args.cfg_filepath)
    runner = Runner(
        num_nodes=args.num_nodes,
        rank=args.rank,
        seed=args.seed,
        dist_url=args.dist_url,
        dist_backend=args.dist_backend,
        multiprocessing=args.multiprocessing,
        logger_queue=logger_listener.queue,
        global_cfg=global_cfg,
        tb_writer_constructor=partial(get_tb_writer, args.log_dir, args.file_name_cfg),
    )
    logger.info("Starting distributed runner")
    try:
        runner()
    except Exception as e:
        tb = traceback.format_exc()
        logger.critical("While running, exception:\n%s\nTraceback:\n%s", str(e), str(tb))
        shutil.rmtree(os.path.join(args.log_dir, TB_SUBDIR), ignore_errors=True)
        time.sleep(1.5)
    finally:
        # make sure listener is stopped
        logger_listener.stop()


if __name__ == "__main__":
    main()
