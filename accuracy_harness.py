"""Converged-accuracy parity harness: this framework vs torch, same pixels.

The reference's only published oracle is an ImageNet accuracy table
(/root/reference/README.md:7-13).  The full 450k-iteration ImageNet run
does not fit one bench chip + no mounted dataset, so this harness produces
the scaled-down version of that evidence end to end:

  1. ``gen``   — build a REAL-JPEG ImageFolder dataset hard enough not to
     saturate: 40 Gabor-texture classes on an (orientation, frequency) grid
     whose per-image parameter jitter OVERLAPS neighboring classes, plus
     pixel noise — an irreducible Bayes error, so converged top-1 plateaus
     meaningfully below 100% and differences between trainers are visible.
  2. ``streams`` — precompute the augmented batch stream ONCE through this
     framework's input pipeline (native JPEG decode + RandomResizedCrop +
     flip, data/loader.py) into uint8 memmaps.  Both trainers then consume
     byte-identical pixels; normalization is one shared numpy function, so
     their f32 inputs are bitwise equal and the comparison isolates
     model/optimizer/BN numerics.
  3. ``ours``  — train ResNet-18 through this framework's compiled train
     step (engine/steps.py: forward, CE, backward, SGD+momentum+coupled-WD,
     BN updates as one XLA program) from a torch-ported init.
  4. ``torch`` — train the line-faithful torchvision-twin ResNet-18
     (tests/test_torch_port.py) with torch.optim.SGD + per-iter milestone
     schedule — the reference recipe's semantics — from the SAME init.

Identical recipe, identical init, identical data order: final top-1 must
agree within run-to-run noise.  ``bench.py accuracy`` drives all four
stages and prints one JSON line with both numbers.
"""
from __future__ import annotations

import json
import os
import shutil
import time

import numpy as np

IMAGE_SIZE = 64  # training crop; source JPEGs are 96x96
N_CLASSES = 40


# ----------------------------------------------------------------------
# Stage 1: dataset generation
# ----------------------------------------------------------------------
def make_texture_dataset(
    root: str,
    n_classes: int = N_CLASSES,
    per_class_train: int = 200,
    per_class_val: int = 40,
    size: int = 96,
    seed: int = 0,
) -> None:
    """40 Gabor-texture classes over an 8x5 (orientation x frequency) grid.

    Class c -> center orientation theta_c (spacing pi/8) and spatial
    frequency f_c (geometric ladder).  Per image: theta jittered by a
    Gaussian whose sigma is ~40% of the class spacing (neighboring classes
    OVERLAP -> irreducible error), frequency jittered x U[0.85, 1.18],
    random phase, class-hue color with jitter, strong additive noise,
    random brightness/contrast.  JPEG q85 at photo-ish 96x96.
    """
    from PIL import Image

    n_orient, n_freq = 8, 5
    assert n_orient * n_freq == n_classes
    freqs = 6.0 * (1.5 ** np.arange(n_freq))  # cycles per image: 6..30
    sigma_theta = 0.4 * (np.pi / n_orient)

    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    for split, per_class in (("train", per_class_train), ("val", per_class_val)):
        rng = np.random.default_rng(seed if split == "train" else seed + 1)
        for c in range(n_classes):
            theta_c = (c % n_orient) * np.pi / n_orient
            f_c = freqs[c // n_orient]
            hue_c = (c * 0.61803) % 1.0  # golden-ratio hue spacing
            d = os.path.join(root, split, f"class_{c:03d}")
            os.makedirs(d, exist_ok=True)
            for i in range(per_class):
                theta = theta_c + rng.normal(0.0, sigma_theta)
                f = f_c * rng.uniform(0.85, 1.18)
                phase = rng.uniform(0, 2 * np.pi)
                grating = np.sin(
                    2 * np.pi * f * (xx * np.cos(theta) + yy * np.sin(theta))
                    + phase
                )
                # class hue with jitter -> RGB via a cheap cosine palette
                hue = (hue_c + rng.normal(0, 0.04)) % 1.0
                base = 0.5 + 0.5 * np.cos(
                    2 * np.pi * (hue + np.array([0.0, 1 / 3, 2 / 3]))
                )
                amp = rng.uniform(0.35, 0.55)
                img = 0.5 + amp * grating[..., None] * base[None, None, :]
                img += rng.normal(0, 0.10, img.shape)  # heavy pixel noise
                img = img * rng.uniform(0.8, 1.2) + rng.uniform(-0.08, 0.08)
                u8 = np.clip(img * 255.0, 0, 255).astype(np.uint8)
                Image.fromarray(u8).save(
                    os.path.join(d, f"img_{i:04d}.jpg"), "JPEG", quality=85
                )


# ----------------------------------------------------------------------
# Stage 2: byte-identical augmented streams (this framework's pipeline)
# ----------------------------------------------------------------------
def precompute_streams(
    root: str, out_dir: str, iters: int, batch: int, seed: int = 0
) -> None:
    """Decode + augment through the framework loader once; save uint8."""
    from pytorch_distributed_training_tpu.data import (
        DataLoader,
        RandomSampler,
        SequentialSampler,
        get_dataset,
    )
    from pytorch_distributed_training_tpu.utils import (
        make_deterministic,
        make_iter_dataloader,
    )

    os.makedirs(out_dir, exist_ok=True)
    make_deterministic(seed)
    train_ds = get_dataset("imagenet", root, "train", image_size=IMAGE_SIZE)
    loader = DataLoader(
        train_ds, batch_size=batch, sampler=RandomSampler(len(train_ds), seed=seed),
        num_workers=1, drop_last=True, output_dtype="uint8",
    )
    imgs = np.lib.format.open_memmap(
        os.path.join(out_dir, "train_imgs.npy"), mode="w+",
        dtype=np.uint8, shape=(iters, batch, IMAGE_SIZE, IMAGE_SIZE, 3),
    )
    labels = np.lib.format.open_memmap(
        os.path.join(out_dir, "train_labels.npy"), mode="w+",
        dtype=np.int32, shape=(iters, batch),
    )
    stream = make_iter_dataloader(loader)
    for it in range(iters):
        b_img, b_lab = next(stream)
        imgs[it] = b_img
        labels[it] = np.asarray(b_lab, np.int32)
    imgs.flush()
    labels.flush()
    loader.close()

    val_ds = get_dataset("imagenet", root, "val", image_size=IMAGE_SIZE)
    vloader = DataLoader(
        val_ds, batch_size=batch, sampler=SequentialSampler(len(val_ds)),
        num_workers=1, drop_last=False, output_dtype="uint8",
    )
    v_imgs, v_labs = [], []
    for b_img, b_lab in vloader:
        v_imgs.append(np.asarray(b_img))
        v_labs.append(np.asarray(b_lab, np.int32))
    vloader.close()
    np.save(os.path.join(out_dir, "val_imgs.npy"), np.concatenate(v_imgs))
    np.save(os.path.join(out_dir, "val_labels.npy"), np.concatenate(v_labs))


def _normalize(u8: np.ndarray) -> np.ndarray:
    """The ONE normalization both trainers share (bitwise-identical f32)."""
    from pytorch_distributed_training_tpu.data import IMAGENET_MEAN, IMAGENET_STD

    return ((u8.astype(np.float32) / 255.0) - IMAGENET_MEAN) / IMAGENET_STD


def _shared_init_state_dict(model_name: str = "ResNet18", seed: int = 0):
    """torch-twin ResNet init (torchvision init semantics) — the shared
    starting point for BOTH trainers.  ``model_name``: ResNet18 (basic
    blocks) or ResNet50 (bottleneck, the reference's flagship recipe
    /root/reference/config/ResNet50.yml)."""
    import sys

    import torch

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
    from test_torch_port import _TORCH_CONFIGS, TorchResNet

    block, layers = _TORCH_CONFIGS[model_name]
    torch.manual_seed(seed)
    tm = TorchResNet(block, layers, num_classes=N_CLASSES)
    return tm


def _recipe(iters: int):
    """lr/momentum/wd + milestone schedule (reference recipe shape scaled
    to batch 64; milestones at 60%/85% of the run, gamma 0.1)."""
    return dict(
        lr=0.025, momentum=0.9, weight_decay=1e-4,
        milestones=[int(iters * 0.6), int(iters * 0.85)], gamma=0.1,
    )


# ----------------------------------------------------------------------
# Stage 3: this framework (compiled step on the default platform)
# ----------------------------------------------------------------------
def train_ours(
    stream_dir: str,
    iters: int,
    eval_every: int = 0,
    log=print,
    model_name: str = "ResNet18",
    sync_bn: bool = False,
    return_state: bool = False,
    eval_in_loop: bool = True,
):
    """Train through this framework's compiled step.

    ``sync_bn``: run the DP+SyncBN path — meaningful on a multi-device
    mesh (the 8-virtual-device CPU mesh via JAX_PLATFORMS=cpu +
    xla_force_host_platform_device_count), where the batch shards over
    ``data`` and BN moments cross the mesh in-graph (ops/batch_norm.py).
    The DP==1dev convergence pin (VERDICT r4 #4) runs this twice on CPU:
    once on 1 device, once on 8 with sync_bn, same streams.

    ``return_state``: return ``(top1, final TrainState)`` instead of bare
    ``top1`` — the extension point ``.accuracy_dp_pin.py`` hashes the final
    params/batch-stats through (ADVICE r5 #3: the pin previously duplicated
    this whole function and could silently desynchronize from it).

    ``eval_in_loop``: run the (relatively expensive) validation sweep at
    every ``eval_every`` milestone; False logs the loss only — the pin's
    cadence, where only the FINAL accuracy matters.
    """
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.engine import (
        build_eval_step,
        build_train_step,
        init_train_state,
    )
    from pytorch_distributed_training_tpu.models import get_model
    from pytorch_distributed_training_tpu.models.torch_port import (
        import_torch_resnet_state_dict,
    )
    from pytorch_distributed_training_tpu.optimizers import SGD
    from pytorch_distributed_training_tpu.parallel import (
        batch_sharding,
        make_mesh,
        replicated_sharding,
    )
    from pytorch_distributed_training_tpu.schedulers import multi_step_lr

    imgs = np.load(os.path.join(stream_dir, "train_imgs.npy"), mmap_mode="r")
    labels = np.load(os.path.join(stream_dir, "train_labels.npy"))
    v_imgs = np.load(os.path.join(stream_dir, "val_imgs.npy"))
    v_labs = np.load(os.path.join(stream_dir, "val_labels.npy"))
    assert iters <= imgs.shape[0], f"stream has {imgs.shape[0]} iters"
    batch = imgs.shape[1]
    rec = _recipe(iters)

    from pytorch_distributed_training_tpu.parallel.mesh import DATA_AXIS

    model = get_model(
        model_name, num_classes=N_CLASSES,
        axis_name=DATA_AXIS if sync_bn else None,
    )
    mesh = make_mesh()
    if sync_bn:
        log(f"[ours] sync_bn over {mesh.devices.size} device(s)")
    opt = SGD(lr=rec["lr"], momentum=rec["momentum"], weight_decay=rec["weight_decay"])
    state = init_train_state(
        model, opt, jax.random.PRNGKey(0),
        jnp.zeros((1, IMAGE_SIZE, IMAGE_SIZE, 3)),
    )
    # shared torch init -> bitwise-identical starting weights
    tm = _shared_init_state_dict(model_name)
    variables = import_torch_resnet_state_dict(
        {"params": state.params, "batch_stats": state.batch_stats},
        tm.state_dict(),
    )
    state = state.replace(
        params=variables["params"], batch_stats=variables["batch_stats"]
    )
    state = jax.device_put(state, replicated_sharding(mesh))
    lr_fn = multi_step_lr(rec["lr"], rec["milestones"], rec["gamma"])
    step = build_train_step(model, opt, lr_fn, mesh, sync_bn=sync_bn)
    eval_step = build_eval_step(model, mesh)
    img_sh = batch_sharding(mesh, 4)
    lab_sh = batch_sharding(mesh, 1)

    def evaluate(st):
        accs, n = [], 0
        for i in range(0, len(v_imgs), batch):
            bi = _normalize(v_imgs[i:i + batch])
            bl = v_labs[i:i + batch]
            _, acc1, _ = eval_step(
                st,
                jax.device_put(bi, img_sh),
                jax.device_put(bl, lab_sh),
            )
            accs.append(float(acc1) * len(bl))
            n += len(bl)
        return sum(accs) / n

    t0 = time.perf_counter()
    for it in range(iters):
        g_img = jax.device_put(_normalize(np.asarray(imgs[it])), img_sh)
        g_lab = jax.device_put(labels[it], lab_sh)
        state, loss = step(state, g_img, g_lab)
        if eval_every and (it + 1) % eval_every == 0:
            mid = (
                f"val@1 {evaluate(state):.2f}%  " if eval_in_loop else ""
            )
            log(
                f"[ours] iter {it + 1}/{iters} loss {float(loss):.6f} "
                f"{mid}({time.perf_counter() - t0:.0f}s)"
            )
    top1 = evaluate(state)
    log(f"[ours] FINAL iter {iters} val top-1 {top1:.2f}%")
    if return_state:
        return top1, state
    return top1


# ----------------------------------------------------------------------
# Stage 4: torch reference-semantics trainer (CPU)
# ----------------------------------------------------------------------
def train_torch(
    stream_dir: str,
    iters: int,
    eval_every: int = 0,
    log=print,
    model_name: str = "ResNet18",
):
    import torch
    import torch.nn.functional as F

    imgs = np.load(os.path.join(stream_dir, "train_imgs.npy"), mmap_mode="r")
    labels = np.load(os.path.join(stream_dir, "train_labels.npy"))
    v_imgs = np.load(os.path.join(stream_dir, "val_imgs.npy"))
    v_labs = np.load(os.path.join(stream_dir, "val_labels.npy"))
    assert iters <= imgs.shape[0]
    batch = imgs.shape[1]
    rec = _recipe(iters)

    model = _shared_init_state_dict(model_name)
    model.train()
    optim = torch.optim.SGD(
        model.parameters(), lr=rec["lr"], momentum=rec["momentum"],
        weight_decay=rec["weight_decay"],
    )
    sched = torch.optim.lr_scheduler.MultiStepLR(
        optim, milestones=rec["milestones"], gamma=rec["gamma"]
    )

    def evaluate():
        model.eval()
        correct, n = 0, 0
        with torch.no_grad():
            for i in range(0, len(v_imgs), batch):
                x = torch.from_numpy(
                    _normalize(v_imgs[i:i + batch])
                ).permute(0, 3, 1, 2)
                pred = model(x).argmax(1).numpy()
                correct += int((pred == v_labs[i:i + batch]).sum())
                n += len(pred)
        model.train()
        return 100.0 * correct / n

    t0 = time.perf_counter()
    for it in range(iters):
        x = torch.from_numpy(_normalize(np.asarray(imgs[it]))).permute(0, 3, 1, 2)
        y = torch.from_numpy(labels[it].astype(np.int64))
        optim.zero_grad(set_to_none=True)
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        optim.step()
        sched.step()  # per iteration (reference :299)
        if eval_every and (it + 1) % eval_every == 0:
            log(
                f"[torch] iter {it + 1}/{iters} loss {float(loss):.4f} "
                f"val@1 {evaluate():.2f}%  ({time.perf_counter() - t0:.0f}s)"
            )
    top1 = evaluate()
    log(f"[torch] FINAL iter {iters} val top-1 {top1:.2f}%")
    return top1


# ----------------------------------------------------------------------
# Generator parameters pinned into the stage done-markers (ADVICE r4 #3):
# the cached dataset/streams are only reused when the parameters that
# produced them match — changing N_CLASSES, per-class counts, IMAGE_SIZE,
# or seeds rebuilds instead of silently reusing stale artifacts.
_GEN_PARAMS = dict(
    n_classes=N_CLASSES, per_class_train=200, per_class_val=40, size=96,
    seed=0,
)


def _stream_params(iters: int, batch: int) -> dict:
    # streams are a pure function of the generated dataset + (iters, batch,
    # crop, seed), so the generator params fold in: a dataset rebuild must
    # also invalidate streams derived from the old dataset
    return dict(iters=iters, batch=batch, image_size=IMAGE_SIZE, seed=0,
                gen=_GEN_PARAMS)


def _stage_cached(done_path: str, params: dict, log, what: str) -> bool:
    """True if the stage's done-marker exists AND records ``params``."""
    if not os.path.exists(done_path):
        return False
    try:
        recorded = json.loads(open(done_path).read())
    except (ValueError, OSError):
        recorded = None
    if recorded != params:
        log(f"[{what}] cached artifacts were built with {recorded}, "
            f"need {params} — rebuilding")
        return False
    return True


def run_all(work_dir: str, iters: int, batch: int = 64, eval_every: int = 0,
            skip_torch: bool = False, log=print,
            model_name: str = "ResNet18", sync_bn: bool = False,
            stream_iters: int = 0) -> dict:
    """gen -> streams -> ours -> torch; cached by directory contents.

    ``stream_iters`` (default: ``iters``): length of the precomputed
    stream — a shorter-horizon run (``iters`` < ``stream_iters``) trains
    on the prefix of the longer stream, same pixels, no regeneration.
    """
    stream_iters = stream_iters or iters
    if stream_iters < iters:
        raise ValueError(
            f"stream_iters {stream_iters} shorter than the {iters}-iter run"
        )
    data_root = os.path.join(work_dir, "data")
    stream_dir = os.path.join(work_dir, f"streams_i{stream_iters}_b{batch}")
    # stage caching gates on DONE MARKERS written after the final flush, not
    # bare file existence — an interrupted generation leaves partial
    # artifacts (the stream memmap is created full-size before filling)
    # that must be rebuilt, never silently reused; the marker records the
    # generator parameters (ADVICE r4 #3)
    gen_done = os.path.join(data_root, ".done")
    if not _stage_cached(gen_done, _GEN_PARAMS, log, "gen"):
        # wipe before rebuilding: the generator only ADDS files, so a
        # parameter change (fewer images/classes) would otherwise leave
        # stale JPEGs mixed into the "rebuilt" dataset — exactly the
        # silent-staleness class the done-markers exist to prevent
        if os.path.isdir(data_root):
            shutil.rmtree(data_root)
        log("[gen] building 40-class texture JPEG dataset...")
        make_texture_dataset(data_root, **_GEN_PARAMS)
        open(gen_done, "w").write(json.dumps(_GEN_PARAMS))
    stream_done = os.path.join(stream_dir, ".done")
    if not _stage_cached(stream_done, _stream_params(stream_iters, batch), log, "streams"):
        if os.path.isdir(stream_dir):
            shutil.rmtree(stream_dir)
        log(f"[streams] precomputing {stream_iters} x {batch} augmented batches...")
        precompute_streams(data_root, stream_dir, stream_iters, batch)
        open(stream_done, "w").write(json.dumps(_stream_params(stream_iters, batch)))
    ours = train_ours(
        stream_dir, iters, eval_every, log=log, model_name=model_name,
        sync_bn=sync_bn,
    )
    result = {"ours_top1": round(ours, 2), "iters": iters, "batch": batch,
              "model": model_name}
    if not skip_torch:
        ref = train_torch(
            stream_dir, iters, eval_every, log=log, model_name=model_name
        )
        result["torch_top1"] = round(ref, 2)
        result["gap_pts"] = round(ours - ref, 2)
    return result


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("stage", choices=["gen", "streams", "ours", "torch", "all"])
    ap.add_argument("--work-dir", default=".accuracy")
    ap.add_argument("--iters", type=int, default=2000)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--eval-every", type=int, default=250)
    ap.add_argument("--model", default="ResNet18",
                    choices=["ResNet18", "ResNet50"])
    ap.add_argument("--sync-bn", action="store_true",
                    help="ours: DP+SyncBN path (pair with JAX_PLATFORMS=cpu"
                         " + an 8-virtual-device mesh for the DP==1dev pin)")
    ap.add_argument("--platform", choices=["chip", "cpu"], default=None,
                    help="ours: pin the jax backend — 'cpu' forces "
                         "JAX_PLATFORMS=cpu so the ours-on-CPU vs "
                         "torch-on-CPU SAME-PLATFORM comparison (VERDICT "
                         "r5 blocker #2) is one command; 'chip' clears any "
                         "inherited CPU pin so the accelerator is used. "
                         "Default: leave the environment's choice alone.")
    ap.add_argument("--stream-iters", type=int, default=None,
                    help="length of the PRECOMPUTED stream to train from "
                         "(default: --iters). Lets shorter-horizon runs "
                         "(scaled recipes; the per-iter milestones come "
                         "from --iters) reuse one long stream prefix — "
                         "same pixels, no regeneration.")
    args = ap.parse_args()

    # must happen before the first (lazy) jax import inside train_ours —
    # jax reads JAX_PLATFORMS at backend-discovery time
    if args.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    elif args.platform == "chip":
        os.environ.pop("JAX_PLATFORMS", None)

    work = args.work_dir
    data_root = os.path.join(work, "data")
    stream_iters = args.stream_iters or args.iters
    stream_dir = os.path.join(work, f"streams_i{stream_iters}_b{args.batch}")
    if args.stage == "gen":
        make_texture_dataset(data_root, **_GEN_PARAMS)
    elif args.stage == "streams":
        precompute_streams(data_root, stream_dir, stream_iters, args.batch)
    elif args.stage == "ours":
        train_ours(stream_dir, args.iters, args.eval_every,
                   model_name=args.model, sync_bn=args.sync_bn)
    elif args.stage == "torch":
        train_torch(stream_dir, args.iters, args.eval_every,
                    model_name=args.model)
    else:
        out = run_all(work, args.iters, args.batch, args.eval_every,
                      model_name=args.model, sync_bn=args.sync_bn,
                      stream_iters=stream_iters)
        print(json.dumps(out))
