"""DP8+SyncBN == 1-device convergence pin (VERDICT r4 #4).

Runs the accuracy-harness recipe (same streams, same ported torch init,
milestones auto-scaled by ``_recipe``) through the framework's compiled
step on EITHER one CPU device or the 8-virtual-device CPU mesh with
SyncBN, and prints the final val top-1 plus a SHA-256 over the final
params/batch-stats bytes.  The two invocations must agree: SyncBN's
global-batch moments over 8 shards are the same math as 1-device BN over
the unsharded batch, and the DP oracle (tests/test_engine.py::
test_dp_step_matches_single_device) pins each step exactly — this script
extends that to a full converged run.

The training loop itself IS ``accuracy_harness.train_ours`` (ADVICE r5
#3: this file used to duplicate its ~80 setup/loop lines and could
silently desynchronize from the harness it pins); this wrapper only adds
the device-count assert, the per-tag log prefix, and the state hash from
``return_state=True``.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=1 \
        python .accuracy_dp_pin.py 1dev  --iters 400
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python .accuracy_dp_pin.py dp8   --iters 400
"""
import argparse
import hashlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

import accuracy_harness as ah


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("tag", choices=["1dev", "dp8"])
    ap.add_argument("--iters", type=int, default=400)
    ap.add_argument("--eval-every", type=int, default=100)
    ap.add_argument("--stream-dir", default=".accuracy/streams_i2000_b64")
    args = ap.parse_args()
    sync_bn = args.tag == "dp8"

    n_dev = jax.device_count()
    expect = 8 if sync_bn else 1
    assert n_dev == expect, (
        f"{args.tag} needs {expect} devices, got {n_dev}; set "
        f"XLA_FLAGS=--xla_force_host_platform_device_count={expect}"
    )

    def log(msg):
        print(f"[{args.tag}] {msg}", flush=True)

    top1, state = ah.train_ours(
        args.stream_dir, args.iters, eval_every=args.eval_every, log=log,
        model_name="ResNet18", sync_bn=sync_bn, return_state=True,
        eval_in_loop=False,  # the pin compares only the FINAL state
    )

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(
        {"params": state.params, "batch_stats": state.batch_stats}
    ):
        h.update(np.asarray(jax.device_get(leaf)).tobytes())
    print(f"[{args.tag}] FINAL top1 {top1:.4f}  state_sha256 {h.hexdigest()}",
          flush=True)


if __name__ == "__main__":
    main()
