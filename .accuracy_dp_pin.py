"""DP8+SyncBN == 1-device convergence pin (VERDICT r4 #4).

Runs the accuracy-harness recipe (same streams, same ported torch init,
milestones auto-scaled by ``_recipe``) through the framework's compiled
step on EITHER one CPU device or the 8-virtual-device CPU mesh with
SyncBN, and prints the final val top-1 plus a SHA-256 over the final
params/batch-stats bytes.  The two invocations must agree: SyncBN's
global-batch moments over 8 shards are the same math as 1-device BN over
the unsharded batch, and the DP oracle (tests/test_engine.py::
test_dp_step_matches_single_device) pins each step exactly — this script
extends that to a full converged run.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=1 \
        python .accuracy_dp_pin.py 1dev  --iters 400
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python .accuracy_dp_pin.py dp8   --iters 400
"""
import argparse
import hashlib
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

import accuracy_harness as ah


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("tag", choices=["1dev", "dp8"])
    ap.add_argument("--iters", type=int, default=400)
    ap.add_argument("--eval-every", type=int, default=100)
    ap.add_argument("--stream-dir", default=".accuracy/streams_i2000_b64")
    args = ap.parse_args()
    sync_bn = args.tag == "dp8"

    n_dev = jax.device_count()
    expect = 8 if sync_bn else 1
    assert n_dev == expect, (
        f"{args.tag} needs {expect} devices, got {n_dev}; set "
        f"XLA_FLAGS=--xla_force_host_platform_device_count={expect}"
    )

    from pytorch_distributed_training_tpu.engine import (
        build_eval_step,
        build_train_step,
        init_train_state,
    )
    from pytorch_distributed_training_tpu.models import get_model
    from pytorch_distributed_training_tpu.models.torch_port import (
        import_torch_resnet_state_dict,
    )
    from pytorch_distributed_training_tpu.optimizers import SGD
    from pytorch_distributed_training_tpu.parallel import (
        batch_sharding,
        make_mesh,
        replicated_sharding,
    )
    from pytorch_distributed_training_tpu.parallel.mesh import DATA_AXIS
    from pytorch_distributed_training_tpu.schedulers import multi_step_lr

    imgs = np.load(os.path.join(args.stream_dir, "train_imgs.npy"), mmap_mode="r")
    labels = np.load(os.path.join(args.stream_dir, "train_labels.npy"))
    v_imgs = np.load(os.path.join(args.stream_dir, "val_imgs.npy"))
    v_labs = np.load(os.path.join(args.stream_dir, "val_labels.npy"))
    batch = imgs.shape[1]
    rec = ah._recipe(args.iters)

    model = get_model(
        "ResNet18", num_classes=ah.N_CLASSES,
        axis_name=DATA_AXIS if sync_bn else None,
    )
    mesh = make_mesh()
    opt = SGD(lr=rec["lr"], momentum=rec["momentum"],
              weight_decay=rec["weight_decay"])
    state = init_train_state(
        model, opt, jax.random.PRNGKey(0),
        jnp.zeros((1, ah.IMAGE_SIZE, ah.IMAGE_SIZE, 3)),
    )
    tm = ah._shared_init_state_dict("ResNet18")
    variables = import_torch_resnet_state_dict(
        {"params": state.params, "batch_stats": state.batch_stats},
        tm.state_dict(),
    )
    state = state.replace(
        params=variables["params"], batch_stats=variables["batch_stats"]
    )
    state = jax.device_put(state, replicated_sharding(mesh))
    lr_fn = multi_step_lr(rec["lr"], rec["milestones"], rec["gamma"])
    step = build_train_step(model, opt, lr_fn, mesh, sync_bn=sync_bn)
    eval_step = build_eval_step(model, mesh)
    img_sh = batch_sharding(mesh, 4)
    lab_sh = batch_sharding(mesh, 1)

    def evaluate(st):
        accs, n = [], 0
        for i in range(0, len(v_imgs), batch):
            bi = ah._normalize(v_imgs[i:i + batch])
            bl = v_labs[i:i + batch]
            _, acc1, _ = eval_step(
                st, jax.device_put(bi, img_sh), jax.device_put(bl, lab_sh)
            )
            accs.append(float(acc1) * len(bl))
            n += len(bl)
        return sum(accs) / n

    t0 = time.perf_counter()
    for it in range(args.iters):
        g_img = jax.device_put(ah._normalize(np.asarray(imgs[it])), img_sh)
        g_lab = jax.device_put(labels[it], lab_sh)
        state, loss = step(state, g_img, g_lab)
        if (it + 1) % args.eval_every == 0:
            print(
                f"[{args.tag}] iter {it + 1}/{args.iters} "
                f"loss {float(loss):.6f}  "
                f"({time.perf_counter() - t0:.0f}s)", flush=True,
            )
    top1 = evaluate(state)

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(
        {"params": state.params, "batch_stats": state.batch_stats}
    ):
        h.update(np.asarray(jax.device_get(leaf)).tobytes())
    print(f"[{args.tag}] FINAL top1 {top1:.4f}  state_sha256 {h.hexdigest()}",
          flush=True)


if __name__ == "__main__":
    main()
