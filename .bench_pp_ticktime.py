"""CPU-mesh PP step-time comparison for the stage-gated embed/head change.

Shapes chosen so the head is a large share of a stage's per-tick FLOPs
(vocab >> embed, shallow blocks), mirroring the TransformerLM-pp.yml
regime the round-4 verdict called out.
"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
import time

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_tpu.engine.pp_steps import (
    build_pp_lm_train_step,
)
from pytorch_distributed_training_tpu.engine.steps import TrainState
from pytorch_distributed_training_tpu.models.transformer_lm import TransformerLM
from pytorch_distributed_training_tpu.optimizers import AdamW
from pytorch_distributed_training_tpu.parallel.pipeline import (
    make_pp_mesh,
    pp_stack_params,
    pp_state_shardings,
)

VOCAB, EMBED, DEPTH, HEADS, SEQ = 8192, 256, 8, 4, 128
BATCH, MICRO = 16, 4  # global batch; per data-shard 8, microbatch 2

mesh = make_pp_mesh(4)  # (data=2, stage=4)
lm = TransformerLM(vocab_size=VOCAB, max_len=SEQ, embed_dim=EMBED,
                   depth=DEPTH, num_heads=HEADS, dtype=jnp.float32)
rng = np.random.default_rng(0)
tokens = rng.integers(0, VOCAB, (BATCH, SEQ + 1)).astype(np.int32)
params = lm.init(jax.random.PRNGKey(0), jnp.asarray(tokens[:1, :SEQ]))["params"]
params = pp_stack_params(params, DEPTH)
opt = AdamW(lr=1e-4)
state = TrainState(params=params, batch_stats={}, opt_state=opt.init(params))
state = jax.device_put(state, pp_state_shardings(state, mesh))
inp = jnp.asarray(tokens[:, :-1])
lab = jnp.asarray(tokens[:, 1:])

import sys
for sched in (sys.argv[1:] or ["gpipe", "1f1b"]):
    step = build_pp_lm_train_step(
        lm, opt, lambda _: jnp.float32(1e-4), mesh, MICRO, schedule=sched,
        donate=False,
    )(state)
    st = state
    for _ in range(2):
        st, loss = step(st, inp, lab)
    float(loss)
    times = []
    for _ in range(4):
        t0 = time.perf_counter()
        for _ in range(3):
            st, loss = step(st, inp, lab)
        float(loss)
        times.append((time.perf_counter() - t0) / 3)
    print(f"{sched}: median step {np.median(times)*1e3:.1f} ms  "
          f"(min {min(times)*1e3:.1f})  loss {float(loss):.4f}")
