"""Within-session A/B of the flash backward variants at the LM bench
attention shape (B4 H16 S2048 D64, bf16, causal, fwd+bwd).  Throwaway
round-5 measurement helper; not part of the package."""
import json
import sys
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def timed(iters=40, windows=3):
    from pytorch_distributed_training_tpu.ops import flash_attention as fa
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        flash_attention,
    )

    fa._make.cache_clear()
    rng = np.random.default_rng(0)
    shape = (4, 2048, 16, 64)
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape, np.float32), jnp.bfloat16)
        for _ in range(3)
    )

    def f(q, k, v):
        o = flash_attention(q, k, v, causal=True)
        return (o.astype(jnp.float32) ** 2).mean()

    grad_fn = jax.value_and_grad(f, argnums=(0, 1, 2))

    @jax.jit
    def many(q, k, v):
        def body(_, q_c):
            _, (dq, dk, dv) = grad_fn(q_c, k, v)
            return q_c + jnp.bfloat16(1e-3) * dq + jnp.bfloat16(1e-6) * (dk + dv)

        return jnp.float32(jax.lax.fori_loop(0, iters, body, q)).sum()

    float(many(q, k, v))
    best = None
    for _ in range(windows):
        t0 = time.perf_counter()
        float(many(q, k, v))
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best, grad_fn(q, k, v)


variants = [
    ("r4-split-f32dots", {"PDT_FLASH_NO_FUSED_BWD": "1", "PDT_FLASH_F32_DOTS": "1"}, None),
    ("split-bf16dots", {"PDT_FLASH_NO_FUSED_BWD": "1"}, None),
    ("fused-bf16 512/1024", {}, (512, 1024)),
    ("fused-bf16 1024/512", {}, (1024, 512)),
    ("fused-bf16 512/512", {}, (512, 512)),
    ("fused-bf16 256/1024", {}, (256, 1024)),
]
results = {}
grads = {}
for name, env, tiles in variants:
    from pytorch_distributed_training_tpu.ops import flash_attention as fa

    for k2 in ("PDT_FLASH_NO_FUSED_BWD", "PDT_FLASH_F32_DOTS"):
        os.environ.pop(k2, None)
    os.environ.update(env)
    if tiles:
        fa._BLOCK_Q_FUSED, fa._BLOCK_K_FUSED = tiles
    try:
        dt, (loss, g) = timed()
    except Exception as e:  # noqa: BLE001 - sweep must survive a VMEM OOM
        print(json.dumps({"variant": name, "error": str(e)[:160]}), flush=True)
        continue
    results[name] = round(dt * 1e3, 3)
    grads[name] = (float(loss), g)
    print(json.dumps({"variant": name, "ms_per_op": results[name]}), flush=True)

if "r4-split-f32dots" not in grads:
    sys.exit("reference variant errored; no parity comparison possible")
ref_l, ref_g = grads["r4-split-f32dots"]
for name, (l, g) in grads.items():
    err = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(g, ref_g)
    )
    print(
        json.dumps(
            {"variant": name, "loss_abs_err_vs_r4": abs(l - ref_l),
             "grad_max_abs_err_vs_r4": err}
        ),
        flush=True,
    )
