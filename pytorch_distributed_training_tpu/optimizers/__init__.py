"""Optimizer factories with PyTorch-exact update semantics.

Re-provides the ``dl_lib.optimizers`` surface pinned by the reference at
train_distributed.py:30, :204-207: ``get_optimizer(cfg) -> class``, then
instantiated with the config minus its ``name`` key.  Names: ``SGD`` (used by
both reference configs) plus ``LARS`` for the large-batch pod recipe.

Accuracy parity lives or dies on update-rule fidelity (SURVEY.md §7 "hard
parts" #1), so ``SGD`` replicates ``torch.optim.SGD`` exactly:

  - **coupled** weight decay: ``d = g + wd * p`` folded into the gradient
    *before* the momentum update (NOT optax's decoupled
    ``add_decayed_weights``-after-momentum),
  - PyTorch momentum: ``buf = mu * buf + (1 - dampening) * d`` with the
    first-step special case ``buf = d`` (torch initializes the buffer to the
    first update, not to zero),
  - update ``p <- p - lr * (d + mu * buf)`` if nesterov else ``p - lr * buf``.

Design: optimizers are functional — ``init(params) -> state`` and
``update(grads, state, params, lr) -> (new_params, new_state)`` — and are
called *inside* the compiled train step, so the parameter update fuses into
the same XLA program as forward/backward/psum (the reference's separate
``optimizer.step()`` kernel launches, train_distributed.py:277, have no
analog: XLA fuses them away).  ``lr`` is passed per-call because the schedule
is evaluated on-device from the step counter (see ``schedulers``).
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "SGD",
    "LARS",
    "AdamW",
    "LAMB",
    "get_optimizer",
    "OPTIMIZERS",
    "SGDState",
    "AdamWState",
]


class SGDState(NamedTuple):
    momentum: Any  # pytree like params (zeros when momentum == 0)
    step: jnp.ndarray  # scalar int32, number of updates applied so far


class AdamWState(NamedTuple):
    mu: Any  # first-moment pytree like params
    nu: Any  # second-moment pytree like params
    step: jnp.ndarray  # scalar int32, number of updates applied so far


class _Out(NamedTuple):
    """Per-leaf update result bundle.

    A dedicated type (not a plain tuple) so the unzip's ``is_leaf`` can't
    mistake tuple/NamedTuple *container* nodes of a user's params pytree for
    update results — with a bare ``isinstance(t, tuple)`` predicate, params
    stored in a tuple would be silently unzipped into a corrupted tree.
    """

    param: Any
    aux1: Any
    aux2: Any = None
    aux3: Any = None


def _unzip(tree_of_out, n: int):
    """Split a pytree of ``_Out`` bundles into n parallel pytrees."""
    is_out = lambda t: isinstance(t, _Out)  # noqa: E731
    return tuple(
        jax.tree.map(lambda t: t[i], tree_of_out, is_leaf=is_out) for i in range(n)
    )


def _fused_map(fn, n_out: int, *trees):
    """``jax.tree.map(fn, *trees)`` + unzip, as ONE kernel per dtype group.

    The per-leaf map hands XLA one fusion root per parameter leaf, so the
    optimizer tail of a deep model pays one (tiny, launch-bound) kernel per
    leaf — ~300 launches for the flagship LM.  Here every leaf is raveled
    and concatenated into a single flat buffer per dtype signature, ``fn``
    runs ONCE over each buffer, and the results are split/reshaped back.

    ``fn`` must be elementwise over its array arguments (scalars broadcast
    fine): concatenation then commutes with the math, so the result is
    BITWISE identical to the per-leaf path (regression-tested in
    tests/test_profiling.py).  Reductions per leaf (e.g. LARS trust norms)
    would NOT commute — LARS therefore has no fused mode.

    Leaves are grouped by the dtype tuple across trees so mixed-precision
    states (bf16 params + f32 moments, or vice versa) never get silently
    cast by a shared buffer.
    """
    treedef = jax.tree.structure(trees[0])
    leaves_per_tree = [treedef.flatten_up_to(t) for t in trees]
    n_leaf = len(leaves_per_tree[0])
    if n_leaf == 0:
        out = jax.tree.map(fn, *trees)
        return _unzip(out, n_out)
    groups: Dict[Any, list] = {}
    for i in range(n_leaf):
        key = tuple(jnp.result_type(t[i]) for t in leaves_per_tree)
        groups.setdefault(key, []).append(i)
    out_leaves = [[None] * n_leaf for _ in range(n_out)]
    for idxs in groups.values():
        flats = [
            (
                jnp.concatenate([t[i].reshape(-1) for i in idxs])
                if len(idxs) > 1
                else t[idxs[0]].reshape(-1)
            )
            for t in leaves_per_tree
        ]
        res = fn(*flats)
        sizes = [leaves_per_tree[0][i].size for i in idxs]
        offsets = list(itertools.accumulate(sizes[:-1]))  # static split points
        for j in range(n_out):
            buf = res[j]
            parts = jnp.split(buf, offsets) if offsets else [buf]
            for i, part in zip(idxs, parts):
                out_leaves[j][i] = part.reshape(leaves_per_tree[0][i].shape)
    return tuple(jax.tree.unflatten(treedef, out_leaves[j]) for j in range(n_out))


def _apply_map(fused: bool, fn, n_out: int, *trees):
    """Route a per-leaf elementwise update through tree.map or ``_fused_map``."""
    if fused:
        return _fused_map(fn, n_out, *trees)
    return _unzip(jax.tree.map(fn, *trees), n_out)


class SGD:
    """``torch.optim.SGD``-semantics SGD (see module docstring).

    ``fused=True`` routes the (elementwise) update through ``_fused_map``:
    one kernel per dtype group instead of one per parameter leaf, bitwise
    identical results.  Config surface: ``training.optimizer.fused: true``.
    """

    def __init__(
        self,
        lr: float,
        momentum: float = 0.0,
        weight_decay: float = 0.0,
        dampening: float = 0.0,
        nesterov: bool = False,
        fused: bool = False,
    ):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires momentum > 0 and dampening = 0")
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.weight_decay = float(weight_decay)
        self.dampening = float(dampening)
        self.nesterov = bool(nesterov)
        self.fused = bool(fused)

    def init(self, params) -> SGDState:
        return SGDState(
            momentum=jax.tree.map(jnp.zeros_like, params),
            step=jnp.zeros((), dtype=jnp.int32),
        )

    def _one(self, lr, first):
        mu, wd, damp = self.momentum, self.weight_decay, self.dampening

        def one(g, p, buf):
            d = g + wd * p if wd != 0 else g
            if mu != 0:
                # torch: buffer starts as the first d, not as mu*0 + (1-damp)*d.
                new_buf = jnp.where(first, d, mu * buf + (1.0 - damp) * d)
                step_dir = d + mu * new_buf if self.nesterov else new_buf
            else:
                new_buf = buf
                step_dir = d
            return _Out(p - lr * step_dir, new_buf)

        return one

    def update(self, grads, state: SGDState, params, lr=None):
        if lr is None:
            lr = self.lr
        one = self._one(lr, state.step == 0)
        new_params, new_bufs = _apply_map(
            self.fused, one, 2, grads, params, state.momentum
        )
        return new_params, SGDState(momentum=new_bufs, step=state.step + 1)

    def update_with_ema(self, grads, state: SGDState, params, lr, ema, decay):
        """Parameter update + EMA fold in the same fused pass.

        ``new_ema = decay * ema + (1 - decay) * new_param`` — identical math
        to the post-hoc tree.map in engine/steps.py, but emitted inside the
        same kernel(s) as the update so the EMA stops paying its own
        one-kernel-per-leaf tail.
        """
        one = self._one(lr, state.step == 0)
        d = decay

        def one_ema(g, p, buf, e):
            out = one(g, p, buf)
            return _Out(out.param, out.aux1, d * e + (1.0 - d) * out.param)

        new_params, new_bufs, new_ema = _apply_map(
            self.fused, one_ema, 3, grads, params, state.momentum, ema
        )
        return new_params, SGDState(momentum=new_bufs, step=state.step + 1), new_ema


def _is_excluded(param) -> bool:
    """True for params LARS should not adapt: biases + norm scales/offsets.

    Matches the standard large-batch recipe (LARS paper / MLPerf ResNet):
    normalization parameters and biases get neither weight decay nor the
    trust-ratio scaling.  Detection is by parameter *role*, not name: every
    such parameter is rank-0/1 (bias vectors, BatchNorm/LayerNorm scale and
    offset), while every matmul/conv/embedding weight is rank>=2.  This makes
    the rule model-family-agnostic — it is exactly right for the ResNet tree
    AND for transformer trees, where name-matching on "bn" would silently
    give LayerNorm scales (``ln1``/``ln2``) trust-ratio updates.
    """
    return jnp.ndim(param) <= 1


class LARS:
    """Layer-wise Adaptive Rate Scaling (You et al., 2017) with momentum.

    For each non-excluded param: trust = eta * ||p|| / (||g|| + wd * ||p||),
    then PyTorch-style momentum on ``trust * (g + wd * p)``.  Excluded params
    (biases, norm scale/offset) fall back to plain momentum SGD without WD.
    Used by the large-batch (8k, LARS) pod config from BASELINE.json.
    """

    def __init__(
        self,
        lr: float,
        momentum: float = 0.9,
        weight_decay: float = 0.0,
        eta: float = 0.001,
        eps: float = 1e-9,
    ):
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.weight_decay = float(weight_decay)
        self.eta = float(eta)
        self.eps = float(eps)

    def init(self, params) -> SGDState:
        return SGDState(
            momentum=jax.tree.map(jnp.zeros_like, params),
            step=jnp.zeros((), dtype=jnp.int32),
        )

    def update(self, grads, state: SGDState, params, lr=None):
        if lr is None:
            lr = self.lr
        mu, wd, eta, eps = self.momentum, self.weight_decay, self.eta, self.eps

        def one(g, p, buf):
            if _is_excluded(p):
                d = g
            else:
                p_norm = jnp.linalg.norm(p.reshape(-1))
                g_norm = jnp.linalg.norm(g.reshape(-1))
                trust = jnp.where(
                    (p_norm > 0) & (g_norm > 0),
                    eta * p_norm / (g_norm + wd * p_norm + eps),
                    1.0,
                )
                d = trust * (g + wd * p)
            new_buf = mu * buf + d
            return _Out(p - lr * new_buf, new_buf)

        flat = jax.tree.map(one, grads, params, state.momentum)
        new_params, new_bufs = _unzip(flat, 2)
        return new_params, SGDState(momentum=new_bufs, step=state.step + 1)


class AdamW:
    """``torch.optim.AdamW``-semantics AdamW (decoupled weight decay).

    Exact torch update order (torch/optim/adamw.py single-tensor path):
      1. ``p <- p * (1 - lr * wd)``          (decoupled decay, BEFORE the step)
      2. ``mu <- b1*mu + (1-b1)*g``; ``nu <- b2*nu + (1-b2)*g^2``
      3. bias correction ``bc1 = 1-b1^t``, ``bc2 = 1-b2^t`` (t counts from 1)
      4. ``p <- p - (lr/bc1) * mu / (sqrt(nu)/sqrt(bc2) + eps)``
    Note torch divides by ``sqrt(nu/bc2) + eps`` with eps OUTSIDE the sqrt
    and applied to the bias-corrected denom — replicated exactly (the optax
    ``adamw`` eps placement differs).  The default LM optimizer beyond the
    reference's SGD-only surface (transformers want Adam-family updates).

    ``exclude_norm_bias=True`` enables the standard transformer recipe of
    applying NO weight decay to biases and norm scales/offsets (detected by
    the same rank<=1 rule as LARS, see ``_is_excluded``): excluded leaves
    skip step 1 entirely, everything else is unchanged.  With the default
    ``False`` the update is bitwise identical to before the flag existed.
    Config surface: ``training.optimizer.exclude_norm_bias: true``.
    """

    def __init__(
        self,
        lr: float,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 1e-2,
        fused: bool = False,
        exclude_norm_bias: bool = False,
    ):
        self.lr = float(lr)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.fused = bool(fused)
        self.exclude_norm_bias = bool(exclude_norm_bias)

    def init(self, params) -> AdamWState:
        return AdamWState(
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params),
            step=jnp.zeros((), dtype=jnp.int32),
        )

    def _one(self, lr, step, wd=None):
        b1, b2, eps = self.b1, self.b2, self.eps
        if wd is None:
            wd = self.weight_decay
        t = (step + 1).astype(jnp.float32)
        bc1 = 1.0 - b1**t
        bc2 = 1.0 - b2**t

        def one(g, p, mu, nu):
            p = p * (1.0 - lr * wd)
            new_mu = b1 * mu + (1.0 - b1) * g
            new_nu = b2 * nu + (1.0 - b2) * jnp.square(g)
            denom = jnp.sqrt(new_nu) / jnp.sqrt(bc2) + eps
            return _Out(p - (lr / bc1) * new_mu / denom, new_mu, new_nu)

        return one

    def _pre_step(self, lr, step, params):
        """Resolve the exclude_norm_bias split: (params, per-leaf fn).

        When the flag is on, decoupled decay is applied here as a per-leaf
        pre-pass over the non-excluded leaves only (exclusion is a static
        per-leaf property, so it cannot live inside a fused elementwise fn
        that concatenates leaves), and ``_one`` then runs with wd = 0 for
        every leaf.  For non-excluded leaves the composition is bitwise the
        default path: ``p * (1 - lr*wd)`` then wd-free adam.
        """
        if self.exclude_norm_bias and self.weight_decay != 0.0:
            wd = self.weight_decay
            params = jax.tree.map(
                lambda p: p if _is_excluded(p) else p * (1.0 - lr * wd), params
            )
            return params, self._one(lr, step, wd=0.0)
        return params, self._one(lr, step)

    def update(self, grads, state: AdamWState, params, lr=None):
        if lr is None:
            lr = self.lr
        params, one = self._pre_step(lr, state.step, params)
        new_params, new_mu, new_nu = _apply_map(
            self.fused, one, 3, grads, params, state.mu, state.nu
        )
        return new_params, AdamWState(mu=new_mu, nu=new_nu, step=state.step + 1)

    def update_with_ema(self, grads, state: AdamWState, params, lr, ema, decay):
        """Parameter update + EMA fold in one pass (see ``SGD.update_with_ema``)."""
        params, one = self._pre_step(lr, state.step, params)
        d = decay

        def one_ema(g, p, mu, nu, e):
            out = one(g, p, mu, nu)
            return _Out(out.param, out.aux1, out.aux2, d * e + (1.0 - d) * out.param)

        new_params, new_mu, new_nu, new_ema = _apply_map(
            self.fused, one_ema, 4, grads, params, state.mu, state.nu, ema
        )
        return (
            new_params,
            AdamWState(mu=new_mu, nu=new_nu, step=state.step + 1),
            new_ema,
        )


class LAMB:
    """Layer-wise Adaptive Moments (You et al., 2019) — LARS for Adam.

    Completes the large-batch recipe pair: LARS covers the SGD/ResNet pod
    configs, LAMB is its Adam-family counterpart for large-batch transformer
    pretraining (the paper's BERT-in-76-minutes recipe).  Per non-excluded
    param (rank >= 2, see ``_is_excluded``):

      1. adam moments ``mu <- b1*mu + (1-b1)*g``, ``nu <- b2*nu + (1-b2)*g^2``
      2. bias-corrected update ``u = (mu/bc1) / (sqrt(nu/bc2) + eps)``
         (eps INSIDE the ratio, per the paper's Algorithm 2 — this is NOT
         the torch-AdamW eps placement)
      3. decoupled decay folded into the direction: ``u <- u + wd * p``
      4. trust ratio ``r = ||p|| / ||u||`` where both norms > 0 else 1
      5. ``p <- p - lr * r * u``

    Excluded params (biases, norm scale/offset) take the same step with
    wd = 0 and r = 1.  Per-leaf norms are reductions, so — like LARS — LAMB
    has no fused mode (concatenation would not commute with step 4).
    Reuses ``AdamWState``: the moment pytrees and step counter are identical.
    """

    def __init__(
        self,
        lr: float,
        betas=(0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.0,
    ):
        self.lr = float(lr)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)

    def init(self, params) -> AdamWState:
        return AdamWState(
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params),
            step=jnp.zeros((), dtype=jnp.int32),
        )

    def update(self, grads, state: AdamWState, params, lr=None):
        if lr is None:
            lr = self.lr
        b1, b2, eps, wd = self.b1, self.b2, self.eps, self.weight_decay
        t = (state.step + 1).astype(jnp.float32)
        bc1 = 1.0 - b1**t
        bc2 = 1.0 - b2**t

        def one(g, p, mu, nu):
            new_mu = b1 * mu + (1.0 - b1) * g
            new_nu = b2 * nu + (1.0 - b2) * jnp.square(g)
            u = (new_mu / bc1) / (jnp.sqrt(new_nu / bc2) + eps)
            if _is_excluded(p):
                return _Out(p - lr * u, new_mu, new_nu)
            u = u + wd * p
            p_norm = jnp.linalg.norm(p.reshape(-1))
            u_norm = jnp.linalg.norm(u.reshape(-1))
            trust = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm, 1.0)
            return _Out(p - lr * trust * u, new_mu, new_nu)

        flat = jax.tree.map(one, grads, params, state.mu, state.nu)
        new_params, new_mu, new_nu = _unzip(flat, 3)
        return new_params, AdamWState(mu=new_mu, nu=new_nu, step=state.step + 1)


OPTIMIZERS = {
    "SGD": SGD,
    "LARS": LARS,
    "AdamW": AdamW,
    "LAMB": LAMB,
}


def get_optimizer(cfg: Dict[str, Any]):
    """Return the optimizer *class* for ``cfg['name']`` (reference: :204)."""
    name = cfg["name"]
    if name not in OPTIMIZERS:
        raise KeyError(f"unknown optimizer '{name}' (have: {sorted(OPTIMIZERS)})")
    return OPTIMIZERS[name]
