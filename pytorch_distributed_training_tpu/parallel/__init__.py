"""Device mesh + multi-host bootstrap + sharding helpers.

The TPU-native replacement for the reference's distributed layer
(``torch.distributed`` + NCCL + TCPStore, train_distributed.py:149-154;
SURVEY.md §2.3, §5.8): process-group init becomes
``jax.distributed.initialize`` over DCN (coordinator = the reference's
``--dist-url``); NCCL collectives become XLA collectives over ICI emitted by
the compiled program; DDP/SyncBN wrappers disappear into in-graph
``psum``/``pmean``.
"""
from .distributed import initialize_distributed, parse_dist_url
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    adapt_spec,
    batch_pspec,
    batch_sharding,
    make_mesh,
    make_3d_mesh,
    make_sp_mesh,
    mesh_axis_sizes,
    replicated_sharding,
)
from .pipeline import (
    STAGE_AXIS,
    make_pp_mesh,
    pp_param_specs,
    pp_stack_params,
    pp_state_shardings,
    pp_unstack_params,
)
from .sequence import SEQUENCE_AXIS, ring_attention, ulysses_attention
from .tensor import lm_tp_param_specs, lm_tp_shardings, tp_state_shardings

__all__ = [
    "initialize_distributed",
    "parse_dist_url",
    "make_mesh",
    "make_3d_mesh",
    "make_sp_mesh",
    "make_pp_mesh",
    "batch_sharding",
    "batch_pspec",
    "replicated_sharding",
    "mesh_axis_sizes",
    "adapt_spec",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQUENCE_AXIS",
    "STAGE_AXIS",
    "ring_attention",
    "ulysses_attention",
    "pp_stack_params",
    "pp_unstack_params",
    "pp_param_specs",
    "pp_state_shardings",
]
