"""Multi-host bootstrap.

Replaces ``dist.init_process_group(backend, init_method, world_size, rank)``
(reference: train_distributed.py:149-154) with the JAX coordination service:
the reference's TCPStore rendezvous URL (``--dist-url tcp://host:port``,
:42) maps directly onto the coordinator address of
``jax.distributed.initialize``; ``--num-nodes``/``--rank`` map onto
``num_processes``/``process_id`` (SURVEY.md §5.8).

Backend-name mapping: the reference defaults ``--dist-backend nccl``; the
TPU runtime accepts ``tpu`` / ``xla`` (and treats ``nccl`` as a compat alias
with a warning, so reference launch scripts keep working unmodified).
"""
from __future__ import annotations

import logging
from typing import Optional, Tuple
from urllib.parse import urlparse

import jax

__all__ = ["parse_dist_url", "initialize_distributed"]

_ACCEPTED_BACKENDS = {"tpu", "xla", "nccl", "gloo"}


def parse_dist_url(dist_url: str) -> Tuple[str, int]:
    """``tcp://host:port`` -> ``(host, port)`` (reference URL scheme, :42)."""
    parsed = urlparse(dist_url)
    if parsed.scheme not in ("tcp", ""):
        raise ValueError(f"unsupported dist-url scheme: {dist_url!r}")
    host = parsed.hostname or "127.0.0.1"
    if parsed.port is None:
        raise ValueError(f"dist-url must include a port: {dist_url!r}")
    return host, parsed.port


def initialize_distributed(
    dist_url: str,
    num_nodes: int,
    rank: int,
    backend: str = "tpu",
    logger: Optional[logging.Logger] = None,
) -> None:
    """Bring up the multi-host runtime (one controller process per host).

    No-op for single-host runs — ``jax.devices()`` already spans the local
    chips, and in-process SPMD needs no coordinator.  The reference's
    per-GPU ``mp.spawn`` topology (:116-135) is deliberately not replicated
    (SURVEY.md §7 deviations): its ``--multiprocessing`` flag becomes a
    compat no-op at the CLI layer.
    """
    log = logger or logging.getLogger(__name__)
    backend = (backend or "tpu").lower()
    if backend not in _ACCEPTED_BACKENDS:
        raise ValueError(
            f"unknown --dist-backend {backend!r} (accepted: {sorted(_ACCEPTED_BACKENDS)})"
        )
    if backend in ("nccl", "gloo"):
        log.warning(
            "--dist-backend %s is a GPU-era alias; using the XLA/TPU runtime", backend
        )
    if num_nodes is None or num_nodes <= 1:
        return
    host, port = parse_dist_url(dist_url)
    jax.distributed.initialize(
        coordinator_address=f"{host}:{port}",
        num_processes=num_nodes,
        process_id=rank,
    )
    log.info(
        "jax.distributed initialized: process %d/%d, %d global devices",
        rank,
        num_nodes,
        jax.device_count(),
    )
