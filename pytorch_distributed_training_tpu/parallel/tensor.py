"""Tensor parallelism: GSPMD sharding rules for the transformer family.

The third parallelism axis (after data and sequence), done the idiomatic
XLA way — NOT hand-written collectives: parameters get ``NamedSharding``
annotations over the mesh ``model`` axis and the XLA SPMD partitioner
derives the Megatron pattern itself (column-parallel QKV/fc1, head-local
attention, row-parallel proj/fc2 with an automatic partial-sum all-reduce).
The reference has no model sharding at all (whole-model replication,
train_distributed.py:189,198; SURVEY.md §2.4 keeps the axis open).

Rules (kernel shapes are [in, out]):

  ===============================  ======================  =================
  parameter                        spec                    role
  ===============================  ======================  =================
  ``attn/qkv``   kernel / bias     P(None, model) / P(m)   column (heads)
  ``attn/proj``  kernel            P(model, None)          row (+allreduce)
  ``mlp/fc1``    kernel / bias     P(None, model) / P(m)   column
  ``mlp/fc2``    kernel            P(model, None)          row (+allreduce)
  everything else                  P()                     replicated
  ===============================  ======================  =================

The QKV column split lands on whole-head boundaries because the attention
op lays its projection out heads-major (ops/attention.py), so the split
propagates through the reshape without resharding.
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import MODEL_AXIS

__all__ = [
    "param_mirror_fields",
    "lm_tp_param_specs",
    "lm_tp_shardings",
    "tp_state_shardings",
    "zero_grad_shardings",
    "mirror_opt_fields",
]


def param_mirror_fields(opt_state, params):
    """Names of opt-state fields whose pytree structure matches ``params``
    (moment trees — SGD momentum, AdamW mu/nu).  THE single matching rule:
    :func:`mirror_opt_fields` and every caller that needs "a
    params-structured field" (e.g. engine/pp_steps' ZeRO-2 grad pinning)
    share it so the rule cannot drift."""
    params_struct = jax.tree.structure(params)
    return [
        name
        for name in opt_state._fields
        if jax.tree.structure(getattr(opt_state, name)) == params_struct
    ]


def mirror_opt_fields(opt_state, params, param_tree, rep):
    """Rebuild an optimizer-state NamedTuple with per-field value trees:
    fields whose pytree structure matches ``params`` (moment trees — SGD
    momentum, AdamW mu/nu, ...) take ``param_tree`` (their parameter's
    spec/sharding), anything else (step counters) maps every leaf to
    ``rep``.  Shared by the TP/ZeRO (:func:`tp_state_shardings`), pipeline
    (``parallel.pipeline.pp_state_shardings``), and pipeline-step
    (``engine.pp_steps``) sharding helpers so the structure-matching rule
    cannot drift between them."""
    mirrors = set(param_mirror_fields(opt_state, params))
    fields = {}
    for name in opt_state._fields:
        if name in mirrors:
            fields[name] = param_tree
        else:
            fields[name] = jax.tree.map(
                lambda _: rep, getattr(opt_state, name)
            )
    return type(opt_state)(**fields)


def zero_shard_moment(sh: NamedSharding, leaf, mesh: Mesh) -> NamedSharding:
    """ZeRO-1 moment sharding rule: ADDITIONALLY shard the first FREE
    dimension (spec None + divisible by the data-axis size) over ``data``.
    For column-parallel kernels that is dim 0; for row-parallel kernels
    (``P(model, None)``) dim 0 carries the model axis, so dim 1 takes the
    data sharding — without this, ~40% of per-block moment memory would
    silently stay unsharded under ZeRO + TP.  Shared by the GSPMD TP path
    (:func:`tp_state_shardings`) and the pipeline path
    (``parallel.pipeline.pp_state_shardings``) so the rule cannot drift."""
    from .mesh import DATA_AXIS

    n_data = mesh.shape[DATA_AXIS]
    spec = list(sh.spec) + [None] * (leaf.ndim - len(sh.spec))
    for d in range(leaf.ndim):
        if spec[d] is None and leaf.shape[d] % n_data == 0:
            spec[d] = DATA_AXIS
            return NamedSharding(mesh, P(*spec))
    return sh


def zero_grad_shardings(grads, mesh: Mesh):
    """ZeRO-2 gradient sharding: the moment rule applied to gradient buffers.

    Gradients mirror their parameter's shape, so the same
    :func:`zero_shard_moment` rule (first free dim over ``data``) gives each
    device a 1/N slice.  Used as a ``with_sharding_constraint`` inside the
    GSPMD train step so XLA reduce-scatters gradients as they are produced —
    the replicated full-gradient tree (and, under ``grad_accumulation``, the
    accumulator carried across micro-batches) never materializes per device.
    Works on tracers: only ``shape``/``ndim`` are read.
    """
    param_sh = lm_tp_shardings(grads, mesh)
    return jax.tree.map(
        lambda sh, leaf: zero_shard_moment(sh, leaf, mesh), param_sh, grads
    )


def _spec_for(path) -> P:
    keys = [str(getattr(k, "key", getattr(k, "name", k))) for k in path]
    leaf = keys[-1] if keys else ""
    if "attn" in keys:
        if "qkv" in keys:
            return P(None, MODEL_AXIS) if leaf == "kernel" else P(MODEL_AXIS)
        if "proj" in keys and leaf == "kernel":
            return P(MODEL_AXIS, None)
    if "mlp" in keys:
        if "fc1" in keys:
            return P(None, MODEL_AXIS) if leaf == "kernel" else P(MODEL_AXIS)
        if "fc2" in keys and leaf == "kernel":
            return P(MODEL_AXIS, None)
    if "moe" in keys:
        # expert parallelism: stacked [E, ...] expert weights (ops/moe.py)
        # shard their expert dim over the model axis; the partitioner
        # inserts the token all-to-alls around the expert einsums.  The
        # router stays replicated (every device routes its own tokens).
        if leaf in ("wi", "wo", "bi", "bo"):
            return P(MODEL_AXIS)
    return P()


def lm_tp_param_specs(params):
    """PartitionSpec pytree for a transformer params tree (rules above)."""
    return jax.tree_util.tree_map_with_path(lambda p, _: _spec_for(p), params)


def lm_tp_shardings(params, mesh: Mesh):
    """NamedSharding pytree for ``params`` on ``mesh``."""
    return jax.tree_util.tree_map_with_path(
        lambda p, _: NamedSharding(mesh, _spec_for(p)), params
    )


def tp_state_shardings(state, mesh: Mesh, zero: int = 0):
    """Shardings for a ``TrainState``: per-parameter optimizer moments
    (SGD momentum, AdamW mu/nu, ...) mirror their parameter's sharding.

    Generic over the optimizer: any opt_state NamedTuple field whose pytree
    structure matches ``params`` is treated as a parameter mirror; scalar
    fields (step counters) stay replicated.

    ``zero`` (config ``training.zero``, stages cumulative):
      1 — moment tensors ADDITIONALLY sharded over the ``data`` axis on
          their first free dimension (when divisible): per-device optimizer
          memory / data-axis size.  The partitioner reduce-scatters grads
          into the sharded update and all-gathers fresh params.
      2 — gradient buffers pinned to the same layout inside the step
          (``zero_grad_shardings`` + ``with_sharding_constraint``).
      3 — PARAMETERS live in the sharded layout too (FSDP semantics):
          per-device parameter memory / data-axis size; the partitioner
          all-gathers each weight at its use sites in forward/backward and
          the whole update runs sharded with no gather at all.  The update
          math is identical in every stage.
    """
    from ..engine.steps import TrainState  # avoid import cycle at module load
    from .mesh import DATA_AXIS

    assert isinstance(state, TrainState)
    param_sh = lm_tp_shardings(state.params, mesh)
    rep = NamedSharding(mesh, P())
    n_data = mesh.shape[DATA_AXIS]
    if int(zero) and n_data > 1:
        moment_sh = jax.tree.map(
            lambda sh, leaf: zero_shard_moment(sh, leaf, mesh),
            param_sh,
            state.params,
        )
        if int(zero) >= 3:
            param_sh = moment_sh  # params adopt the scattered layout (FSDP)
    else:
        moment_sh = param_sh
    opt_sh = mirror_opt_fields(state.opt_state, state.params, moment_sh, rep)
    bs_sh = jax.tree.map(lambda _: rep, state.batch_stats)
    return TrainState(params=param_sh, batch_stats=bs_sh, opt_state=opt_sh)
