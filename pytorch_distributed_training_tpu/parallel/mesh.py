"""Device mesh construction + sharding helpers.

Design stance (SURVEY.md §7): a 2-D ``(data, model)`` mesh with the model
axis trivial (size 1) for the reference's pure-DP workload — DP is the only
strategy the reference implements (SURVEY.md §2.4) but the mesh deliberately
keeps a model axis open so tensor/pipeline sharding can land without
reshaping the core (§2.4 "mesh design should leave a model axis open").
``mesh_utils.create_device_mesh`` orders devices so the data axis rides ICI
within a slice.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "make_sp_mesh",
    "make_3d_mesh",
    "batch_sharding",
    "batch_pspec",
    "replicated_sharding",
    "mesh_axis_sizes",
    "adapt_spec",
]

DATA_AXIS = "data"
MODEL_AXIS = "model"


def _make_nd_mesh(
    inner_sizes: Sequence[int],
    inner_names: Sequence[str],
    devices: Optional[Sequence],
) -> Mesh:
    """Shared builder: data axis outermost + the given inner axes.

    ``mesh_utils.create_device_mesh`` orders the full device set for ICI
    adjacency; explicit device subsets fall back to a plain reshape.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    inner = 1
    for s in inner_sizes:
        inner *= s
    if inner == 0 or n % inner != 0:
        raise ValueError(
            f"{n} devices not divisible by "
            + " x ".join(f"{nm} ({s})" for nm, s in zip(inner_names, inner_sizes))
        )
    shape = (n // inner, *inner_sizes)
    if n == jax.device_count() and list(devices) == jax.devices():
        dev_array = mesh_utils.create_device_mesh(shape)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, (DATA_AXIS, *inner_names))


def make_mesh(devices: Optional[Sequence] = None, model_parallelism: int = 1) -> Mesh:
    """Build the global ``(data, model)`` mesh over all addressable processes.

    Args:
      devices: explicit device list (default: all of ``jax.devices()``, which
        spans every host after ``jax.distributed.initialize``).
      model_parallelism: size of the model axis (1 = pure DP, the reference's
        only strategy).
    """
    return _make_nd_mesh((model_parallelism,), (MODEL_AXIS,), devices)


def make_sp_mesh(
    sequence_parallelism: int, devices: Optional[Sequence] = None
) -> Mesh:
    """Build a 2-D ``(data, sequence)`` mesh for long-context training.

    The sequence axis carries the ring-attention K/V rotation
    (:mod:`.sequence`); ``mesh_utils`` ordering keeps ring neighbors
    ICI-adjacent so the per-step ``ppermute`` is a nearest-neighbor DMA.
    """
    from .sequence import SEQUENCE_AXIS

    return _make_nd_mesh((sequence_parallelism,), (SEQUENCE_AXIS,), devices)


def make_3d_mesh(
    sequence_parallelism: int,
    model_parallelism: int,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """3-D ``(data, sequence, model)`` mesh — DP x SP x TP composition.

    Axis order keeps the model (TP) axis innermost: Megatron's per-layer
    all-reduces are the highest-frequency collectives, so they get the
    tightest ICI neighborhoods from ``mesh_utils`` ordering; sequence
    (context) next; data outermost (lowest-frequency gradient reduction,
    free to cross DCN at pod scale).  Any axis may be size 1.
    """
    from .sequence import SEQUENCE_AXIS

    return _make_nd_mesh(
        (sequence_parallelism, model_parallelism),
        (SEQUENCE_AXIS, MODEL_AXIS),
        devices,
    )


def batch_pspec(ndim: int) -> P:
    """PartitionSpec sharding the leading (batch) dim over the data axis."""
    return P(DATA_AXIS, *([None] * (ndim - 1)))


def batch_sharding(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """NamedSharding for an ``[batch, ...]`` array (NHWC images: ndim=4)."""
    return NamedSharding(mesh, batch_pspec(ndim))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params / optimizer state in pure DP)."""
    return NamedSharding(mesh, P())


def mesh_axis_sizes(mesh: Mesh) -> dict:
    """``{axis_name: size}`` — the JSON-serializable mesh description the
    elastic checkpoint metadata records, so a restore under a different
    topology can log/validate exactly what reshape it is performing."""
    return {str(name): int(size) for name, size in mesh.shape.items()}


def adapt_spec(spec, mesh: Mesh) -> P:
    """Re-derive a saved PartitionSpec against a *target* mesh.

    ``spec`` is the saved leaf's partition spec as recorded in checkpoint
    metadata (a sequence of axis-name / axis-name-tuple / None entries).
    Axes the target mesh still has keep their placement; axes that
    disappeared with the reshape (e.g. a stage axis on a run restarted
    without pipeline parallelism) drop to replication on that dim —
    the elastic-restore rule: the *target* topology's layout wins, and a
    vanished mesh axis can only mean "this dim is no longer sharded".
    """
    names = set(mesh.axis_names)

    def _one(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    return P(*(_one(e) for e in tuple(spec)))
