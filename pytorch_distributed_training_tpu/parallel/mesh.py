"""Device mesh construction + sharding helpers.

Design stance (SURVEY.md §7): a 2-D ``(data, model)`` mesh with the model
axis trivial (size 1) for the reference's pure-DP workload — DP is the only
strategy the reference implements (SURVEY.md §2.4) but the mesh deliberately
keeps a model axis open so tensor/pipeline sharding can land without
reshaping the core (§2.4 "mesh design should leave a model axis open").
``mesh_utils.create_device_mesh`` orders devices so the data axis rides ICI
within a slice.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "batch_sharding",
    "batch_pspec",
    "replicated_sharding",
]

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(devices: Optional[Sequence] = None, model_parallelism: int = 1) -> Mesh:
    """Build the global ``(data, model)`` mesh over all addressable processes.

    Args:
      devices: explicit device list (default: all of ``jax.devices()``, which
        spans every host after ``jax.distributed.initialize``).
      model_parallelism: size of the model axis (1 = pure DP, the reference's
        only strategy).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % model_parallelism != 0:
        raise ValueError(
            f"{n} devices not divisible by model_parallelism={model_parallelism}"
        )
    shape = (n // model_parallelism, model_parallelism)
    if len(devices) == jax.device_count() and devices == jax.devices():
        dev_array = mesh_utils.create_device_mesh(shape)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def batch_pspec(ndim: int) -> P:
    """PartitionSpec sharding the leading (batch) dim over the data axis."""
    return P(DATA_AXIS, *([None] * (ndim - 1)))


def batch_sharding(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """NamedSharding for an ``[batch, ...]`` array (NHWC images: ndim=4)."""
    return NamedSharding(mesh, batch_pspec(ndim))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params / optimizer state in pure DP)."""
    return NamedSharding(mesh, P())
