"""Sequence / context parallelism: ring attention + Ulysses all-to-all.

The reference has no sequence axis anywhere (image classification,
SURVEY.md §5.7), but the framework treats long-context as first-class: both
standard sequence-parallel attention strategies are provided as pure SPMD
collectives usable inside ``shard_map`` over a mesh ``sequence`` axis:

  - :func:`ring_attention` — blockwise (flash-style) attention with K/V
    blocks rotating around the device ring via ``lax.ppermute``.  Each of
    the N ring steps overlaps the neighbor exchange with the local
    QK^T/softmax/PV block work; memory per device stays O(S_local), so the
    attainable context length scales linearly with the ring size.  This is
    the Ring Attention construction (Liu et al., 2023) on XLA collectives:
    the ``ppermute`` lowers to ICI neighbor DMA on TPU.
  - :func:`ulysses_attention` — DeepSpeed-Ulysses-style all-to-all: resharding
    [B, S/n, H, D] -> [B, S, H/n, D] with ``lax.all_to_all``, local full
    attention over heads, inverse all-to-all back to sequence sharding.
    Cheaper at moderate S (two all-to-alls vs N-1 permutes) but caps
    parallelism at the head count.

Numerics: accumulation in float32 with the online-softmax recurrence
(max-shifted), output cast back to the query dtype.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["ring_attention", "ulysses_attention", "SEQUENCE_AXIS"]

SEQUENCE_AXIS = "sequence"

from ..utils.vma import mark_varying, varying_axes_of

_NEG_INF = float("-inf")


def _block_attn(q, k, v, scale, q_off, k_off, causal, m, l, o):
    """One online-softmax accumulation step against a single K/V block.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]; m, l: [B, H, Sq] f32 running
    max / normalizer; o: [B, Sq, H, D] f32 unnormalized output accumulator.
    ``q_off``/``k_off`` are the global positions of the blocks' first tokens
    (for the causal mask).
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        q_pos = q_off + jnp.arange(q.shape[1])
        k_pos = k_off + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # A fully-masked row keeps m_new == -inf; exp(-inf - -inf) is NaN, so
    # gate both correction factors on finiteness (the row contributes 0).
    finite = jnp.isfinite(m_new)
    alpha = jnp.where(finite, jnp.exp(m - m_new), 0.0)  # [B, H, Sq]
    p = jnp.where(finite[..., None], jnp.exp(s - m_new[..., None]), 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_flash_ok(q) -> bool:
    """Flash-kernel eligibility for the ring inner blocks (same gate as
    ``ops.attention._use_flash`` minus the shard_map check — ring attention
    is by contract inside shard_map; the shape/VMEM rule is the shared
    :func:`..ops.flash_attention.flash_shapes_ok`)."""
    from ..ops.flash_attention import flash_enabled, flash_shapes_ok

    if not flash_enabled():
        return False
    b, s_local, h, d = q.shape
    return flash_shapes_ok(s_local, d)


def _ring_attention_flash(
    q, k, v, axis_name, causal, scale, interpret=False
):
    """Ring attention with the Pallas flash kernel as the per-step block
    attention — the Ring Attention paper's actual construction (blockwise
    flash inner, ppermute outer).  Each ring step is one of three static
    cases by global block position: strictly-past K/V blocks get full
    (unmasked) flash, the diagonal block causal flash, future blocks a
    masked no-op; partial results combine with the logsumexp rule
    ``o = w_acc*o_acc + w_b*o_b, w = exp(lse - logaddexp(...))``, which is
    exactly differentiable because :func:`..ops.flash_attention.
    flash_attention_lse`'s VJP handles lse cotangents."""
    from ..ops.flash_attention import flash_attention_lse

    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    perm = [(j, (j - 1) % n) for j in range(n)]
    axes = varying_axes_of(q, (axis_name,))

    def full_fn(kc, vc):
        return flash_attention_lse(
            q, kc, vc, causal=False, sm_scale=scale, interpret=interpret
        )

    def causal_fn(kc, vc):
        return flash_attention_lse(
            q, kc, vc, causal=True, sm_scale=scale, interpret=interpret
        )

    def masked_fn(kc, vc):
        del kc, vc
        # f32 like the flash branches' out_f32 outputs (switch branch types
        # must match; the combine accumulates in f32 across ring steps)
        return mark_varying(
            (
                jnp.zeros(q.shape, jnp.float32),
                jnp.full((b, s_local, h), _NEG_INF, jnp.float32),
            ),
            axes,
        )

    def step(i, carry):
        o_acc, lse_acc, k_cur, v_cur = carry
        src = (idx + i) % n
        if causal:
            branch = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
            o_b, lse_b = jax.lax.switch(
                branch, [full_fn, causal_fn, masked_fn], k_cur, v_cur
            )
        else:
            o_b, lse_b = full_fn(k_cur, v_cur)
        # combine: step 0 is always the (finite-everywhere) diagonal block,
        # so lse_acc is finite from then on and no -inf - -inf NaN can form
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        w_acc = jnp.exp(lse_acc - lse_new)[..., None]
        w_b = jnp.exp(lse_b - lse_new)[..., None]
        o_new = w_acc * o_acc + w_b * o_b  # all f32 (out_f32 block outputs)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o_new, lse_new, k_nxt, v_nxt

    o0, lse0 = mark_varying(
        (
            jnp.zeros((b, s_local, h, d), jnp.float32),
            jnp.full((b, s_local, h), _NEG_INF, jnp.float32),
        ),
        axes,
    )
    o, _, _, _ = jax.lax.fori_loop(0, n, step, (o0, lse0, k, v))
    return o.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = SEQUENCE_AXIS,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded across a device ring.

    Must be called inside ``shard_map`` with ``axis_name`` bound in the mesh.
    Block layout: the global sequence is sharded contiguously — device ``i``
    holds tokens ``[i*S_local, (i+1)*S_local)``.

    Args:
      q, k, v: local shards ``[batch, seq_local, heads, head_dim]``.
      causal: apply a causal mask over *global* positions.
      impl: ``None`` auto-selects the Pallas flash inner kernel when
        eligible (:func:`_ring_flash_ok`); ``"flash"``/``"xla"`` force.
      interpret: Pallas interpreter mode for the flash inner (CPU tests).
    Returns:
      ``[batch, seq_local, heads, head_dim]`` in ``q.dtype``.
    """
    b, s_local, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if impl not in (None, "flash", "xla"):
        raise ValueError(f"unknown ring impl {impl!r}")
    if impl == "flash" or (impl is None and _ring_flash_ok(q)):
        return _ring_attention_flash(
            q, k, v, axis_name, causal, scale, interpret=interpret
        )
    n = jax.lax.psum(1, axis_name)  # static axis size
    idx = jax.lax.axis_index(axis_name)

    m0 = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    o0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    # constants start device-invariant; the loop body makes them vary over
    # the ring axis, so the carry types only match if we pre-mark them
    m0, l0, o0 = mark_varying((m0, l0, o0), varying_axes_of(q, (axis_name,)))
    # receive from the right neighbor: after i rotations we hold block idx+i
    perm = [(j, (j - 1) % n) for j in range(n)]

    def step(i, carry):
        m, l, o, k_cur, v_cur = carry
        src = (idx + i) % n
        m, l, o = _block_attn(
            q, k_cur, v_cur, scale, idx * s_local, src * s_local, causal, m, l, o
        )
        # rotate even on the last step: K/V return home, so the carry shape
        # and ownership are invariant (and XLA overlaps the permute with the
        # independent block compute above).
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, l, o, k_nxt, v_nxt

    m, l, o, _, _ = jax.lax.fori_loop(0, n, step, (m0, l0, o0, k, v))
    l_t = l.transpose(0, 2, 1)[..., None]  # [B, Sq, H, 1]
    out = jnp.where(l_t > 0, o / jnp.maximum(l_t, 1e-37), 0.0)
    return out.astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = SEQUENCE_AXIS,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    impl: Optional[str] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses construction).

    Reshards ``[B, S/n, H, D] -> [B, S, H/n, D]`` (heads must divide by the
    axis size), runs *local* full attention per head group, then reshards
    back.  Two ``all_to_all`` collectives total; on TPU they ride ICI.
    """
    n = jax.lax.psum(1, axis_name)
    b, s_local, h, d = q.shape
    if h % n != 0:
        raise ValueError(f"heads ({h}) must be divisible by the axis size ({n})")

    def scatter_heads(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def gather_heads(x):  # [B, S, H/n, D] -> [B, S/n, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    # after the reshard this is ordinary full attention over the local head
    # group — route through the shared local-attention dispatch so the
    # Pallas flash kernel applies on TPU (function-level import: attention.py
    # imports this module at load time).  ``impl``/``interpret`` mirror
    # ring_attention's (interpret = flash in Pallas interpreter mode for the
    # CPU test mesh).
    from ..ops.attention import dot_product_attention

    out = dot_product_attention(
        qg, kg, vg, causal=causal, sm_scale=scale, impl=impl,
        interpret=interpret,
    )
    return gather_heads(out)
