"""Pipeline parallelism: GPipe-style microbatch streaming over a stage axis.

The fourth parallelism axis (after data, sequence, model).  The reference
has no pipeline sharding of any kind (whole-model replication,
train_distributed.py:189,198; SURVEY.md §2.4 lists PP as absent) — this is
a beyond-parity capability, built the TPU-native way: the whole pipeline
schedule is ONE compiled SPMD program under ``shard_map`` over a
``(data, stage)`` mesh.

Layout.  The transformer's decoder blocks are homogeneous, so their params
stack into one pytree with a leading ``[depth, ...]`` layer axis; sharding
that axis over ``stage`` gives each device a contiguous group of
``depth / n_stages`` layers (a pipeline stage) with NO resharding of the
math inside a stage.  Embedding / final-LN / head params stay replicated
over ``stage`` (their FLOPs are negligible next to the blocks; replication
avoids the classic first/last-stage special cases).

Schedule.  Microbatches flow through the stages in the GPipe pattern:
``n_micro + n_stages - 1`` ticks of a ``lax.scan``; each tick every stage
applies its layer group to its current activation, then a single
``ppermute`` rotates activations one hop along the stage axis — a
nearest-neighbor ICI DMA, the same primitive ring attention uses
(``parallel.sequence``).  Stage 0 injects the next microbatch's embeddings;
the last stage computes logits + the masked partial loss.  Bubble fraction
is the usual ``(S-1)/(M+S-1)``; raise ``n_micro`` to amortize.

Gradients are exact by the same argument as the SP step (engine/sp_steps):
the objective is the global-mean loss as a replicated scalar (psum over
data AND stage of per-microbatch partial sums), so differentiating through
the scan + ppermutes yields the true global gradient — ppermute transposes
to the reverse rotation (activation cotangents ride the ring backwards,
exactly pipeline backward), stage-sharded block params get local grads, and
shard_map's AD transpose psums the replicated (embed/head) cotangents.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import _make_nd_mesh
from .tensor import mirror_opt_fields

__all__ = [
    "STAGE_AXIS",
    "make_pp_mesh",
    "pp_stack_params",
    "pp_unstack_params",
    "pp_param_specs",
    "pp_state_shardings",
]

STAGE_AXIS = "stage"


def make_pp_mesh(
    pipeline_parallelism: int,
    tensor_parallelism: int = 1,
    sequence_parallelism: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """``(data, stage)`` mesh — growing a ``model`` axis for PP x TP
    (Megatron splits inside each pipeline stage; engine/pp_steps runs
    shard_map-manual over data/stage and leaves ``model`` to the GSPMD
    partitioner) or a ``sequence`` axis for PP x SP (ring attention inside
    each stage over sequence shards).  ``mesh_utils`` ordering keeps
    successive stages ICI-adjacent so the per-tick activation ``ppermute``
    is a nearest-neighbor hop; the model/sequence axis sits innermost so
    the much-more-frequent per-matmul all-reduces (TP) or per-layer ring
    hops (SP) ride the fastest links."""
    from .mesh import MODEL_AXIS
    from .sequence import SEQUENCE_AXIS

    if tensor_parallelism > 1 and sequence_parallelism > 1:
        raise ValueError(
            "pipeline x tensor x sequence (3 inner axes) is not wired; "
            "pick PP x TP or PP x SP"
        )
    if tensor_parallelism > 1:
        return _make_nd_mesh(
            (pipeline_parallelism, tensor_parallelism),
            (STAGE_AXIS, MODEL_AXIS),
            devices,
        )
    if sequence_parallelism > 1:
        return _make_nd_mesh(
            (pipeline_parallelism, sequence_parallelism),
            (STAGE_AXIS, SEQUENCE_AXIS),
            devices,
        )
    return _make_nd_mesh((pipeline_parallelism,), (STAGE_AXIS,), devices)


def pp_stack_params(params, depth: int):
    """Re-layout a :class:`TransformerLM` params tree for the pipeline step.

    ``{block0..block{L-1}, tok_embedding, pos_embedding, ln, head}`` →
    ``{"blocks": <leading-[L] stacked tree>, "shared": <the rest>}``.
    The stacked layer axis is what ``pp_param_specs`` shards over ``stage``.
    """
    blocks = [params[f"block{i}"] for i in range(depth)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    shared = {k: v for k, v in params.items() if not k.startswith("block")}
    return {"blocks": stacked, "shared": shared}


def pp_unstack_params(pp_params, depth: int):
    """Inverse of :func:`pp_stack_params` (e.g. for export / weight port)."""
    out = dict(pp_params["shared"])
    for i in range(depth):
        out[f"block{i}"] = jax.tree.map(lambda x: x[i], pp_params["blocks"])
    return out


def pp_param_specs(pp_params, tensor_parallel: bool = False):
    """PartitionSpec pytree: blocks shard their layer axis over ``stage``,
    shared params replicate.  With ``tensor_parallel``, each block leaf
    ADDITIONALLY carries the Megatron spec from :func:`..parallel.tensor`
    shifted one dim right of the stacked layer axis (qkv/fc1 column-split,
    proj/fc2 row-split over ``model``) — the same single-source sharding
    rules as the pure-TP path."""
    if tensor_parallel:
        from .tensor import _spec_for

        def blk(path, _):
            inner = _spec_for(path)
            return P(STAGE_AXIS, *inner)

        blocks = jax.tree_util.tree_map_with_path(blk, pp_params["blocks"])
    else:
        blocks = jax.tree.map(lambda _: P(STAGE_AXIS), pp_params["blocks"])
    return {
        "blocks": blocks,
        "shared": jax.tree.map(lambda _: P(), pp_params["shared"]),
    }


def pp_state_shardings(state, mesh: Mesh, zero: bool = False):
    """Shardings for a pipeline ``TrainState``: optimizer moment trees that
    mirror the params structure take the params' specs (stage-sharded
    moments for stage-sharded layers), scalar fields stay replicated.

    ``zero``: ZeRO-1 — moments are ADDITIONALLY sharded over the ``data``
    axis on their first free divisible dim (``tensor.zero_shard_moment``,
    the same rule as the GSPMD path), cutting per-device optimizer memory
    by the data-axis size.  The pipeline step then computes the update
    OUTSIDE its shard_map so the GSPMD partitioner reduce-scatters the
    gradients into the sharded moment update and gathers fresh params
    (engine/pp_steps, ``zero=True``)."""
    from ..engine.steps import TrainState  # avoid import cycle at module load

    assert isinstance(state, TrainState)
    from .mesh import DATA_AXIS, MODEL_AXIS
    from .tensor import zero_shard_moment

    rep = NamedSharding(mesh, P())
    tp = MODEL_AXIS in mesh.axis_names and mesh.shape[MODEL_AXIS] > 1
    # derive from pp_param_specs so the layout rule has a single source of
    # truth shared with the compiled step's shard_map specs (pp_steps)
    param_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        pp_param_specs(state.params, tensor_parallel=tp),
        is_leaf=lambda x: isinstance(x, P),
    )
    moment_sh = (
        jax.tree.map(
            lambda sh, leaf: zero_shard_moment(sh, leaf, mesh),
            param_sh,
            state.params,
        )
        if zero and mesh.shape[DATA_AXIS] > 1
        else param_sh
    )
    opt_sh = mirror_opt_fields(state.opt_state, state.params, moment_sh, rep)
    bs_sh = jax.tree.map(lambda _: rep, state.batch_stats)
    return TrainState(params=param_sh, batch_stats=bs_sh, opt_state=opt_sh)
