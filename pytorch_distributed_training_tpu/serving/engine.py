"""InferenceEngine: orbax checkpoint -> compiled, batched inference.

The serving mirror of ``engine/runner.py``: build the model from the same
``model:`` config section a training run used, restore the forward-pass
leaves of its checkpoint (:func:`..engine.checkpoint.load_serving_state`),
and compile one jit program per shape bucket.  Requests of any size/length
are padded UP to a bucket, so the number of XLA compiles is bounded by
``len(batch_buckets) * len(seq_buckets)`` (classification: just
``len(batch_buckets)``) no matter what traffic looks like — the serving
analog of the fixed-shape training step.

Batch buckets are rounded up to multiples of the mesh data-axis size so
every program shards its batch the way the training step did
(``parallel/mesh.py``); compute runs in the serving dtype (default bf16,
the paper's mixed-precision stance) with f32 logits.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.checkpoint import load_serving_state
from ..engine.steps import _input_normalizer
from ..models import get_model
from ..ops.quant import quantize_tree
from ..parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    make_mesh,
    replicated_sharding,
)
from .batcher import DynamicBatcher, Request
from .decode import build_generate_fn
from .lora import LoraRegistry
from .metrics import ServingMetrics
from .scheduler import ContinuousScheduler
from .speculative import SpeculativeSpec

__all__ = ["InferenceEngine"]

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


class InferenceEngine:
    """Restore a checkpoint and serve it through a dynamic batcher.

    Use :meth:`from_config`; ``submit`` returns a future per request:

      - LM (``TransformerLM``): payload is a 1-D int token prompt; result
        ``{"tokens": int32 [gen_len], "gen_len": int}``.
      - classification (ResNet/ViT): payload is one HWC image
        (uint8, normalized in-graph; or pre-normalized float32); result
        ``{"label": int, "logits": float32 [n_classes]}``.
    """

    def __init__(
        self,
        model,
        params,
        batch_stats,
        mesh,
        *,
        is_lm: bool,
        batch_buckets: Sequence[int],
        seq_buckets: Sequence[int],
        max_batch_size: int,
        max_delay_ms: float,
        deadline_ms: Optional[float] = None,
        max_backlog: Optional[int] = None,
        max_new_tokens: int = 0,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        image_size: int = 0,
        input_norm=None,
        seed: int = 0,
        scheduler: Optional[Dict[str, Any]] = None,
        resilience: Optional[Dict[str, Any]] = None,
        quant: Optional[Dict[str, Any]] = None,
        lora: Optional[Dict[str, Any]] = None,
        speculative: Optional[Dict[str, Any]] = None,
        logger: Optional[logging.Logger] = None,
        replica_id: Optional[int] = None,
        heartbeat_path: Optional[str] = None,
        heartbeat_interval_s: float = 0.5,
        liveness_timeout_s: Optional[float] = None,
    ):
        self.model = model
        self.mesh = mesh
        self.is_lm = is_lm
        self.max_new_tokens = max_new_tokens
        self.image_size = image_size
        self.logger = logger or logging.getLogger(__name__)
        # fleet identity (serving/fleet.py): namespaces this engine's
        # process-registry mirror and names its heartbeat file; None =
        # the historical single-replica engine, byte-identical behavior
        self.replica_id = replica_id
        self.heartbeat_path = heartbeat_path
        self.metrics = ServingMetrics(replica_id)
        n_data = mesh.shape[DATA_AXIS]
        self.batch_buckets = sorted({_round_up(b, n_data) for b in batch_buckets})
        self.seq_buckets = sorted(set(int(s) for s in seq_buckets))
        # stacked decode-path modes (serving.quant / serving.lora /
        # serving.speculative), each parsed with the scheduler block's
        # copy-pop-raise idiom so a typo'd key fails at build time
        quant_cfg = dict(quant or {})
        use_quant = bool(quant_cfg.pop("enabled", False))
        if quant_cfg:
            raise ValueError(f"unknown serving.quant keys: {sorted(quant_cfg)}")
        lora_cfg = dict(lora or {})
        use_lora = bool(lora_cfg.pop("enabled", False))
        lora_rank = int(lora_cfg.pop("rank", 8))
        lora_adapters = lora_cfg.pop("adapters", None)
        if lora_cfg:
            raise ValueError(f"unknown serving.lora keys: {sorted(lora_cfg)}")
        spec_cfg = dict(speculative or {})
        use_spec = bool(spec_cfg.pop("enabled", False))
        spec_k = int(spec_cfg.pop("k", 4))
        spec_draft = spec_cfg.pop("draft", None)
        spec_draft_seed = int(spec_cfg.pop("draft_seed", 0))
        spec_min_acceptance = float(spec_cfg.pop("min_acceptance", 0.0))
        if spec_cfg:
            raise ValueError(
                f"unknown serving.speculative keys: {sorted(spec_cfg)}"
            )
        if not 0.0 <= spec_min_acceptance <= 1.0:
            raise ValueError(
                "serving.speculative.min_acceptance must be in [0, 1], "
                f"got {spec_min_acceptance}"
            )
        # the metrics object owns the one-shot floor warning: acceptance
        # is only measurable where spec_proposed/spec_accepted live
        self.metrics.spec_min_acceptance = spec_min_acceptance
        use_sched = is_lm and bool((scheduler or {}).get("enabled", False))
        if (use_quant or use_lora or use_spec) and not is_lm:
            raise ValueError("serving.quant/lora/speculative are LM-only")
        if (use_lora or use_spec) and not use_sched:
            raise ValueError(
                "serving.lora and serving.speculative require "
                "serving.scheduler.enabled — adapter multiplexing and "
                "draft verification live in the continuous scheduler's "
                "paged decode programs"
            )
        base_model = model
        # surfaced for logs/bench: which decode-path modes are on
        self.serving_modes = {
            "quant": use_quant, "lora": use_lora, "speculative": use_spec,
        }
        self.lora_registry: Optional[LoraRegistry] = None
        if use_lora:
            self.lora_registry = LoraRegistry(lora_rank, lora_adapters)
            model, params = self.lora_registry.graft(model, params)
            self.model = model
            self.logger.info(
                "multi-LoRA serving: rank %d, adapters %s",
                self.lora_registry.rank, self.lora_registry.names,
            )
        if is_lm:
            if not self.seq_buckets:
                raise ValueError("LM serving needs at least one seq bucket")
            worst = self.seq_buckets[-1] + max_new_tokens
            if worst > model.max_len:
                raise ValueError(
                    f"largest seq bucket {self.seq_buckets[-1]} + "
                    f"max_new_tokens {max_new_tokens} = {worst} exceeds "
                    f"model max_len {model.max_len}"
                )
            self._generate = build_generate_fn(
                model, max_new_tokens, temperature=temperature, eos_id=eos_id,
                quant=use_quant,
            )
        else:
            normalize = _input_normalizer(input_norm)

            @jax.jit
            def classify(params, batch_stats, img):
                img = normalize(img)
                variables = {"params": params}
                if batch_stats:
                    variables["batch_stats"] = batch_stats
                return model.apply(variables, img, train=False)

            self._classify = classify
        # params live on-device replicated for the engine's lifetime — the
        # per-batch device_put only moves the (small) padded inputs
        rep = replicated_sharding(mesh)
        self.params = jax.device_put(params, rep)
        self.batch_stats = (
            jax.device_put(batch_stats, rep) if batch_stats else {}
        )
        # int8 decode (serving.quant) on the BATCHER path: quantize once
        # at build and hand the int8 tree to the decode phase only; the
        # scheduler path quantizes its own copy (serving/scheduler.py)
        self._decode_params = None
        if use_quant and is_lm and not use_sched:
            self._decode_params = jax.device_put(
                quantize_tree(self.params), rep
            )
        self._rng = jax.random.PRNGKey(seed)
        self._batch_counter = 0
        # continuous batching (serving.scheduler.enabled): the LM decode
        # loop moves to the iteration-level scheduler over the paged KV
        # pool; the DynamicBatcher path stays the default (and the only
        # path for classification and multi-host serving)
        sched_cfg = dict(scheduler or {})
        use_sched = is_lm and bool(sched_cfg.pop("enabled", False))
        self.scheduler: Optional[ContinuousScheduler] = None
        self.batcher: Optional[DynamicBatcher] = None
        if use_sched:
            spec = None
            if use_spec:
                if spec_draft is not None:
                    # the draft clones the BASE model (never the LoRA
                    # graft: a draft miss only costs acceptance) with the
                    # config's field overrides, random-init like the
                    # checkpoint-less smoke mode — restoring a trained
                    # draft checkpoint is ROADMAP work
                    draft_model = base_model.clone(**dict(spec_draft))
                    draft_params = jax.device_put(
                        draft_model.init(
                            jax.random.PRNGKey(spec_draft_seed),
                            jnp.zeros((1, 1), jnp.int32),
                        )["params"],
                        rep,
                    )
                    spec = SpeculativeSpec(spec_k, draft_model, draft_params)
                else:
                    spec = SpeculativeSpec(spec_k)
            self.scheduler = ContinuousScheduler(
                model, self.params,
                slots=int(sched_cfg.pop("slots", 8)),
                block_size=int(sched_cfg.pop("block_size", 16)),
                num_blocks=int(sched_cfg.pop("num_blocks", 64)),
                prefix_cache=bool(sched_cfg.pop("prefix_cache", True)),
                batch_buckets=self.batch_buckets,
                seq_buckets=self.seq_buckets,
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                eos_id=eos_id,
                deadline_ms=deadline_ms,
                max_backlog=max_backlog,
                metrics=self.metrics,
                seed=seed,
                pool_sharding=rep,
                resilience=resilience,
                quant=use_quant,
                lora=self.lora_registry,
                speculative=spec,
                async_depth=int(sched_cfg.pop("async_depth", 0)),
                logger=self.logger,
                replica_id=replica_id,
                heartbeat_path=heartbeat_path,
                heartbeat_interval_s=heartbeat_interval_s,
                liveness_timeout_s=liveness_timeout_s,
            )
            if sched_cfg:
                raise ValueError(
                    f"unknown serving.scheduler keys: {sorted(sched_cfg)}"
                )
        else:
            if resilience is not None:
                raise ValueError(
                    "serving.resilience requires serving.scheduler.enabled "
                    "— the batcher path has no supervisor (poison-bisect, "
                    "hot-restart and replay all live in the continuous "
                    "scheduler)"
                )
            self.batcher = DynamicBatcher(
                self._run_batch, max_batch_size, max_delay_ms,
                deadline_ms=deadline_ms, max_backlog=max_backlog,
                # degradation events land in the same metrics ledger as
                # latency/throughput, so one snapshot tells the whole story
                on_timeout=lambda: self.metrics.incr("timeouts"),
                on_shed=lambda: self.metrics.incr("sheds"),
            )

    # ------------------------------------------------------------------ #

    @classmethod
    def from_config(cls, cfg: Dict[str, Any], logger=None) -> "InferenceEngine":
        """Build from a ``serve-*.yml`` config (see config_parsing)."""
        model, params, batch_stats, mesh, kwargs = cls.resolve_config(
            cfg, logger
        )
        return cls(model, params, batch_stats, mesh, **kwargs)

    @classmethod
    def resolve_config(cls, cfg: Dict[str, Any], logger=None):
        """Resolve a ``serve-*.yml`` config into constructor ingredients.

        Returns ``(model, params, batch_stats, mesh, kwargs)`` so callers
        that build SEVERAL engines from one checkpoint (the serving fleet
        — N replicas share one restored parameter tree and one mesh) pay
        the restore/init exactly once and stamp each replica's identity
        into a copy of ``kwargs``.
        """
        logger = logger or logging.getLogger(__name__)
        serve = cfg["serving"]
        dtype_name = serve.get("dtype", "bfloat16")
        if dtype_name not in _DTYPES:
            raise ValueError(
                f"serving.dtype must be one of {sorted(_DTYPES)}, got {dtype_name!r}"
            )
        dtype = _DTYPES[dtype_name]
        model_cfg = dict(cfg["model"])
        model_name = model_cfg.pop("name")
        is_lm = model_name.lower() == "transformerlm"
        n_classes = cfg["dataset"]["n_classes"]
        model = get_model(model_name, num_classes=n_classes, dtype=dtype, **model_cfg)

        mesh = make_mesh()
        ckpt_dir = serve.get("checkpoint")
        image_size = int(cfg["dataset"].get("image_size", 224))
        if ckpt_dir:
            params, batch_stats, step = load_serving_state(ckpt_dir, logger)
            logger.info("Serving %s from checkpoint iter %d", model_name, step)
        else:
            # smoke / bench mode: random init, loudly
            logger.warning(
                "serving.checkpoint not set — serving RANDOM-INIT %s "
                "weights (smoke/bench mode only)", model_name
            )
            rng = jax.random.PRNGKey(int(serve.get("seed", 0)))
            if is_lm:
                seq = min(int(s) for s in serve.get("seq_buckets", [16]))
                init_in = jnp.zeros((1, seq), jnp.int32)
            else:
                init_in = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
            variables = model.init(rng, init_in)
            params = variables["params"]
            batch_stats = variables.get("batch_stats", {})

        max_batch = int(serve.get("max_batch_size", 8))
        input_norm = None
        if not is_lm and serve.get("normalize", True):
            from ..data.datasets import IMAGENET_MEAN, IMAGENET_STD

            input_norm = (IMAGENET_MEAN, IMAGENET_STD)
        kwargs = dict(
            is_lm=is_lm,
            batch_buckets=serve.get("batch_buckets", [max_batch]),
            seq_buckets=serve.get("seq_buckets", [16]),
            max_batch_size=max_batch,
            max_delay_ms=float(serve.get("max_delay_ms", 5.0)),
            deadline_ms=(
                float(serve["deadline_ms"])
                if serve.get("deadline_ms") is not None else None
            ),
            max_backlog=(
                int(serve["max_backlog"])
                if serve.get("max_backlog") is not None else None
            ),
            max_new_tokens=int(serve.get("max_new_tokens", 16)),
            temperature=float(serve.get("temperature", 0.0)),
            eos_id=serve.get("eos_id"),
            image_size=image_size,
            input_norm=input_norm,
            seed=int(serve.get("seed", 0)),
            scheduler=serve.get("scheduler"),
            resilience=serve.get("resilience"),
            quant=serve.get("quant"),
            lora=serve.get("lora"),
            speculative=serve.get("speculative"),
            logger=logger,
        )
        return model, params, batch_stats, mesh, kwargs

    # ------------------------------------------------------------------ #

    def submit(
        self,
        payload,
        deadline_ms: Optional[float] = None,
        max_new_tokens: Optional[int] = None,
        on_token=None,
        rng=None,
        replay_tokens=None,
        adapter: Optional[str] = None,
    ):
        """Validate + enqueue one request; returns its result future.

        ``deadline_ms`` overrides the engine's default per-request
        deadline (``serving.deadline_ms``); past it an unflushed request
        resolves with ``TimeoutError``.  LM-only extras: ``max_new_tokens``
        caps this request below ``serving.max_new_tokens`` (on the
        batcher path the result is truncated host-side — the batch still
        pays the full decode; the scheduler path retires the slot the
        moment the cap is hit), ``on_token``/``rng`` stream tokens /
        override the sampling key and need the continuous scheduler, and
        ``adapter`` routes the request through a registered LoRA adapter
        (``serving.lora``, scheduler path only).
        """
        if self.is_lm:
            prompt = np.asarray(payload, np.int32)
            if prompt.ndim != 1 or prompt.size < 1:
                raise ValueError(
                    f"LM payload must be a non-empty 1-D token sequence, "
                    f"got shape {prompt.shape}"
                )
            if prompt.size > self.seq_buckets[-1]:
                raise ValueError(
                    f"prompt length {prompt.size} exceeds largest seq "
                    f"bucket {self.seq_buckets[-1]}"
                )
            if max_new_tokens is not None and not (
                1 <= int(max_new_tokens) <= self.max_new_tokens
            ):
                raise ValueError(
                    f"max_new_tokens must be in [1, {self.max_new_tokens}], "
                    f"got {max_new_tokens}"
                )
            if self.scheduler is not None:
                return self.scheduler.submit(
                    prompt, deadline_ms=deadline_ms,
                    max_new_tokens=max_new_tokens, on_token=on_token, rng=rng,
                    replay_tokens=replay_tokens, adapter=adapter,
                )
            if (
                on_token is not None or rng is not None or replay_tokens
                or adapter is not None
            ):
                raise ValueError(
                    "on_token / per-request rng / replay_tokens / adapter "
                    "require serving.scheduler.enabled (the batcher path "
                    "samples whole batches and resolves futures only at "
                    "the end)"
                )
            return self.batcher.submit(
                prompt, deadline_ms=deadline_ms,
                max_new=(int(max_new_tokens) if max_new_tokens else None),
            )
        if (
            max_new_tokens is not None or on_token is not None
            or rng is not None or replay_tokens or adapter is not None
        ):
            raise ValueError(
                "max_new_tokens/on_token/rng/replay_tokens/adapter are "
                "LM-only"
            )
        img = np.asarray(payload)
        want = (self.image_size, self.image_size, 3)
        if img.shape != want:
            raise ValueError(f"image payload must have shape {want}, got {img.shape}")
        return self.batcher.submit(img, deadline_ms=deadline_ms)

    def depth(self) -> int:
        if self.scheduler is not None:
            return self.scheduler.depth()
        return self.batcher.depth()

    def compile_count(self) -> int:
        """Number of distinct XLA programs compiled so far (<= bucket grid)."""
        if self.scheduler is not None:
            return self.scheduler.compile_count()
        fn = self._generate if self.is_lm else self._classify
        return fn._cache_size()

    def drain(self, deadline_ms: Optional[float] = None) -> float:
        """Graceful shutdown: stop admitting, finish in-flight, close.

        Returns wall ms spent.  On the scheduler path the drain is
        deadline-bounded (``serving.resilience.drain_deadline_ms`` or the
        override); the batcher path has no admission gate beyond
        ``close()``'s synchronous flush, so drain == close there.
        """
        if self.scheduler is not None:
            return self.scheduler.drain(deadline_ms)
        import time

        t0 = time.monotonic()
        self.batcher.close()
        return (time.monotonic() - t0) * 1000.0

    def health(self) -> Dict[str, Any]:
        """Readiness/liveness snapshot for orchestration probes."""
        if self.scheduler is not None:
            return self.scheduler.health()
        return {
            "ready": True,
            "live": True,
            "queue_depth": self.batcher.depth(),
        }

    def warmup(self) -> Dict[str, float]:
        """Compile every program the engine can ever run, NOW.

        A freshly restored engine pays its XLA compiles on first traffic
        — which is exactly when an autoscaler scale-up needs the new
        replica to absorb load, so cold-compile latency lands in client
        TTFT at the worst possible moment.  Warmup drives one throwaway
        call through each (batch-bucket × seq-bucket) prefill program and
        each decode-phase program instead: scheduler-path calls use
        all-``-1`` positions (every pool scatter drops — the OOB idiom)
        and DISCARD the returned pool, so the live pool is never mutated
        and the call is safe even against a running scheduler thread.

        Returns ``{"warmup_ms", "programs"}`` (programs = compile-count
        delta, 0 when everything was already warm — warmup is
        idempotent).  ``ServingFleet.add_replica`` calls this before
        routing traffic to a new replica and publishes the wall time as
        the ``scale_up_ready_ms`` gauge.
        """
        import time

        t0 = time.perf_counter()
        before = self.compile_count()
        if not self.is_lm:
            self._warmup_classify()
        elif self.scheduler is not None:
            self._warmup_scheduler()
        else:
            self._warmup_batcher()
        warmed = self.compile_count() - before
        ms = (time.perf_counter() - t0) * 1000.0
        self.logger.info(
            "engine warmup: %d program(s) compiled in %.0f ms", warmed, ms
        )
        return {"warmup_ms": ms, "programs": float(warmed)}

    def _warmup_scheduler(self) -> None:
        sched = self.scheduler
        pad_key = sched._pad_key
        T = sched.table_blocks
        for bb in sched.batch_buckets:
            keys = jnp.stack([pad_key] * bb)
            gi = np.zeros((bb,), np.int32)
            aids = np.full((bb,), -1, np.int32)
            last_col = np.zeros((bb,), np.int32)
            tables = np.zeros((bb, T), np.int32)
            for sb in sched.seq_buckets:
                tok, _, _pool = sched._fns.prefill(
                    sched.params, sched._pool,
                    np.zeros((bb, sb), np.int32),
                    np.full((bb, sb), -1, np.int32),
                    tables, last_col, keys, gi, aids,
                )
                jax.block_until_ready(tok)
        W = sched.slots_n
        pos = np.full((W,), -1, np.int32)
        dtables = np.zeros((W, T), np.int32)
        dgi = np.zeros((W,), np.int32)
        daids = np.full((W,), -1, np.int32)
        dkeys = jnp.stack([pad_key] * W)
        dparams = sched._qparams if sched._quant else sched.params
        tok, _, _pool = sched._fns.decode_step(
            dparams, sched._pool, np.zeros((W,), np.int32), pos, dtables,
            dkeys, dgi, daids,
        )
        jax.block_until_ready(tok)
        if sched._async_depth:
            # _zero_carry matches the program's own token-output sharding,
            # so this single call covers both the first dispatch and the
            # steady-state carried-token dispatch (one cache entry)
            tok, _, _pool = sched._fns.decode_step_fed(
                dparams, sched._pool, sched._zero_carry(),
                np.zeros((W,), bool), np.zeros((W,), np.int32), pos,
                dtables, dkeys, dgi, daids,
            )
            jax.block_until_ready(tok)
        if sched._spec is not None:
            self._warmup_speculative(sched)

    def _warmup_speculative(self, sched) -> None:
        """The speculative round's extra programs: the verify scorer and
        the fork's row copy on the target side, plus the draft model's
        own prefill/decode set over the draft pool."""
        W = sched.slots_n
        T = sched.table_blocks
        k = sched._spec.k
        pad_keys = jnp.stack([sched._pad_key] * W)
        aids = np.full((W,), -1, np.int32)
        logits, _pool = sched._fns.verify(
            sched.params, sched._pool,
            np.zeros((W, k + 1), np.int32),
            np.full((W, k + 1), -1, np.int32),
            np.zeros((W, T), np.int32), aids,
        )
        jax.block_until_ready(logits)
        n_rows = sched._kv.num_blocks * sched._kv.block_size
        oob = np.full((W * sched._kv.block_size,), n_rows, np.int32)
        jax.block_until_ready(sched._fns.copy_rows(sched._pool, oob, oob))
        for bb in sched.batch_buckets:
            keys = jnp.stack([sched._pad_key] * bb)
            for sb in sched.seq_buckets:
                tok, _, _pool = sched._draft_fns.prefill(
                    sched._draft_params, sched._draft_pool,
                    np.zeros((bb, sb), np.int32),
                    np.full((bb, sb), -1, np.int32),
                    np.zeros((bb, T), np.int32),
                    np.zeros((bb,), np.int32), keys,
                    np.zeros((bb,), np.int32), np.full((bb,), -1, np.int32),
                )
                jax.block_until_ready(tok)
        tok, _, _pool = sched._draft_fns.decode_step(
            sched._draft_params, sched._draft_pool,
            np.zeros((W,), np.int32), np.full((W,), -1, np.int32),
            np.zeros((W, T), np.int32), pad_keys,
            np.zeros((W,), np.int32), aids,
        )
        jax.block_until_ready(tok)

    def _warmup_batcher(self) -> None:
        """Batcher-path warmup: one (prefill, decode) execution per
        (batch, seq) bucket pair through the exact ``_run_lm`` shapes.
        Decode here actually runs its while_loop (bounded by
        ``max_new_tokens``) — warmup cost is dominated by the compiles
        it exists to front-load."""
        tok_sh = batch_sharding(self.mesh, 2)
        row_sh = batch_sharding(self.mesh, 1)
        rng = jax.random.PRNGKey(0)
        dp = (
            self.params if self._decode_params is None
            else self._decode_params
        )
        for bb in self.batch_buckets:
            plen = jax.device_put(np.ones((bb,), np.int32), row_sh)
            for sb in self.seq_buckets:
                carry = self._generate.prefill(
                    self.params,
                    jax.device_put(np.zeros((bb, sb), np.int32), tok_sh),
                    plen, rng,
                )
                out, _gen = self._generate.decode(dp, plen, carry)
                jax.block_until_ready(out)

    def _warmup_classify(self) -> None:
        for bb in self.batch_buckets:
            img = np.zeros(
                (bb, self.image_size, self.image_size, 3), np.float32
            )
            jax.block_until_ready(
                self._classify(
                    self.params, self.batch_stats,
                    jax.device_put(img, batch_sharding(self.mesh, 4)),
                )
            )

    def install_drain_handler(self, signum=None) -> None:
        """Route SIGTERM (or ``signum``) to a graceful :meth:`drain`.

        The handler only spawns a daemon thread — drain joins the
        scheduler thread, which a signal handler must not do inline
        (handlers run ON the main thread, possibly inside scheduler-
        adjacent code).  Call from the main thread (signal.signal's own
        requirement).
        """
        import signal
        import threading

        signum = signal.SIGTERM if signum is None else signum

        def _handler(sig, frame):
            self.logger.warning(
                "signal %s received — draining serving engine", sig
            )
            threading.Thread(
                target=self.drain, name="serving-drain", daemon=True
            ).start()

        signal.signal(signum, _handler)

    def close(self) -> None:
        if self.scheduler is not None:
            self.scheduler.close()
        else:
            self.batcher.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------ #

    def _bucket_for(self, n: int, buckets: Sequence[int], kind: str) -> int:
        for b in buckets:
            if n <= b:
                return b
        raise ValueError(f"{kind} {n} exceeds largest bucket {buckets[-1]}")

    def _next_rng(self):
        self._batch_counter += 1
        return jax.random.fold_in(self._rng, self._batch_counter)

    def _run_batch(self, requests: List[Request]) -> List[Any]:
        depth = self.batcher.depth()
        if self.is_lm:
            results, phase = self._run_lm(requests)
            n_items = int(sum(r["gen_len"] for r in results))
            self.metrics.record_batch(
                [r.enqueued_at for r in requests], n_items, depth,
                gen_lens=[r["gen_len"] for r in results], **phase,
            )
        else:
            results = self._run_images(requests)
            self.metrics.record_batch(
                [r.enqueued_at for r in requests], len(results), depth
            )
        return results

    def _run_lm(self, requests: List[Request]):
        import time

        lens = [req.payload.size for req in requests]
        bb = self._bucket_for(len(requests), self.batch_buckets, "batch size")
        sb = self._bucket_for(max(lens), self.seq_buckets, "prompt length")
        tokens = np.zeros((bb, sb), np.int32)
        prompt_len = np.ones((bb,), np.int32)  # pad rows: 1-token dummy
        for i, req in enumerate(requests):
            tokens[i, : lens[i]] = req.payload
            prompt_len[i] = lens[i]
        tok_sh = batch_sharding(self.mesh, 2)
        row_sh = batch_sharding(self.mesh, 1)
        plen_dev = jax.device_put(prompt_len, row_sh)
        # phase-timed (round 6): prefill and decode are separate programs
        # (serving/decode.py), so each gets its own wall clock — the sync
        # between them is one block_until_ready on the carry, which the
        # decode dispatch would have waited on anyway
        t0 = time.perf_counter()
        carry = self._generate.prefill(
            self.params, jax.device_put(tokens, tok_sh), plen_dev,
            self._next_rng(),
        )
        jax.block_until_ready(carry)
        t1 = time.perf_counter()
        out, gen_len = self._generate.decode(
            self.params if self._decode_params is None
            else self._decode_params,
            plen_dev, carry,
        )
        out = np.asarray(out)  # host materialization = decode sync
        gen_len = np.asarray(gen_len)
        t2 = time.perf_counter()
        results = []
        for i, req in enumerate(requests):
            g = int(gen_len[i])
            # per-request cap on the batch path: TRUNCATE host-side — the
            # whole batch already paid the full decode loop, which is
            # precisely the pathology the continuous scheduler removes
            cap = req.meta.get("max_new")
            if cap:
                g = min(g, int(cap))
            results.append({"tokens": out[i, :g], "gen_len": g})
        phase = dict(
            prompt_tokens=int(sum(lens)), prefill_s=t1 - t0, decode_s=t2 - t1
        )
        return results, phase

    def _run_images(self, requests: List[Request]) -> List[Any]:
        bb = self._bucket_for(len(requests), self.batch_buckets, "batch size")
        first = requests[0].payload
        img = np.zeros((bb,) + first.shape, first.dtype)
        for i, req in enumerate(requests):
            img[i] = req.payload
        logits = self._classify(
            self.params,
            self.batch_stats,
            jax.device_put(img, batch_sharding(self.mesh, 4)),
        )
        logits = np.asarray(logits, np.float32)
        return [
            {"label": int(logits[i].argmax()), "logits": logits[i]}
            for i in range(len(requests))
        ]
