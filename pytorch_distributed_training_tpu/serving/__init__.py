"""Checkpoint-to-inference serving: the consumer side of the lifecycle.

The training half of this repo ends at an orbax checkpoint; this package
turns one into a low-latency batched service, applying the paper's central
lever — saturate the accelerator by batching — to inference:

  - :mod:`.engine`   — :class:`InferenceEngine`: restore, compile, serve.
  - :mod:`.batcher`  — :class:`DynamicBatcher`: request queue with
    max-batch-size / max-delay flush and per-request futures.
  - :mod:`.decode`   — autoregressive generation over the KV-cache decode
    mode of :class:`..models.transformer_lm.TransformerLM`.
  - :mod:`.metrics`  — p50/p99 latency, queue depth, throughput.
  - :mod:`.scheduler` — :class:`ContinuousScheduler`: iteration-level
    (continuous) batching — slot array + per-step retire-and-refill.
  - :mod:`.kv_pool`  — :class:`PagedKVPool`: block allocator, admission
    control, and prefix cache behind the paged attention mode.
  - :mod:`.resilience` — :class:`ServingSupervisor`: poison-bisect
    request isolation, bounded hot-restart with token-identical replay,
    drain/health lifecycle.

``python -m pytorch_distributed_training_tpu.serving --config
config/serve-lm.yml`` runs a synthetic open-loop demo (``__main__``).
"""
from .batcher import DynamicBatcher
from .decode import build_generate_fn, build_paged_fns
from .engine import InferenceEngine
from .kv_pool import BlockAllocator, PagedKVPool
from .metrics import ServingMetrics
from .resilience import (
    EngineRestartError,
    HungTickError,
    PoisonedRequestError,
    ServingSupervisor,
)
from .scheduler import ContinuousScheduler

__all__ = [
    "BlockAllocator",
    "ContinuousScheduler",
    "DynamicBatcher",
    "EngineRestartError",
    "HungTickError",
    "InferenceEngine",
    "PagedKVPool",
    "PoisonedRequestError",
    "ServingMetrics",
    "ServingSupervisor",
    "build_generate_fn",
    "build_paged_fns",
]
