"""Checkpoint-to-inference serving: the consumer side of the lifecycle.

The training half of this repo ends at an orbax checkpoint; this package
turns one into a low-latency batched service, applying the paper's central
lever — saturate the accelerator by batching — to inference:

  - :mod:`.engine`   — :class:`InferenceEngine`: restore, compile, serve.
  - :mod:`.batcher`  — :class:`DynamicBatcher`: request queue with
    max-batch-size / max-delay flush and per-request futures.
  - :mod:`.decode`   — autoregressive generation over the KV-cache decode
    mode of :class:`..models.transformer_lm.TransformerLM`.
  - :mod:`.metrics`  — p50/p99 latency, queue depth, throughput.
  - :mod:`.scheduler` — :class:`ContinuousScheduler`: iteration-level
    (continuous) batching — slot array + per-step retire-and-refill.
  - :mod:`.kv_pool`  — :class:`PagedKVPool`: block allocator, admission
    control, and prefix cache behind the paged attention mode.
  - :mod:`.resilience` — :class:`ServingSupervisor`: poison-bisect
    request isolation, bounded hot-restart with token-identical replay,
    drain/health lifecycle.
  - :mod:`.router`   — :class:`FleetRouter`: health-gated, prefix-affine
    placement over N replicas; replica failover with token-identical
    replay, hedged re-dispatch, fleet backpressure.
  - :mod:`.fleet`    — :class:`ServingFleet`: replica lifecycle (one
    checkpoint restore, N engines), concurrent drain, SIGTERM handler,
    aggregate health/metrics, elastic add/remove of replicas.
  - :mod:`.workload` — :class:`TraceGenerator`: seeded diurnal +
    flash-crowd request traces (pure function of the seed).
  - :mod:`.autoscaler` — :class:`FleetAutoscaler`: SLO-driven replica
    scaling; grows via the shared restore, shrinks only through drain.
  - :mod:`.kv_transfer` — content-addressed, CRC-32-verified paged-KV
    block export/import between replicas (host-staged).
  - :mod:`.disagg`   — :class:`DisaggFleet`: prefill/decode
    disaggregation over a :class:`FleetCacheDirectory` fleet-shared
    prefix-cache tier, with a degrade-to-colocated recovery ladder.

``python -m pytorch_distributed_training_tpu.serving --config
config/serve-lm.yml`` runs a synthetic open-loop demo (``__main__``).
"""
from .autoscaler import FleetAutoscaler
from .batcher import DynamicBatcher
from .decode import build_generate_fn, build_paged_fns
from .disagg import DisaggFleet, FleetCacheDirectory
from .engine import InferenceEngine
from .fleet import ServingFleet
from .kv_pool import BlockAllocator, PagedKVPool
from .kv_transfer import BlockPayload, payload_checksum, verify_payload
from .metrics import ServingMetrics, aggregate_snapshots
from .resilience import (
    EngineRestartError,
    HungTickError,
    PoisonedRequestError,
    ServingSupervisor,
)
from .router import FleetDownError, FleetRouter, ReplicaDownError
from .scheduler import ContinuousScheduler
from .workload import TraceGenerator, TraceRequest

__all__ = [
    "BlockAllocator",
    "BlockPayload",
    "ContinuousScheduler",
    "DisaggFleet",
    "DynamicBatcher",
    "EngineRestartError",
    "FleetAutoscaler",
    "FleetCacheDirectory",
    "FleetDownError",
    "FleetRouter",
    "HungTickError",
    "InferenceEngine",
    "PagedKVPool",
    "PoisonedRequestError",
    "ReplicaDownError",
    "ServingFleet",
    "ServingMetrics",
    "ServingSupervisor",
    "TraceGenerator",
    "TraceRequest",
    "aggregate_snapshots",
    "build_generate_fn",
    "build_paged_fns",
    "payload_checksum",
    "verify_payload",
]
