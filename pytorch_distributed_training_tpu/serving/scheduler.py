"""Iteration-level (continuous) batching over the paged KV pool.

The :class:`..serving.batcher.DynamicBatcher` schedules at request-batch
granularity: a batch holds its jit program until every member finishes
decoding, so one long generation stalls the accelerator for the whole
group — the pathology PERF.md's serve bench measures directly.  This
module replaces that with Orca-style iteration-level scheduling (Yu et
al. OSDI'22) over a vLLM-style paged cache (Kwon et al. SOSP'23,
serving/kv_pool.py): the decode loop is a HOST-driven step loop over a
fixed-width slot array, and between single-token steps finished rows are
retired and their slots refilled from the queue with freshly prefilled
requests.  A slot never waits on its neighbors.

Compile count stays bounded by construction, exactly like the batcher
path: every device call has a fixed shape — prefill pads (rows, suffix
tokens) up to the (batch, seq) bucket grid, and the decode step is ONE
[slots, 1] program reused forever (inactive slots ride along with
position -1; their pool scatter drops and their sampled token is ignored
host-side).  Admitting more traffic changes the CONTENT of those arrays,
never their shape.

Degradation composes with PR 3's levers: per-request ``deadline_ms``
expires requests still QUEUED past their deadline (admitted requests run
to completion — retiring mid-flight would waste the blocks already
computed), and ``max_backlog`` sheds with the batcher's
:class:`OverloadedError` after sweeping expired entries out of the depth
accounting.  Counters flow through :class:`ServingMetrics` and are
mirrored into the process telemetry registry (``serving_*``) so the
one-ledger rule holds.

Single-process by design (for now): inputs are handed to jit uncommitted
rather than sharded over the mesh — multi-host serving stays on the
batcher path until the scheduler learns sharded block tables.

Determinism for tests: construct with ``start=False`` and drive
:meth:`tick` by hand — one tick = admit + prefill + one decode step, so a
scripted arrival trace replays bit-identically.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry.registry import get_registry
from .batcher import OverloadedError
from .decode import build_paged_fns
from .kv_pool import PagedKVPool
from .metrics import ServingMetrics

__all__ = ["ContinuousScheduler"]


class _PagedRequest:
    """One request's slot-side state: prompt, reservation, token stream."""

    __slots__ = (
        "prompt", "max_new", "future", "enqueued_at", "deadline",
        "on_token", "row_key", "admission", "slot", "tokens",
    )

    def __init__(self, prompt, max_new, deadline, on_token, row_key):
        self.prompt = prompt  # 1-D np.int32
        self.max_new = max_new
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()
        self.deadline = deadline  # absolute monotonic, None = forever
        self.on_token = on_token
        self.row_key = row_key
        self.admission = None  # set when a slot admits us
        self.slot = -1
        self.tokens: List[int] = []

    @property
    def gen_idx(self) -> int:
        """Generated-token count so far == index of the NEXT token."""
        return len(self.tokens)


class ContinuousScheduler:
    """Slot array + block pool + host step loop.

    ``submit(prompt)`` returns a future resolved with the batcher-path
    result shape ``{"tokens": int32 [gen_len], "gen_len": int}``; the
    optional ``on_token`` callback streams each token the moment the host
    sees it (called on the scheduler thread — keep it cheap).
    """

    def __init__(
        self,
        model,
        params,
        *,
        slots: int = 8,
        block_size: int = 16,
        num_blocks: int = 64,
        prefix_cache: bool = True,
        batch_buckets: Sequence[int],
        seq_buckets: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        max_backlog: Optional[int] = None,
        metrics: Optional[ServingMetrics] = None,
        seed: int = 0,
        pool_sharding=None,
        logger: Optional[logging.Logger] = None,
        start: bool = True,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        if max_backlog is not None and max_backlog < 1:
            raise ValueError(f"max_backlog must be >= 1, got {max_backlog}")
        self.slots_n = int(slots)
        self.batch_buckets = sorted(set(int(b) for b in batch_buckets))
        self.seq_buckets = sorted(set(int(s) for s in seq_buckets))
        if not self.batch_buckets or not self.seq_buckets:
            raise ValueError("scheduler needs batch_buckets and seq_buckets")
        self.max_new_tokens = int(max_new_tokens)
        worst = self.seq_buckets[-1] + self.max_new_tokens
        if worst > model.max_len:
            raise ValueError(
                f"largest seq bucket {self.seq_buckets[-1]} + max_new_tokens "
                f"{self.max_new_tokens} = {worst} exceeds model max_len "
                f"{model.max_len}"
            )
        self.eos_id = eos_id
        self.deadline_ms = deadline_ms
        self.max_backlog = max_backlog
        self.logger = logger or logging.getLogger(__name__)
        self.metrics = metrics or ServingMetrics()

        self._kv = PagedKVPool(num_blocks, block_size, prefix_cache)
        # every block table is padded to the worst-case footprint so the
        # decode program's shape never depends on a request's length
        self.table_blocks = self._kv.blocks_needed(
            self.seq_buckets[-1], self.max_new_tokens
        )
        if self.table_blocks > self._kv.num_blocks:
            raise ValueError(
                f"worst-case request needs {self.table_blocks} blocks but "
                f"num_blocks is {self._kv.num_blocks}; grow the pool or "
                "shrink seq_buckets/max_new_tokens"
            )
        self._fns = build_paged_fns(
            model, block_size, num_blocks, temperature=temperature
        )
        self.params = params
        self._pool = self._fns.init_pool(params)
        if pool_sharding is not None:
            # land the initial pool under the same sharding jit will give
            # the UPDATED pool, or the second call of each prefill shape
            # recompiles for the sharding change (engine passes the mesh's
            # replicated sharding; plain single-device use needs nothing)
            self._pool = jax.device_put(self._pool, pool_sharding)
        self._pad_key = jax.random.PRNGKey(0)
        self._base_rng = jax.random.PRNGKey(int(seed))
        self._seq_no = 0  # guarded by: self._cond

        # _slots is the scheduler thread's working set: only _admit /
        # _fail_inflight / drain touch it cross-thread, and they take the
        # condition; per-iteration reads/writes in the loop body stay
        # lock-free by thread confinement (see module docstring).
        self._slots: List[Optional[_PagedRequest]] = [None] * self.slots_n
        self._queue: "deque[_PagedRequest]" = deque()  # guarded by: self._cond
        self._cond = threading.Condition()
        self._closed = False  # guarded by: self._cond
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="serving-scheduler", daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------------ #
    # client side

    def submit(
        self,
        prompt,
        deadline_ms: Optional[float] = None,
        max_new_tokens: Optional[int] = None,
        on_token: Optional[Callable[[int], None]] = None,
        rng=None,
    ) -> Future:
        """Enqueue one prompt; the future resolves at retirement.

        ``max_new_tokens`` caps THIS request below the scheduler-wide
        budget (its slot retires early instead of padding the batch with
        dead decode steps — the whole point of iteration-level
        scheduling); ``rng`` overrides the request's sampling key (a
        PRNGKey) so tests can replay the whole-batch path row for row.
        """
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(
                f"prompt must be a non-empty 1-D token sequence, got shape "
                f"{prompt.shape}"
            )
        if prompt.size > self.seq_buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds largest seq bucket "
                f"{self.seq_buckets[-1]}"
            )
        mnt = self.max_new_tokens if max_new_tokens is None else int(max_new_tokens)
        if not 1 <= mnt <= self.max_new_tokens:
            raise ValueError(
                f"max_new_tokens must be in [1, {self.max_new_tokens}], got {mnt}"
            )
        dl = deadline_ms if deadline_ms is not None else self.deadline_ms
        if dl is not None and dl <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {dl}")
        with self._cond:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            # sweep expired entries BEFORE the backlog check so live
            # requests are never shed to protect doomed ones (the
            # DynamicBatcher bug this PR also fixes)
            self._sweep_expired_locked()
            if (
                self.max_backlog is not None
                and len(self._queue) >= self.max_backlog
            ):
                self._bump("sheds")
                raise OverloadedError(
                    f"serving backlog full ({self.max_backlog} waiting); "
                    "request shed"
                )
            if rng is None:
                rng = jax.random.fold_in(self._base_rng, self._seq_no)
                self._seq_no += 1
            req = _PagedRequest(
                prompt, mnt,
                deadline=(time.monotonic() + dl / 1000.0) if dl else None,
                on_token=on_token, row_key=rng,
            )
            self._queue.append(req)
            self.metrics.observe_depth(len(self._queue))
            self._cond.notify_all()
        return req.future

    def depth(self) -> int:
        """Requests queued but not yet admitted to a slot."""
        with self._cond:
            return len(self._queue)

    def active(self) -> int:
        """Slots currently decoding.

        Takes the condition: callers poll this from foreign threads, and
        an unlocked read races _fail_inflight's wholesale rebind of the
        slot list (it could observe retired requests as still active).
        """
        with self._cond:
            return sum(1 for s in self._slots if s is not None)

    def compile_count(self) -> int:
        """Distinct XLA programs compiled so far: bounded by the prefill
        bucket grid + the single decode-step program, whatever traffic
        does."""
        return self._fns._cache_size()

    def close(self) -> None:
        """Drain queue and in-flight slots, then stop the loop."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
        else:
            # test mode (start=False): drain synchronously
            while self.tick():
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------ #
    # scheduler side — everything below runs on ONE thread (the loop, or
    # the test driving tick() by hand), which is what lets kv_pool.py go
    # lock-free

    def tick(self) -> bool:
        """One scheduler iteration: admit+prefill, then one decode step.

        Returns True if any work happened (the synchronous drain in
        ``close`` loops on it).
        """
        newly = self._admit()
        if newly:
            self._prefill(newly)
        n_active = self.active()
        if n_active:
            self._decode_step()
        return bool(newly) or n_active > 0

    def _bump(self, name: str, n: int = 1) -> None:
        """Engine-local AND process-global: the snapshot shows the
        engine's own counts, the telemetry registry the fleet view."""
        self.metrics.incr(name, n)
        get_registry().counter(f"serving_{name}").inc(n)

    def _expire(self, req: _PagedRequest, now: float) -> bool:
        if req.deadline is None or now < req.deadline:
            return False
        self._bump("timeouts")
        if not req.future.done():
            req.future.set_exception(
                TimeoutError(
                    "serving request exceeded its deadline after "
                    f"{now - req.enqueued_at:.3f}s in queue"
                )
            )
        return True

    def _sweep_expired_locked(self) -> None:
        now = time.monotonic()
        if any(r.deadline is not None and now >= r.deadline for r in self._queue):
            self._queue = deque(
                r for r in self._queue if not self._expire(r, now)
            )

    def _admit(self) -> List[_PagedRequest]:
        """Fill free slots from the queue head (FCFS: a head request the
        pool cannot cover blocks those behind it — no starvation, at the
        cost of head-of-line blocking; counted as ``admission_waits``)."""
        newly: List[_PagedRequest] = []
        with self._cond:
            self._sweep_expired_locked()
            free = [i for i, s in enumerate(self._slots) if s is None]
            # one prefill call per tick: cap admissions at the largest
            # batch bucket so the call stays on the compiled grid
            max_admit = min(len(free), self.batch_buckets[-1])
            while self._queue and len(newly) < max_admit:
                req = self._queue[0]
                adm = self._kv.admit(req.prompt.tolist(), req.max_new)
                if adm is None:
                    self._bump("admission_waits")
                    break
                self._queue.popleft()
                req.admission = adm
                req.slot = free[len(newly)]
                self._slots[req.slot] = req
                newly.append(req)
                self._bump("admitted")
                cacheable = (req.prompt.size - 1) // self._kv.block_size
                if adm.n_shared:
                    self._bump("prefix_hit_blocks", adm.n_shared)
                if cacheable - adm.n_shared:
                    self._bump("prefix_miss_blocks", cacheable - adm.n_shared)
        return newly

    def _bucket_for(self, n: int, buckets: Sequence[int], kind: str) -> int:
        for b in buckets:
            if n <= b:
                return b
        raise ValueError(f"{kind} {n} exceeds largest bucket {buckets[-1]}")

    def _prefill(self, newly: List[_PagedRequest]) -> None:
        """One bucketed prefill over every request admitted this tick.

        Prefix-cache hits shorten the device work directly: only the
        SUFFIX past ``cached_len`` is fed (positions ``cached_len ..
        prompt_len-1``), padded up to a seq bucket.
        """
        t0 = time.perf_counter()
        suffix = [r.prompt.size - r.admission.cached_len for r in newly]
        bb = self._bucket_for(len(newly), self.batch_buckets, "admitted rows")
        sb = self._bucket_for(max(suffix), self.seq_buckets, "prefill suffix")
        tokens = np.zeros((bb, sb), np.int32)
        positions = np.full((bb, sb), -1, np.int32)
        tables = np.zeros((bb, self.table_blocks), np.int32)
        last_col = np.zeros((bb,), np.int32)
        keys = [self._pad_key] * bb
        for i, req in enumerate(newly):
            cl = req.admission.cached_len
            tokens[i, : suffix[i]] = req.prompt[cl:]
            positions[i, : suffix[i]] = np.arange(cl, req.prompt.size)
            tables[i, : len(req.admission.block_ids)] = req.admission.block_ids
            last_col[i] = suffix[i] - 1
            keys[i] = req.row_key
        tok, self._pool = self._fns.prefill(
            self.params, self._pool, tokens, positions, tables,
            last_col, jnp.stack(keys),
        )
        tok = np.asarray(tok)
        t1 = time.perf_counter()
        for i, req in enumerate(newly):
            # blocks are filled now — publish them for future prefix hits
            # BEFORE this request can retire and release them
            self._kv.register_prefix(req.prompt.tolist(), req.admission)
            self._push_token(req, int(tok[i]))
        self.metrics.record_prefill(
            prompt_tokens=int(sum(suffix)), n_requests=len(newly),
            prefill_s=t1 - t0,
        )

    def _decode_step(self) -> None:
        """One single-token step for every occupied slot."""
        t0 = time.perf_counter()
        W = self.slots_n
        prev = np.zeros((W,), np.int32)
        pos = np.full((W,), -1, np.int32)
        tables = np.zeros((W, self.table_blocks), np.int32)
        gen_idx = np.zeros((W,), np.int32)
        keys = [self._pad_key] * W
        active = []
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            active.append(req)
            prev[i] = req.tokens[-1]
            # prev = generated token gen_idx-1 at global position
            # prompt_len + gen_idx - 1; feeding it samples token gen_idx
            pos[i] = req.prompt.size + req.gen_idx - 1
            tables[i, : len(req.admission.block_ids)] = req.admission.block_ids
            gen_idx[i] = req.gen_idx
            keys[i] = req.row_key
        n_active = len(active)
        tok, self._pool = self._fns.decode_step(
            self.params, self._pool, prev, pos, tables,
            jnp.stack(keys), gen_idx,
        )
        tok = np.asarray(tok)
        t1 = time.perf_counter()
        for req in active:
            self._push_token(req, int(tok[req.slot]))
        self.metrics.record_decode(n_tokens=n_active, decode_s=t1 - t0)
        self.metrics.record_iteration(
            active_slots=n_active, total_slots=W,
            blocks_in_use=self._kv.blocks_in_use,
            total_blocks=self._kv.num_blocks,
        )

    def _push_token(self, req: _PagedRequest, tok: int) -> None:
        req.tokens.append(tok)
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:  # a client callback must not kill the loop
                self.logger.exception("on_token callback raised; ignoring")
        if (self.eos_id is not None and tok == self.eos_id) or (
            req.gen_idx >= req.max_new
        ):
            self._retire(req)

    def _retire(self, req: _PagedRequest) -> None:
        self._slots[req.slot] = None
        self._kv.release(req.admission)
        req.admission = None
        if not req.future.done():
            req.future.set_result(
                {
                    "tokens": np.asarray(req.tokens, np.int32),
                    "gen_len": len(req.tokens),
                }
            )
        self._bump("retired")
        self.metrics.record_request(req.enqueued_at, gen_len=len(req.tokens))
        if self._kv.prefix_evictions:
            # drain the pool's eviction tally into the ledger (the pool
            # itself is metrics-free bookkeeping)
            self._bump("prefix_evictions", self._kv.prefix_evictions)
            self._kv.prefix_evictions = 0

    def _fail_inflight(self, exc: BaseException) -> None:
        """A device error poisons every in-flight request (their pool
        state is unknown); queued requests are failed too rather than
        retried into the same error."""
        with self._cond:
            doomed = [s for s in self._slots if s is not None]
            doomed.extend(self._queue)
            self._queue.clear()
            self._slots = [None] * self.slots_n
        for req in doomed:
            if req.admission is not None:
                self._kv.release(req.admission)
                req.admission = None
            if not req.future.done():
                req.future.set_exception(exc)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not (
                    self._closed
                    or self._queue
                    or any(s is not None for s in self._slots)
                ):
                    self._cond.wait()
                if (
                    self._closed
                    and not self._queue
                    and all(s is None for s in self._slots)
                ):
                    return
            try:
                self.tick()
            except BaseException as exc:  # keep the loop alive
                self.logger.exception("scheduler tick failed")
                self._fail_inflight(exc)
