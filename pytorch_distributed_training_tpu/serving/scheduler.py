"""Iteration-level (continuous) batching over the paged KV pool.

The :class:`..serving.batcher.DynamicBatcher` schedules at request-batch
granularity: a batch holds its jit program until every member finishes
decoding, so one long generation stalls the accelerator for the whole
group — the pathology PERF.md's serve bench measures directly.  This
module replaces that with Orca-style iteration-level scheduling (Yu et
al. OSDI'22) over a vLLM-style paged cache (Kwon et al. SOSP'23,
serving/kv_pool.py): the decode loop is a HOST-driven step loop over a
fixed-width slot array, and between single-token steps finished rows are
retired and their slots refilled from the queue with freshly prefilled
requests.  A slot never waits on its neighbors.

Compile count stays bounded by construction, exactly like the batcher
path: every device call has a fixed shape — prefill pads (rows, suffix
tokens) up to the (batch, seq) bucket grid, and the decode step is ONE
[slots, 1] program reused forever (inactive slots ride along with
position -1; their pool scatter drops and their sampled token is ignored
host-side).  Admitting more traffic changes the CONTENT of those arrays,
never their shape.

Degradation composes with PR 3's levers: per-request ``deadline_ms``
expires requests still QUEUED past their deadline (admitted requests run
to completion — retiring mid-flight would waste the blocks already
computed), and ``max_backlog`` sheds with the batcher's
:class:`OverloadedError` after sweeping expired entries out of the depth
accounting.  Counters flow through :class:`ServingMetrics` and are
mirrored into the process telemetry registry (``serving_*``) so the
one-ledger rule holds.

Fault tolerance (PR 9, serving/resilience.py): a tick exception no
longer fails the world — :class:`ServingSupervisor` classifies it and
either evicts the one poisoned request (poison-bisect over
``_decode_probe``, or the on-device ``isfinite`` output guard for NaN
emitters) or hot-restarts the engine, rebuilding the compiled programs
and pool and replaying every in-flight request token-identically
(``_replay``; the per-row per-token-index ``fold_in`` keys make the
resample bitwise reproducible).  ``drain()`` gives SIGTERM a bounded
graceful shutdown and ``health()`` the readiness/liveness snapshot; an
optional tick watchdog (engine/watchdog.py) turns a hung step into a
diagnosed restart.  The ``serve_*`` kinds in engine/fault.py drive all
of it deterministically.

Async decode pipeline (``async_depth > 0``, default-off): the sync loop
above pays one full host round-trip per token — ``np.asarray(tok)``
before the next dispatch — so the device idles for the whole host
bookkeeping window every single-token step.  With a depth set, the
sampled-token carry stays ON DEVICE (``decode_step_fed`` feeds its own
output back as the next ``prev_tok``) and a bounded in-flight ring
drains host readbacks one tick behind dispatch; host bookkeeping stays
exact through per-request ``dispatched`` counters and the drained
stream is bitwise token-identical to the sync path (greedy and
sampled).  See :meth:`ContinuousScheduler._decode_step_async`.

Single-process by design (for now): inputs are handed to jit uncommitted
rather than sharded over the mesh — multi-host serving stays on the
batcher path until the scheduler learns sharded block tables.

Determinism for tests: construct with ``start=False`` and drive
:meth:`tick` by hand — one tick = admit + prefill + one decode step, so a
scripted arrival trace replays bit-identically.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..engine import fault
from ..engine.watchdog import StepWatchdog
from ..telemetry.registry import get_registry
from ..telemetry.spans import span
from ..ops.quant import quantize_tree
from . import kv_transfer
from .batcher import OverloadedError
from .decode import build_paged_fns
from .kv_pool import PagedKVPool
from .metrics import ServingMetrics
from .resilience import HungTickError, PoisonedRequestError, ServingSupervisor
from .speculative import greedy_accept

__all__ = ["ContinuousScheduler"]


class _PagedRequest:
    """One request's slot-side state: prompt, reservation, token stream."""

    __slots__ = (
        "prompt", "max_new", "future", "enqueued_at", "deadline",
        "on_token", "row_key", "admission", "slot", "tokens", "poison",
        "adapter", "adapter_name", "draft_admission", "dispatched",
    )

    def __init__(self, prompt, max_new, deadline, on_token, row_key):
        self.prompt = prompt  # 1-D np.int32
        self.max_new = max_new
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()
        self.deadline = deadline  # absolute monotonic, None = forever
        self.on_token = on_token
        self.row_key = row_key
        self.admission = None  # set when a slot admits us
        self.slot = -1
        self.tokens: List[int] = []
        self.poison = None  # fault-injection marker ("raise")
        self.adapter = -1  # LoRA adapter id; -1 = base model
        self.adapter_name: Optional[str] = None
        self.draft_admission = None  # speculative mode: draft-pool blocks
        # async pipeline: generated tokens DETERMINED so far — drained
        # into ``tokens`` plus steps still in the in-flight ring.  The
        # host derives every dispatch input (position, sampling index)
        # from this counter, so only the token VALUE needs to stay on
        # device.  Invariant: dispatched >= len(tokens); equal in sync
        # mode and whenever the ring is empty for this row.
        self.dispatched = 0

    @property
    def gen_idx(self) -> int:
        """Generated-token count so far == index of the NEXT token."""
        return len(self.tokens)


class ContinuousScheduler:
    """Slot array + block pool + host step loop.

    ``submit(prompt)`` returns a future resolved with the batcher-path
    result shape ``{"tokens": int32 [gen_len], "gen_len": int}``; the
    optional ``on_token`` callback streams each token the moment the host
    sees it (called on the scheduler thread — keep it cheap).
    """

    def __init__(
        self,
        model,
        params,
        *,
        slots: int = 8,
        block_size: int = 16,
        num_blocks: int = 64,
        prefix_cache: bool = True,
        batch_buckets: Sequence[int],
        seq_buckets: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        max_backlog: Optional[int] = None,
        metrics: Optional[ServingMetrics] = None,
        seed: int = 0,
        pool_sharding=None,
        resilience: Optional[Dict[str, Any]] = None,
        quant: bool = False,
        lora=None,
        speculative=None,
        async_depth: int = 0,
        logger: Optional[logging.Logger] = None,
        start: bool = True,
        replica_id: Optional[int] = None,
        heartbeat_path: Optional[str] = None,
        heartbeat_interval_s: float = 0.5,
        liveness_timeout_s: Optional[float] = None,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        if max_backlog is not None and max_backlog < 1:
            raise ValueError(f"max_backlog must be >= 1, got {max_backlog}")
        self.slots_n = int(slots)
        self.batch_buckets = sorted(set(int(b) for b in batch_buckets))
        self.seq_buckets = sorted(set(int(s) for s in seq_buckets))
        if not self.batch_buckets or not self.seq_buckets:
            raise ValueError("scheduler needs batch_buckets and seq_buckets")
        self.max_new_tokens = int(max_new_tokens)
        worst = self.seq_buckets[-1] + self.max_new_tokens
        if worst > model.max_len:
            raise ValueError(
                f"largest seq bucket {self.seq_buckets[-1]} + max_new_tokens "
                f"{self.max_new_tokens} = {worst} exceeds model max_len "
                f"{model.max_len}"
            )
        self.eos_id = eos_id
        self.deadline_ms = deadline_ms
        self.max_backlog = max_backlog
        self.logger = logger or logging.getLogger(__name__)
        self.metrics = metrics or ServingMetrics(replica_id)
        # fleet identity + external liveness (PR 12, serving/router.py):
        # the heartbeat file's mtime is this replica's liveness clock for
        # observers OUTSIDE the process/thread — the scheduler thread
        # itself touches it (tick + idle wakeups), deliberately not a
        # side thread, so a wedged scheduler goes stale instead of being
        # masked by a healthy beater.
        self.replica_id = replica_id
        self.heartbeat_path = heartbeat_path
        if heartbeat_interval_s <= 0:
            raise ValueError(
                f"heartbeat_interval_s must be > 0, got {heartbeat_interval_s}"
            )
        self._hb_interval = float(heartbeat_interval_s)
        self._liveness_timeout_s = (
            float(liveness_timeout_s) if liveness_timeout_s is not None
            else None
        )
        if self._liveness_timeout_s is not None and self._liveness_timeout_s <= 0:
            raise ValueError(
                f"liveness_timeout_s must be > 0, got {liveness_timeout_s}"
            )
        self._last_beat = 0.0  # scheduler-thread confined (+ constructor)

        # kept for hot-restart: _rebuild_and_requeue reconstructs the
        # compiled programs and the pool from the same ingredients
        self._model = model
        self._temperature = float(temperature)
        self._block_size = int(block_size)
        self._num_blocks = int(num_blocks)
        self._prefix_cache = bool(prefix_cache)
        self._pool_sharding = pool_sharding

        # multi-tenant decode modes (PR 17), each default-off:
        #   quant — decode programs take the int8 tree (ops/quant.py);
        #   lora — LoraRegistry: per-row adapter selection over a model
        #     already cloned/grafted with stacked factors (engine's job);
        #   speculative — SpeculativeSpec: draft-proposed, target-verified
        #     rounds over a SECOND paged pool for the draft.
        self._quant = bool(quant)
        self._lora = lora
        self._spec = speculative
        self._has_lora = getattr(model, "lora_adapters", 0) > 0
        if self._lora is not None and not self._has_lora:
            raise ValueError(
                "a LoRA registry was given but the model has no stacked "
                "factors — pass the registry's grafted (model, params) pair"
            )
        if self._spec is not None and self._temperature != 0.0:
            raise ValueError(
                "speculative decoding requires temperature 0.0: the greedy "
                "accept rule is exact only against the argmax stream (the "
                "sampled accept rule is serving/speculative.py's "
                "sampled_accept, not yet wired to the scheduler)"
            )
        # async decode pipeline (default-off): depth of the in-flight
        # dispatch ring.  0 = today's synchronous loop (read every step's
        # tokens back before dispatching the next); N >= 1 keeps up to N
        # dispatched steps un-drained, with the sampled-token carry fed
        # back ON DEVICE (decode_step_fed) so the accelerator never waits
        # out the host's per-token bookkeeping window.
        self._async_depth = int(async_depth)
        if self._async_depth < 0:
            raise ValueError(
                f"async_depth must be >= 0, got {async_depth}"
            )
        if self._async_depth and self._spec is not None:
            raise ValueError(
                "async_depth and speculative decoding are mutually "
                "exclusive: a speculative round's host accept/reject "
                "must observe every verify result before the next round "
                "can be proposed, so there is nothing to pipeline"
            )
        # speculative branch forking reserves ONE private spare block per
        # request on top of its footprint (the CoW target for the
        # boundary block each round)
        self._extra_blocks = 1 if self._spec is not None else 0

        self._kv = PagedKVPool(num_blocks, block_size, prefix_cache)
        # every block table is padded to the worst-case footprint so the
        # decode program's shape never depends on a request's length
        self.table_blocks = self._kv.blocks_needed(
            self.seq_buckets[-1], self.max_new_tokens
        )
        if self.table_blocks + self._extra_blocks > self._kv.num_blocks:
            raise ValueError(
                f"worst-case request needs "
                f"{self.table_blocks + self._extra_blocks} blocks but "
                f"num_blocks is {self._kv.num_blocks}; grow the pool or "
                "shrink seq_buckets/max_new_tokens"
            )
        self._fns = build_paged_fns(
            model, block_size, num_blocks, temperature=temperature,
            quant=self._quant,
        )
        self.params = params
        # decode programs stream the int8 tree in quant mode; prefill and
        # verify always take the plain tree (compute-bound / accuracy
        # anchor respectively — see ops/quant.py)
        self._qparams = quantize_tree(params) if self._quant else None
        self._pool = self._fns.init_pool(params)
        if pool_sharding is not None:
            # land the initial pool under the same sharding jit will give
            # the UPDATED pool, or the second call of each prefill shape
            # recompiles for the sharding change (engine passes the mesh's
            # replicated sharding; plain single-device use needs nothing)
            self._pool = jax.device_put(self._pool, pool_sharding)
        self._draft_fns = None
        self._draft_pool = None
        self._dkv: Optional[PagedKVPool] = None
        if self._spec is not None:
            # self-draft (no dedicated draft model) = draft IS the target:
            # acceptance pins at 1.0, the end-to-end exactness test
            self._draft_model = (
                self._spec.draft_model
                if self._spec.draft_model is not None else model
            )
            self._draft_params = (
                self._spec.draft_params
                if self._spec.draft_params is not None else params
            )
            self._draft_lora = (
                getattr(self._draft_model, "lora_adapters", 0) > 0
            )
            self._build_draft()
        self._pad_key = jax.random.PRNGKey(0)
        self._base_rng = jax.random.PRNGKey(int(seed))
        self._seq_no = 0  # guarded by: self._cond

        # _slots is the scheduler thread's working set: only _admit /
        # _fail_inflight / drain touch it cross-thread, and they take the
        # condition; per-iteration reads/writes in the loop body stay
        # lock-free by thread confinement (see module docstring).
        self._slots: List[Optional[_PagedRequest]] = [None] * self.slots_n  # confined: _loop
        self._queue: "deque[_PagedRequest]" = deque()  # guarded by: self._cond
        # cross-replica KV transfer verbs (serving/kv_transfer.py):
        # foreign threads enqueue export/import requests here and the
        # scheduler thread services them at its next tick boundary, so
        # pool reads and scatters keep their single-thread confinement
        self._xfer_q: deque = deque()  # guarded by: self._cond
        self._cond = threading.Condition()
        self._closed = False  # guarded by: self._cond
        self._draining = False  # guarded by: self._cond
        self._drain_deadline: Optional[float] = None  # guarded by: self._cond
        self._last_tick: Optional[float] = None  # guarded by: self._cond
        self._hang_info = None  # guarded by: self._cond
        # fleet kill/hang switches (hard_kill / inject_hang set them from
        # the router's monitor thread; the scheduler thread processes
        # them at its next tick boundary so slot/pool mutation stays
        # thread-confined)
        self._die_exc: Optional[BaseException] = None  # guarded by: self._cond
        self._dead = False  # guarded by: self._cond
        self._hang_sec: Optional[float] = None  # guarded by: self._cond
        self._tick_started_at: Optional[float] = None  # guarded by: self._cond
        # prefix-cache block tallies for the registry gauges (tick-thread
        # reads; _admit writes under the condition it already holds)
        self._hit_blocks = 0
        self._miss_blocks = 0

        # tick-thread-confined recovery state (supervisor runs inside
        # tick's except clause, on the same thread); health/_on_tick_hang
        # read them cross-thread as best-effort diagnostics
        self._tick_no = 0  # confined: _loop
        self._tick_phase = ""  # confined: _loop

        # async-pipeline state (all confined: _loop).  _inflight holds
        # (tok_dev, finite_dev, rows) per dispatched-but-undrained step;
        # _carry_tok is the LAST dispatch's on-device token vector — the
        # next step's prev_tok input.  _last_dispatch/_tick_block_s feed
        # the decode_dispatch_gap_ms / tick_host_ms histograms.
        self._inflight: deque = deque()  # confined: _loop
        self._carry_tok = None  # confined: _loop
        # (tick_no, perf_counter) of the latest decode dispatch
        self._last_dispatch: Optional[tuple] = None  # confined: _loop
        self._tick_block_s = 0.0  # confined: _loop

        res = dict(resilience or {})
        wd = dict(res.pop("watchdog", None) or {})
        self.drain_deadline_ms = res.pop("drain_deadline_ms", None)
        if self.drain_deadline_ms is not None:
            self.drain_deadline_ms = float(self.drain_deadline_ms)
            if self.drain_deadline_ms <= 0:
                raise ValueError(
                    f"drain_deadline_ms must be > 0, got {self.drain_deadline_ms}"
                )
        self._supervisor = ServingSupervisor(
            self,
            max_restarts=int(res.pop("max_restarts", 2)),
            poison_bisect=bool(res.pop("poison_bisect", True)),
            logger=self.logger,
        )
        if res:
            raise ValueError(f"unknown serving.resilience keys: {sorted(res)}")
        wd_enabled = bool(wd.pop("enabled", False))
        wd_factor = float(wd.pop("factor", 10.0))
        wd_min_seconds = float(wd.pop("min_seconds", 60.0))
        wd_warmup = int(wd.pop("warmup", 3))
        wd_poll = wd.pop("poll_seconds", None)
        if wd:
            raise ValueError(
                f"unknown serving.resilience.watchdog keys: {sorted(wd)}"
            )
        self._watchdog: Optional[StepWatchdog] = None
        if wd_enabled:
            self._watchdog = StepWatchdog(
                factor=wd_factor,
                min_seconds=wd_min_seconds,
                warmup=wd_warmup,
                poll_seconds=wd_poll,
                on_hang=self._on_tick_hang,
                logger=self.logger,
            )

        self._beat(force=True)  # exists-from-birth: no startup-grace races
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="serving-scheduler", daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------------ #
    # client side

    def submit(
        self,
        prompt,
        deadline_ms: Optional[float] = None,
        max_new_tokens: Optional[int] = None,
        on_token: Optional[Callable[[int], None]] = None,
        rng=None,
        replay_tokens: Optional[Sequence[int]] = None,
        adapter: Optional[str] = None,
    ) -> Future:
        """Enqueue one prompt; the future resolves at retirement.

        ``adapter`` names a registered LoRA adapter (serving.lora): this
        request decodes through that adapter's low-rank delta, batched in
        the SAME iteration as every other tenant's rows; None = the base
        model.  Requires the engine's LoRA registry.

        ``max_new_tokens`` caps THIS request below the scheduler-wide
        budget (its slot retires early instead of padding the batch with
        dead decode steps — the whole point of iteration-level
        scheduling); ``rng`` overrides the request's sampling key (a
        PRNGKey) so tests can replay the whole-batch path row for row.

        ``replay_tokens`` pre-populates the request's generated stream:
        admission takes the hot-restart replay path (``_replay``) instead
        of a fresh prefill, re-deriving the KV state for those tokens
        through the same decode program and verifying each one against
        the stream bit-for-bit — WITHOUT refiring ``on_token`` for them.
        This is how the fleet router fails a half-generated request over
        from a dead replica to a survivor token-identically; pass the
        exact ``rng`` the original submission used or the continuation
        diverges.
        """
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(
                f"prompt must be a non-empty 1-D token sequence, got shape "
                f"{prompt.shape}"
            )
        if prompt.size > self.seq_buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds largest seq bucket "
                f"{self.seq_buckets[-1]}"
            )
        mnt = self.max_new_tokens if max_new_tokens is None else int(max_new_tokens)
        if not 1 <= mnt <= self.max_new_tokens:
            raise ValueError(
                f"max_new_tokens must be in [1, {self.max_new_tokens}], got {mnt}"
            )
        dl = deadline_ms if deadline_ms is not None else self.deadline_ms
        if dl is not None and dl <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {dl}")
        aid = -1
        if adapter is not None:
            if self._lora is None:
                raise ValueError(
                    "adapter= requires serving.lora.enabled (no adapter "
                    "registry on this engine)"
                )
            aid = self._lora.id_of(adapter)
        replay = [int(t) for t in replay_tokens] if replay_tokens else []
        if replay:
            if rng is None:
                raise ValueError(
                    "replay_tokens needs the ORIGINAL submission's rng — "
                    "a fresh key would resample a different stream and "
                    "every replayed token would flag replay_parity_mismatch"
                )
            if len(replay) >= mnt:
                raise ValueError(
                    f"replay_tokens ({len(replay)}) must be shorter than "
                    f"max_new_tokens ({mnt}); a fully-generated request "
                    "has nothing left to decode"
                )
        with self._cond:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            if self._draining:
                raise RuntimeError(
                    "scheduler is draining; not accepting new requests"
                )
            # sweep expired entries BEFORE the backlog check so live
            # requests are never shed to protect doomed ones (the
            # DynamicBatcher bug this PR also fixes)
            self._sweep_expired_locked()
            if (
                self.max_backlog is not None
                and len(self._queue) >= self.max_backlog
            ):
                self._bump("sheds")
                raise OverloadedError(
                    f"serving backlog full ({self.max_backlog} waiting); "
                    "request shed"
                )
            if rng is None:
                rng = jax.random.fold_in(self._base_rng, self._seq_no)
                self._seq_no += 1
            req = _PagedRequest(
                prompt, mnt,
                deadline=(time.monotonic() + dl / 1000.0) if dl else None,
                on_token=on_token, row_key=rng,
            )
            req.adapter = aid
            req.adapter_name = adapter
            if replay:
                req.tokens = replay
                req.dispatched = len(replay)
            self._queue.append(req)
            self.metrics.observe_depth(len(self._queue))
            self._cond.notify_all()
        return req.future

    def depth(self) -> int:
        """Requests queued but not yet admitted to a slot."""
        with self._cond:
            return len(self._queue)

    def active(self) -> int:
        """Slots currently decoding.

        Takes the condition: callers poll this from foreign threads, and
        an unlocked read races _fail_inflight's wholesale rebind of the
        slot list (it could observe retired requests as still active).
        """
        with self._cond:
            return sum(1 for s in self._slots if s is not None)

    def compile_count(self) -> int:
        """Distinct XLA programs compiled so far: bounded by the prefill
        bucket grid + ONE program each for decode/verify/copy (per model —
        the speculative draft has its own set), whatever traffic does."""
        n = self._fns._cache_size()
        if self._draft_fns is not None:
            n += self._draft_fns._cache_size()
        return n

    def drain(self, deadline_ms: Optional[float] = None) -> float:
        """Graceful shutdown: stop admitting NEW submissions, finish the
        queued + in-flight work, then close.  Returns wall ms spent.

        Past ``deadline_ms`` (default ``resilience.drain_deadline_ms``;
        None = unbounded) the next tick fails the remaining requests with
        ``TimeoutError`` and the drain completes — bounded, like every
        other recovery path.  Safe from any thread; idempotent.
        """
        t0 = time.monotonic()
        dl = deadline_ms if deadline_ms is not None else self.drain_deadline_ms
        with self._cond:
            if self._closed:
                return 0.0
            self._draining = True
            if dl is not None:
                self._drain_deadline = t0 + dl / 1000.0
            self._cond.notify_all()
        if self._thread is None:
            while self.tick():
                pass
        else:
            with self._cond:
                while not self._closed and (
                    self._queue or any(s is not None for s in self._slots)
                ):
                    # the loop thread does the work (and enforces the
                    # deadline inside tick); this is just a progress watch
                    self._cond.wait(timeout=0.01)
        self.close()
        return (time.monotonic() - t0) * 1000.0

    def health(self) -> Dict[str, Any]:
        """Readiness/liveness snapshot for orchestration probes.

        ``ready`` = accepting submissions; ``live`` = worth keeping the
        process (False once the restart budget is exhausted, the replica
        is hard-killed, or — with ``liveness_timeout_s`` set — the
        scheduler thread has made no Python progress for that long while
        it HAD work, i.e. it is wedged inside a tick or a device call;
        idle-with-nothing-to-do never counts as stalled).  Mirrored into
        :class:`ServingMetrics` gauges (``health_*``) so one metrics
        snapshot carries health alongside latency/throughput.
        """
        now = time.monotonic()
        with self._cond:
            depth = len(self._queue)
            active = sum(1 for s in self._slots if s is not None)
            closed = self._closed
            draining = self._draining
            last = self._last_tick
            started = self._tick_started_at
            dead = self._dead
        restarts = self._supervisor.restarts()
        exhausted = self._supervisor.exhausted()
        stalled = False
        if self._liveness_timeout_s is not None:
            # a tick in progress counts as busy from its START stamp (a
            # hung device call never updates _last_tick); otherwise only
            # pending work makes an old tick suspicious — an idle healthy
            # replica legitimately stops ticking
            busy = started is not None or depth > 0 or active > 0
            ref = started if started is not None else last
            if busy and ref is not None:
                stalled = (now - ref) > self._liveness_timeout_s
        snap = {
            "ready": not (closed or draining or exhausted or dead or stalled),
            "live": not (exhausted or dead or stalled),
            "stalled": stalled,
            "queue_depth": depth,
            "active_slots": active,
            "slots": self.slots_n,
            "engine_restarts": restarts,
            "restart_budget": self._supervisor.max_restarts,
            "last_tick_age_s": (now - last) if last is not None else None,
            "draining": draining,
            "closed": closed,
        }
        self.metrics.record_health(snap)
        return snap

    def hard_kill(self, exc: BaseException) -> None:
        """Fleet-level kill switch: fail this whole replica with ``exc``.

        Safe from ANY thread (the fleet router's monitor calls it): only
        a flag is set here; the scheduler thread processes the death at
        its next tick boundary, so slot/pool mutation keeps its
        single-thread contract.  Every queued and in-flight request fails
        with ``exc`` (the router fails them over to a survivor) and the
        scheduler closes.  Idempotent; a no-op after a clean close.
        """
        with self._cond:
            if self._closed or self._die_exc is not None:
                return
            self._die_exc = exc
            self._cond.notify_all()

    def inject_hang(self, seconds: float) -> None:
        """Wedge the scheduler thread for ``seconds`` at its next tick
        boundary (the ``replica_hang`` fault hook): no Python progress,
        no heartbeat — only an OUTSIDE observer reading the heartbeat
        file's age (or ``health()``'s liveness clock) can see it, which
        is exactly what the router's staleness detection must prove."""
        with self._cond:
            if self._closed:
                return
            self._hang_sec = float(seconds)
            self._cond.notify_all()

    def export_kv_prefix(
        self,
        prompt: Sequence[int],
        namespace=None,
        stall_s: Optional[float] = None,
    ) -> Future:
        """Stage ``prompt``'s cached prefix blocks for transfer (any thread).

        Resolves to a list of CRC-sealed :class:`kv_transfer.BlockPayload`
        — possibly empty when nothing is cached.  The host-side gather
        runs on the scheduler thread at its next tick boundary, so the
        pool is quiescent for the copy.  ``stall_s`` is the
        ``kv_transfer_stall`` fault hook: the SOURCE side sleeps before
        resolving, so the importing coordinator's bounded deadline is
        exercised against a genuinely late payload.
        """
        fut: Future = Future()
        arr = np.asarray(prompt, dtype=np.int32).reshape(-1)
        with self._cond:
            if self._closed or self._dead:
                raise RuntimeError("cannot export KV from a closed scheduler")
            self._xfer_q.append(("export", (arr, namespace, stall_s), fut))
            self._cond.notify_all()
        return fut

    def export_kv_refs(
        self,
        prompt: Sequence[int],
        namespace=None,
        stall_s: Optional[float] = None,
    ) -> Future:
        """Stage ``prompt``'s cached prefix blocks as LAZY refs (any thread).

        Resolves to a list of :class:`kv_transfer.BlockRef` — the cheap
        half of an export.  Only the device slice dispatch runs on the
        scheduler thread; the caller materializes the refs into
        CRC-sealed payloads (``kv_transfer.materialize_payloads``) on its
        own executor, keeping the device→host copies and checksum work
        off the scheduler loop entirely.  This is the disaggregated
        transfer path's verb; :meth:`export_kv_prefix` keeps the one-shot
        payload contract.
        """
        fut: Future = Future()
        arr = np.asarray(prompt, dtype=np.int32).reshape(-1)
        with self._cond:
            if self._closed or self._dead:
                raise RuntimeError("cannot export KV from a closed scheduler")
            self._xfer_q.append(("export_refs", (arr, namespace, stall_s), fut))
            self._cond.notify_all()
        return fut

    def import_kv_blocks(self, payloads) -> Future:
        """Adopt transferred blocks into the local prefix cache (any thread).

        Resolves to ``{"accepted", "rejected", "bytes"}``.  Per payload,
        in chain order: a checksum mismatch rejects the block AND stops
        the chain (descendants of a corrupt link would be unreachable),
        an already-cached key is skipped (first-writer-wins — a local
        prefill beat the transfer), a full pool stops the chain.  Bad
        payloads never raise: rejection is an accounted, recoverable
        event (``kv_transfer_rejects``) and the decode side simply
        recomputes whatever did not land.
        """
        fut: Future = Future()
        with self._cond:
            if self._closed or self._dead:
                raise RuntimeError("cannot import KV into a closed scheduler")
            self._xfer_q.append(("import", list(payloads), fut))
            self._cond.notify_all()
        return fut

    def close(self) -> None:
        """Drain queue and in-flight slots, then stop the loop."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
        else:
            # test mode (start=False): drain synchronously
            while self.tick():
                pass
        if self._watchdog is not None:
            self._watchdog.close()
        self._report_unfired_faults()

    def _report_unfired_faults(self) -> None:
        """Account injected serve-side faults still armed at close.

        A one-shot fault scheduled for a tick this engine never reached
        (drain deadline expired first, queue emptied early) would otherwise
        vanish silently — the chaos oracle then mis-reads the scenario as
        "fault recovered" when it never fired.  Count and log each leftover
        so every injected fault ends the scenario as exactly one of
        fired-and-recovered or reported-unfired.
        """
        pending = fault.get_injector().pending()
        for kind, steps in pending.items():
            if not (kind.startswith("serve_") or kind.startswith("replica_")):
                continue
            fault.bump(f"fault_unfired_{kind}", len(steps))
            logging.getLogger(__name__).warning(
                "scheduler closed with injected %s fault(s) still armed for "
                "tick(s) %s — the engine never reached them", kind, steps,
            )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------ #
    # scheduler side — everything below runs on ONE thread (the loop, or
    # the test driving tick() by hand), which is what lets kv_pool.py go
    # lock-free

    def tick(self) -> bool:
        """One scheduler iteration: admit+prefill, then one decode step.

        Returns True if any work happened (the synchronous drain in
        ``close`` loops on it).  A failing tick is handed to the
        supervisor, which evicts the poisoned request or hot-restarts —
        the caller never sees the exception unless recovery itself dies.
        """
        with self._cond:
            self._tick_started_at = time.monotonic()
            die = self._die_exc
            hang, self._hang_sec = self._hang_sec, None
        if hang is not None:
            # simulated wedge: sleep BEFORE the heartbeat touch so the
            # file goes stale exactly like a real stuck device call
            self.logger.warning(
                "fault injection: replica scheduler wedged for %.2fs", hang
            )
            time.sleep(hang)
        if die is not None:
            try:
                self._die(die)
            finally:
                with self._cond:
                    self._tick_started_at = None
            return True
        self._beat()
        self._tick_no += 1
        self._tick_phase = "setup"
        if self._watchdog is not None:
            self._watchdog.step_started(self._tick_no)
        try:
            try:
                # tick_host_ms = tick wall minus time BLOCKED on device
                # readbacks (the decode paths accumulate their np.asarray
                # waits into _tick_block_s) — the host-overhead number the
                # async pipeline exists to hide
                self._tick_block_s = 0.0
                t_tick0 = time.perf_counter()
                did = self._tick_inner()
                if did:
                    self.metrics.record_tick(
                        max(
                            time.perf_counter() - t_tick0
                            - self._tick_block_s,
                            0.0,
                        ) * 1000.0
                    )
            finally:
                if self._watchdog is not None:
                    self._watchdog.step_finished()
                with self._cond:
                    self._last_tick = time.monotonic()
                    self._tick_started_at = None
            with self._cond:
                hang, self._hang_info = self._hang_info, None
            if hang is not None and hang[0] == self._tick_no:
                raise HungTickError(
                    f"scheduler tick {hang[0]} ran {hang[1]:.2f}s "
                    f"(watchdog limit {hang[2]:.2f}s)"
                )
            return did
        except Exception as exc:
            self.logger.exception(
                "scheduler tick %d failed in phase %r; invoking supervisor",
                self._tick_no, self._tick_phase,
            )
            # async pipeline: settle the in-flight dispatch ring BEFORE
            # recovery.  The supervisor's bisect probes and replays
            # assume sync-equivalent host state, and a step that was
            # merely in flight when an unrelated row poisoned the tick
            # must not confound attribution.  No-op in sync mode.
            self.flush_async()
            return self._supervisor.handle_tick_failure(exc)

    def _tick_inner(self) -> bool:
        with self._cond:
            expired = (
                self._draining
                and self._drain_deadline is not None
                and time.monotonic() >= self._drain_deadline
                and (
                    bool(self._queue)
                    or any(s is not None for s in self._slots)
                )
            )
        if expired:
            self._bump("drain_expired")
            self._fail_inflight(
                TimeoutError(
                    "graceful drain exceeded its deadline; failing the "
                    "remaining requests"
                )
            )
            return True
        self._tick_phase = "kv_transfer"
        did_xfer = self._service_kv_transfers()
        self._tick_phase = "admit"
        newly = self._admit()
        self._tick_phase = "prefill"
        if newly:
            self._prefill(newly)
        self._tick_phase = "inject"
        self._consult_injector()
        n_active = self.active()
        if n_active:
            self._tick_phase = "decode"
            if self._spec is not None:
                self._spec_decode_step()
            elif self._async_depth:
                self._decode_step_async()
            else:
                self._decode_step()
        self._publish_pool_gauges()
        return bool(newly) or n_active > 0 or did_xfer

    def _bump(self, name: str, n: int = 1) -> None:
        """Engine-local AND process-global: the snapshot shows the
        engine's own counts, the telemetry registry the fleet view.  The
        global mirror is namespaced per replica (``serving_r<id>_*``)
        when this scheduler has a fleet identity, so N replicas in one
        process stop colliding on the shared names."""
        self.metrics.incr(name, n)
        get_registry().counter(self.metrics.global_name(name)).inc(n)

    def _beat(self, force: bool = False) -> None:
        """Touch the heartbeat file (throttled to ``heartbeat_interval_s``).

        Atomic tmp + ``os.replace`` against readers, mtime as the clock —
        the ElasticCoordinator pattern.  Write failures are logged and
        swallowed: a full disk must not take down serving, it just makes
        this replica look stale (fail-safe direction)."""
        if self.heartbeat_path is None:
            return
        now = time.monotonic()
        if not force and now - self._last_beat < self._hb_interval:
            return
        self._last_beat = now
        tmp = self.heartbeat_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "replica_id": self.replica_id,
                        "pid": os.getpid(),
                        "tick": self._tick_no,
                    },
                    f,
                )
            os.replace(tmp, self.heartbeat_path)
        except OSError:
            self.logger.exception("heartbeat write failed; continuing")

    def _publish_pool_gauges(self) -> None:
        """Per-replica pool-state gauges in the PROCESS registry: the
        router's placement reads these cross-thread (block utilization
        for load scoring, prefix-hit rate for affinity telemetry), and
        the serve bench surfaces them in its JSON line."""
        reg = get_registry()
        util = self._kv.blocks_in_use / max(self._kv.num_blocks, 1)
        reg.gauge(self.metrics.global_name("block_util")).set(util)
        total = self._hit_blocks + self._miss_blocks
        if total:
            reg.gauge(self.metrics.global_name("prefix_hit_rate")).set(
                self._hit_blocks / total
            )

    # ------------------------------------------------------------------ #
    # KV transfer service (disaggregated serving — serving/disagg.py
    # coordinates; serving/kv_transfer.py is the wire format)

    def _service_kv_transfers(self) -> bool:
        """Run queued export/import verbs on the scheduler thread."""
        did = False
        while True:
            with self._cond:
                if not self._xfer_q:
                    return did
                verb, arg, fut = self._xfer_q.popleft()
            did = True
            try:
                if verb == "export":
                    res = self._export_kv(*arg)
                elif verb == "export_refs":
                    res = self._export_kv_refs(*arg)
                else:
                    res = self._import_kv(arg)
            except Exception as exc:
                # the verb failed, not the engine: the pool was either
                # only read (export) or mutated through invariant-safe
                # adopt/scatter (import) — fail the one future and move on
                if not fut.done():
                    fut.set_exception(exc)
            else:
                if not fut.done():
                    fut.set_result(res)

    def _export_kv(self, prompt, namespace, stall_s):
        payloads = kv_transfer.extract_payloads(
            self._kv, self._pool, prompt, namespace=namespace
        )
        if payloads:
            self._bump("kv_transfer_exported_blocks", len(payloads))
        if stall_s is not None:
            self.logger.warning(
                "fault injection: kv transfer export stalled %.2fs", stall_s
            )
            time.sleep(float(stall_s))
        return payloads

    def _export_kv_refs(self, prompt, namespace, stall_s):
        refs = kv_transfer.extract_block_refs(
            self._kv, self._pool, prompt, namespace=namespace
        )
        if refs:
            self._bump("kv_transfer_exported_blocks", len(refs))
        if stall_s is not None:
            self.logger.warning(
                "fault injection: kv transfer export stalled %.2fs", stall_s
            )
            time.sleep(float(stall_s))
        return refs

    def _import_kv(self, payloads):
        t0 = time.perf_counter()
        accepted = []
        rejected = 0
        nbytes = 0
        for p in payloads:
            if not kv_transfer.verify_payload(p):
                rejected += 1
                self._bump("kv_transfer_rejects")
                self.logger.warning(
                    "kv transfer: checksum reject of block %d — dropping "
                    "the rest of the chain; decode recomputes locally",
                    p.index,
                )
                break
            if self._kv.is_cached(p.key):
                continue
            blk = self._kv.adopt_block(p.key)
            if blk is None:
                break  # pool full even after LRU eviction: partial adopt is fine
            accepted.append((blk, p))
            nbytes += p.nbytes
        if accepted:
            self._pool = kv_transfer.scatter_payloads(
                self._pool, self._kv.num_blocks * self._kv.block_size, accepted
            )
        if accepted or rejected:
            self.metrics.record_kv_transfer(
                nbytes=nbytes,
                seconds=time.perf_counter() - t0,
                blocks=len(accepted),
            )
            reg = get_registry()
            if nbytes:
                reg.counter(
                    self.metrics.global_name("kv_transfer_bytes")
                ).inc(nbytes)
            if accepted:
                reg.counter(
                    self.metrics.global_name("kv_transfer_blocks")
                ).inc(len(accepted))
        return {"accepted": len(accepted), "rejected": rejected, "bytes": nbytes}

    def _expire(self, req: _PagedRequest, now: float) -> bool:
        if req.deadline is None or now < req.deadline:
            return False
        self._bump("timeouts")
        if not req.future.done():
            req.future.set_exception(
                TimeoutError(
                    "serving request exceeded its deadline after "
                    f"{now - req.enqueued_at:.3f}s in queue"
                )
            )
        return True

    def _sweep_expired_locked(self) -> None:
        now = time.monotonic()
        if any(r.deadline is not None and now >= r.deadline for r in self._queue):
            self._queue = deque(
                r for r in self._queue if not self._expire(r, now)
            )

    def _admit(self) -> List[_PagedRequest]:
        """Fill free slots from the queue head (FCFS: a head request the
        pool cannot cover blocks those behind it — no starvation, at the
        cost of head-of-line blocking; counted as ``admission_waits``)."""
        newly: List[_PagedRequest] = []
        with self._cond:
            self._sweep_expired_locked()
            free = [i for i, s in enumerate(self._slots) if s is None]
            # one prefill call per tick: cap admissions at the largest
            # batch bucket so the call stays on the compiled grid
            max_admit = min(len(free), self.batch_buckets[-1])
            while self._queue and len(newly) < max_admit:
                req = self._queue[0]
                # the adapter id namespaces the prefix cache: identical
                # prompts under different adapters have DIFFERENT K/V
                # (cross-tenant reuse would be silent corruption)
                adm = self._kv.admit(
                    req.prompt.tolist(), req.max_new,
                    namespace=req.adapter,
                    extra_blocks=self._extra_blocks,
                )
                if adm is None:
                    self._bump("admission_waits")
                    break
                if self._spec is not None:
                    # all-or-nothing across BOTH pools: holding the target
                    # reservation while waiting on the draft pool could
                    # deadlock two half-admitted requests
                    dadm = self._dkv.admit(req.prompt.tolist(), req.max_new)
                    if dadm is None:
                        self._kv.release(adm)
                        self._bump("admission_waits")
                        break
                    req.draft_admission = dadm
                self._queue.popleft()
                req.admission = adm
                req.slot = free[len(newly)]
                self._slots[req.slot] = req
                newly.append(req)
                self._bump("admitted")
                cacheable = (req.prompt.size - 1) // self._kv.block_size
                self._hit_blocks += adm.n_shared
                self._miss_blocks += cacheable - adm.n_shared
                if adm.n_shared:
                    self._bump("prefix_hit_blocks", adm.n_shared)
                if cacheable - adm.n_shared:
                    self._bump("prefix_miss_blocks", cacheable - adm.n_shared)
        return newly

    def _bucket_for(self, n: int, buckets: Sequence[int], kind: str) -> int:
        for b in buckets:
            if n <= b:
                return b
        raise ValueError(f"{kind} {n} exceeds largest bucket {buckets[-1]}")

    def _table_ids(self, req: _PagedRequest) -> List[int]:
        """The request's LOGICAL block table: the admission's footprint
        blocks in order.  In speculative mode the admission carries one
        extra trailing block — the private spare — which is never in the
        table; the verify step reaches it through the branch table and
        commit swaps it in (swapping entries inside ``block_ids`` keeps
        release/refcount accounting exact)."""
        ids = req.admission.block_ids
        if self._extra_blocks:
            return ids[: len(ids) - self._extra_blocks]
        return ids

    def _prefill(self, newly: List[_PagedRequest]) -> None:
        """Prefill every request admitted this tick.

        Fresh requests (no tokens yet) go through one bucketed batch
        call; requests re-admitted by a hot-restart carry their delivered
        token stream and take the replay path instead.
        """
        replay = [r for r in newly if r.tokens]
        fresh = [r for r in newly if not r.tokens]
        if fresh:
            self._prefill_fresh(fresh)
        if replay:
            self._replay(replay)
        if self._spec is not None:
            # the draft pool needs the prompt K/V too (its own programs,
            # its own blocks); requests evicted by the target prefill's
            # output guard have already released both reservations
            live = [r for r in newly if r.admission is not None]
            if live:
                self._draft_prefill(live)

    def _prefill_fresh(self, newly: List[_PagedRequest]) -> None:
        """One bucketed prefill over the fresh admissions of this tick.

        Prefix-cache hits shorten the device work directly: only the
        SUFFIX past ``cached_len`` is fed (positions ``cached_len ..
        prompt_len-1``), padded up to a seq bucket.
        """
        t0 = time.perf_counter()
        suffix = [r.prompt.size - r.admission.cached_len for r in newly]
        bb = self._bucket_for(len(newly), self.batch_buckets, "admitted rows")
        sb = self._bucket_for(max(suffix), self.seq_buckets, "prefill suffix")
        tokens = np.zeros((bb, sb), np.int32)
        positions = np.full((bb, sb), -1, np.int32)
        tables = np.zeros((bb, self.table_blocks), np.int32)
        last_col = np.zeros((bb,), np.int32)
        aids = np.full((bb,), -1, np.int32)
        keys = [self._pad_key] * bb
        for i, req in enumerate(newly):
            cl = req.admission.cached_len
            tokens[i, : suffix[i]] = req.prompt[cl:]
            positions[i, : suffix[i]] = np.arange(cl, req.prompt.size)
            ids = self._table_ids(req)
            tables[i, : len(ids)] = ids
            last_col[i] = suffix[i] - 1
            aids[i] = req.adapter
            keys[i] = req.row_key
        tok, finite, self._pool = self._fns.prefill(
            self.params, self._pool, tokens, positions, tables,
            last_col, jnp.stack(keys), np.zeros((bb,), np.int32), aids,
        )
        rb0 = time.perf_counter()
        tok = np.asarray(tok)
        finite = np.asarray(finite)
        t1 = time.perf_counter()
        self._tick_block_s += t1 - rb0
        for i, req in enumerate(newly):
            if not finite[i]:
                # output guard: this prompt produced non-finite logits —
                # evict it (and keep its blocks out of the prefix cache)
                self._evict_poisoned(
                    req, cause=None, trigger="non-finite prefill logits"
                )
                continue
            # blocks are filled now — publish them for future prefix hits
            # BEFORE this request can retire and release them
            self._kv.register_prefix(
                req.prompt.tolist(), req.admission, namespace=req.adapter
            )
            self._push_token(req, int(tok[i]))
        self.metrics.record_prefill(
            prompt_tokens=int(sum(suffix)), n_requests=len(newly),
            prefill_s=t1 - t0,
        )

    def _draft_prefill(self, reqs: List[_PagedRequest]) -> None:
        """Scatter each admitted request's FULL prompt K/V into the draft
        pool (speculative mode).  Always the whole prompt — the draft pool
        runs without a prefix cache, so the target's cache hits cannot
        shorten this call.  The sampled token is discarded (draft rounds
        start from the COMMITTED stream) and the keys are the pad key:
        the draft is always greedy.

        Replayed (hot-restart) requests get the same treatment: their
        generated tokens' draft K/V is NOT rebuilt — those rows read as
        zeros, which can only depress the acceptance rate, never change
        the committed stream (every emitted token is the target's).
        """
        bb = self._bucket_for(len(reqs), self.batch_buckets, "draft rows")
        sb = self._bucket_for(
            max(r.prompt.size for r in reqs), self.seq_buckets, "draft prompt"
        )
        tokens = np.zeros((bb, sb), np.int32)
        positions = np.full((bb, sb), -1, np.int32)
        tables = np.zeros((bb, self.table_blocks), np.int32)
        last_col = np.zeros((bb,), np.int32)
        aids = np.full((bb,), -1, np.int32)
        for i, req in enumerate(reqs):
            n = req.prompt.size
            tokens[i, :n] = req.prompt
            positions[i, :n] = np.arange(n)
            dids = req.draft_admission.block_ids
            tables[i, : len(dids)] = dids
            last_col[i] = n - 1
            aids[i] = req.adapter if self._draft_lora else -1
        keys = jnp.stack([self._pad_key] * bb)
        _tok, _finite, self._draft_pool = self._draft_fns.prefill(
            self._draft_params, self._draft_pool, tokens, positions, tables,
            last_col, keys, np.zeros((bb,), np.int32), aids,
        )

    def _replay(self, reqs: List[_PagedRequest]) -> None:
        """Rebuild restart-surviving requests' KV state bit-exactly.

        Prompt K/V comes back through the bucketed prefill (prefix-cache
        hits shorten it exactly like a fresh admission); the already-
        delivered generated tokens are then re-fed through the SAME
        decode program that produced them.  Per-row per-token-index
        sampling keys make every resampled token bitwise identical to
        the stored stream — verified per token, never re-delivered
        (clients already hold these tokens; ``on_token`` does not refire).
        """
        suffix = [r.prompt.size - r.admission.cached_len for r in reqs]
        bb = self._bucket_for(len(reqs), self.batch_buckets, "replayed rows")
        sb = self._bucket_for(max(suffix), self.seq_buckets, "replay suffix")
        tokens = np.zeros((bb, sb), np.int32)
        positions = np.full((bb, sb), -1, np.int32)
        tables = np.zeros((bb, self.table_blocks), np.int32)
        last_col = np.zeros((bb,), np.int32)
        aids = np.full((bb,), -1, np.int32)
        keys = [self._pad_key] * bb
        for i, req in enumerate(reqs):
            cl = req.admission.cached_len
            tokens[i, : suffix[i]] = req.prompt[cl:]
            positions[i, : suffix[i]] = np.arange(cl, req.prompt.size)
            ids = self._table_ids(req)
            tables[i, : len(ids)] = ids
            last_col[i] = suffix[i] - 1
            aids[i] = req.adapter
            keys[i] = req.row_key
        tok, finite, self._pool = self._fns.prefill(
            self.params, self._pool, tokens, positions, tables,
            last_col, jnp.stack(keys), np.zeros((bb,), np.int32), aids,
        )
        tok = np.asarray(tok)
        finite = np.asarray(finite)
        live: List[_PagedRequest] = []
        for i, req in enumerate(reqs):
            if not finite[i]:
                self._evict_poisoned(
                    req, cause=None, trigger="non-finite replay prefill logits"
                )
                continue
            self._kv.register_prefix(
                req.prompt.tolist(), req.admission, namespace=req.adapter
            )
            self._verify_replay(req, 0, int(tok[i]))
            live.append(req)
        # feed generated tokens 0..K-2 back through the decode program,
        # re-verifying tokens 1..K-1 — identical per-row inputs through
        # the identical program reproduce the original run's writes
        max_gen = max((r.gen_idx for r in live), default=0)
        for k in range(1, max_gen):
            step_reqs = [r for r in live if r.gen_idx > k]
            if not step_reqs:
                break
            W = self.slots_n
            prev = np.zeros((W,), np.int32)
            pos = np.full((W,), -1, np.int32)
            tables = np.zeros((W, self.table_blocks), np.int32)
            gi = np.zeros((W,), np.int32)
            aids = np.full((W,), -1, np.int32)
            keys = [self._pad_key] * W
            for req in step_reqs:
                i = req.slot
                prev[i] = req.tokens[k - 1]
                pos[i] = req.prompt.size + k - 1
                ids = self._table_ids(req)
                tables[i, : len(ids)] = ids
                gi[i] = k
                aids[i] = req.adapter
                keys[i] = req.row_key
            tok, finite, self._pool = self._fns.decode_step(
                self._qparams if self._quant else self.params,
                self._pool, prev, pos, tables, jnp.stack(keys), gi, aids,
            )
            tok = np.asarray(tok)
            finite = np.asarray(finite)
            for req in step_reqs:
                if not finite[req.slot]:
                    self._evict_poisoned(
                        req, cause=None,
                        trigger="non-finite replay decode logits",
                    )
                    live.remove(req)
                    continue
                self._verify_replay(req, k, int(tok[req.slot]))
        for req in live:
            self._bump("replayed_tokens", req.gen_idx)

    def _verify_replay(self, req: _PagedRequest, idx: int, tok: int) -> None:
        """Replay parity check: the resample must equal what the client
        already received.  A mismatch is counted and logged but the
        DELIVERED stream stays authoritative."""
        if tok != req.tokens[idx]:
            self._bump("replay_parity_mismatch")
            self.logger.error(
                "replay divergence: slot %d generated token %d resampled as "
                "%d but %d was delivered (keeping the delivered stream)",
                req.slot, idx, tok, req.tokens[idx],
            )

    # ------------------------------------------------------------------ #
    # fault injection (engine/fault.py serve_* kinds) — consulted once per
    # tick, after admissions so the slot targets exist

    def _consult_injector(self) -> None:
        inj = fault.get_injector()
        if not inj.active:
            return
        t = self._tick_no
        sec = inj.take("serve_hang", t)
        if sec is not None:
            fault.bump("injected_serve_hangs")
            self.logger.warning(
                "fault injection: hanging tick %d for %.2fs", t, sec
            )
            time.sleep(sec)
        slot = inj.take("serve_raise", t)
        if slot is not None:
            req = self._slot_target(int(slot), "serve_raise")
            if req is not None:
                fault.bump("injected_serve_raises")
                req.poison = "raise"
        slot = inj.take("serve_nan", t)
        if slot is not None:
            req = self._slot_target(int(slot), "serve_nan")
            if req is not None:
                fault.bump("injected_serve_nans")
                self._corrupt_pool_rows(req)
        if inj.take("serve_device_lost", t) is not None:
            fault.bump("injected_serve_device_lost")
            raise fault.DeviceLostError(
                f"injected device loss at serving tick {t}"
            )

    def _slot_target(self, slot: int, kind: str) -> Optional[_PagedRequest]:
        req = self._slots[slot] if 0 <= slot < self.slots_n else None
        if req is None:
            self.logger.warning(
                "fault injection: %s@%d targets empty slot %d; dropped",
                kind, self._tick_no, slot,
            )
        return req

    def _corrupt_pool_rows(self, req: _PagedRequest) -> None:
        """NaN the KEY-pool row of ``req``'s last WRITTEN position.

        That position's block sits past the prefix-cache registration cap
        ((prompt_len-1)//block_size), so it is exclusively owned — the
        poison is per-request by construction.  Only ``k_pool`` rows are
        corrupted: a NaN key makes the OWNER's attention logits NaN
        (position is live for it) while every other reader — including a
        later request recycling the freed block — masks it to -inf before
        the softmax.  A NaN VALUE row would leak through recycling: masked
        positions get exactly-zero softmax weight, and 0 * NaN is NaN in
        the value contraction.
        """
        bs = self._kv.block_size
        p = req.prompt.size + max(req.gen_idx, 1) - 2
        row = req.admission.block_ids[p // bs] * bs + p % bs
        n_rows = self._kv.num_blocks * bs

        def corrupt(path, leaf):
            names = {
                str(getattr(part, "key", getattr(part, "name", "")))
                for part in path
            }
            if (
                "k_pool" in names
                and hasattr(leaf, "ndim") and leaf.ndim >= 1
                and leaf.shape[0] == n_rows
                and jnp.issubdtype(leaf.dtype, jnp.floating)
            ):
                return leaf.at[row].set(jnp.nan)
            return leaf

        self._pool = jax.tree_util.tree_map_with_path(corrupt, self._pool)

    # ------------------------------------------------------------------ #
    # decode

    def _decode_arrays(self, reqs: List[_PagedRequest]):
        """Fixed-width decode inputs with ``reqs`` live and every other
        slot riding along at position -1."""
        W = self.slots_n
        prev = np.zeros((W,), np.int32)
        pos = np.full((W,), -1, np.int32)
        tables = np.zeros((W, self.table_blocks), np.int32)
        gen_idx = np.zeros((W,), np.int32)
        aids = np.full((W,), -1, np.int32)
        keys = [self._pad_key] * W
        for req in reqs:
            i = req.slot
            prev[i] = req.tokens[-1]
            # prev = generated token gen_idx-1 at global position
            # prompt_len + gen_idx - 1; feeding it samples token gen_idx
            pos[i] = req.prompt.size + req.gen_idx - 1
            ids = self._table_ids(req)
            tables[i, : len(ids)] = ids
            gen_idx[i] = req.gen_idx
            aids[i] = req.adapter
            keys[i] = req.row_key
        return prev, pos, tables, gen_idx, aids, keys

    def _poison_shim(self, reqs: List[_PagedRequest]) -> None:
        """Injected per-request dispatch failure (``serve_raise``).  The
        message deliberately names no slot: attribution is the
        supervisor's bisect's job."""
        for req in reqs:
            if req.poison == "raise":
                raise fault.FaultInjectionError(
                    f"injected decode-dispatch failure (tick {self._tick_no})"
                )

    def _decode_step(self) -> None:
        """One single-token step for every occupied slot."""
        t0 = time.perf_counter()
        active = [req for req in self._slots if req is not None]
        self._poison_shim(active)
        prev, pos, tables, gen_idx, aids, keys = self._decode_arrays(active)
        n_active = len(active)
        self._note_dispatch_gap()
        # the span marks this tick as PRODUCTIVE serving work — the
        # serve-side MTTR endpoint (telemetry/slo.py pairs it with the
        # preceding poison_bisect/serving_restart recovery span)
        with span("decode_step", step=self._tick_no, active=n_active):
            tok, finite, self._pool = self._fns.decode_step(
                self._qparams if self._quant else self.params,
                self._pool, prev, pos, tables,
                jnp.stack(keys), gen_idx, aids,
            )
        rb0 = time.perf_counter()
        tok = np.asarray(tok)
        finite = np.asarray(finite)
        t1 = time.perf_counter()
        self._tick_block_s += t1 - rb0
        for req in active:
            if not finite[req.slot]:
                # on-device output guard: evict the NaN emitter, every
                # other row's logits are untouched (disjoint block tables)
                self._evict_poisoned(
                    req, cause=None, trigger="non-finite decode logits"
                )
                continue
            self._push_token(req, int(tok[req.slot]))
        self.metrics.record_decode(n_tokens=n_active, decode_s=t1 - t0)
        self.metrics.record_iteration(
            active_slots=n_active, total_slots=self.slots_n,
            blocks_in_use=self._kv.blocks_in_use,
            total_blocks=self._kv.num_blocks,
        )

    def _decode_probe(self, reqs: List[_PagedRequest]) -> None:
        """Re-drive the decode dispatch for a SUBSET of the active slots —
        the supervisor's bisect primitive.  Inputs are identical to the
        failed step's, so the pool scatter is idempotent and sampling is
        pure: probing commits nothing the real step would not."""
        self._poison_shim(reqs)
        prev, pos, tables, gen_idx, aids, keys = self._decode_arrays(reqs)
        tok, _, self._pool = self._fns.decode_step(
            self._qparams if self._quant else self.params,
            self._pool, prev, pos, tables,
            jnp.stack(keys), gen_idx, aids,
        )
        # surface async dispatch errors here, inside the probe's try
        jax.block_until_ready(tok)

    # ------------------------------------------------------------------ #
    # async decode pipeline (serving.scheduler.async_depth > 0)

    def _note_dispatch_gap(self) -> None:
        """Record the host-side gap between consecutive decode dispatch
        enqueues — the number the pipeline exists to shrink.  Only gaps
        between BACK-TO-BACK decode ticks count: an idle queue between
        two dispatches is not host overhead."""
        now = time.perf_counter()
        if (
            self._last_dispatch is not None
            and self._tick_no - self._last_dispatch[0] <= 1
        ):
            self.metrics.record_dispatch_gap(
                (now - self._last_dispatch[1]) * 1000.0
            )
        self._last_dispatch = (self._tick_no, now)

    def _decode_step_async(self) -> None:
        """Pipelined decode: dispatch step *k* without waiting for step
        *k-1*'s host readback.

        The sampled-token carry stays ON DEVICE — ``decode_step_fed``
        feeds its own token output back as the next ``prev_tok``, with
        rows the host just (re)filled spliced in via ``fresh_mask`` — and
        a ring of up to ``async_depth`` dispatched steps drains one tick
        behind dispatch.  Host state stays exact without the tokens: the
        per-request ``dispatched`` counter derives every position and
        sampling index, so the drained stream is bitwise identical to
        the sync path's (same per-row fold_in keys, same per-row pool
        writes in the same order).

        Lag consequences, all bounded by ``async_depth``: retire/refill
        and the NaN output guard observe tokens late, so a row can
        execute past EOS — never past ``max_new`` (the dispatch cap is
        host-exact) — and those overrun writes land at positions
        ``<= prompt_len + max_new - 2``, inside the row's reserved
        footprint; the sampled overrun tokens are discarded at drain
        because the request has already retired (``admission is None``),
        and once its blocks recycle, any stale overrun rows are masked
        exactly like every other recycled-block row.
        """
        active = [req for req in self._slots if req is not None]
        self._poison_shim(active)
        # host-exact dispatch cap: a row never dispatches past its token
        # budget, so only EOS (host-unknown until drain) can overrun
        disp = [r for r in active if r.dispatched < r.max_new]
        if disp:
            W = self.slots_n
            fresh_mask = np.zeros((W,), bool)
            fresh_tok = np.zeros((W,), np.int32)
            pos = np.full((W,), -1, np.int32)
            tables = np.zeros((W, self.table_blocks), np.int32)
            gen_idx = np.zeros((W,), np.int32)
            aids = np.full((W,), -1, np.int32)
            keys = [self._pad_key] * W
            rows = []
            for req in disp:
                i = req.slot
                d = req.dispatched
                if d == req.gen_idx:
                    # nothing of this row is in flight: its last token is
                    # host-known (fresh prefill, refill, or post-recovery
                    # rollback) and overrides the stale carry in-graph
                    fresh_mask[i] = True
                    fresh_tok[i] = req.tokens[-1]
                pos[i] = req.prompt.size + d - 1
                ids = self._table_ids(req)
                tables[i, : len(ids)] = ids
                gen_idx[i] = d
                aids[i] = req.adapter
                keys[i] = req.row_key
                rows.append((req, i, d))
            prev = self._carry_tok
            if prev is None:
                # first dispatch of a pipeline run: every dispatched row
                # is fresh by construction, the zeros are never sampled
                prev = self._zero_carry()
            self._note_dispatch_gap()
            with span("decode_step", step=self._tick_no, active=len(disp)):
                tok, finite, self._pool = self._fns.decode_step_fed(
                    self._qparams if self._quant else self.params,
                    self._pool, prev, fresh_mask, fresh_tok, pos, tables,
                    jnp.stack(keys), gen_idx, aids,
                )
            for req in disp:
                req.dispatched += 1
            self._carry_tok = tok
            self._inflight.append((tok, finite, rows))
            self.metrics.record_iteration(
                active_slots=len(disp), total_slots=self.slots_n,
                blocks_in_use=self._kv.blocks_in_use,
                total_blocks=self._kv.num_blocks,
            )
        # drain one tick behind dispatch (ring bounded at async_depth);
        # when nothing is left to dispatch, drain EVERYTHING so the
        # endgame cannot strand determined tokens in flight
        target = self._async_depth if disp else 0
        pushed = 0
        t0 = time.perf_counter()
        while len(self._inflight) > target:
            pushed += self._drain_entry(self._inflight.popleft())
        t1 = time.perf_counter()
        self._tick_block_s += t1 - t0
        if pushed:
            self.metrics.record_decode(n_tokens=pushed, decode_s=t1 - t0)

    def _zero_carry(self):
        """A mesh-replicated, COMMITTED int32[slots] zeros vector whose
        sharding matches ``decode_step_fed``'s token output.

        The jit cache keys on input shardings: feeding an uncommitted
        ``jnp.zeros`` as the first carry and the committed program output
        as every later one would compile the SAME program twice (one
        re-layout entry).  Matching the output's replicated NamedSharding
        up front keeps the async path at exactly one compiled program —
        the compile-count pin the tests hold."""
        z = jnp.zeros((self.slots_n,), jnp.int32)
        leaf_sh = getattr(
            jax.tree_util.tree_leaves(self.params)[0], "sharding", None
        )
        if isinstance(leaf_sh, jax.sharding.NamedSharding):
            z = jax.device_put(
                z,
                jax.sharding.NamedSharding(
                    leaf_sh.mesh, jax.sharding.PartitionSpec()
                ),
            )
        return z

    def _drain_entry(self, entry) -> int:
        """Materialize one ring entry's host readback and apply it.

        Rows whose request already left its slot (EOS overrun after a
        lagged retire, poison eviction, hot-restart requeue) or whose
        host stream was rolled back since dispatch are discarded — their
        token was never part of the committed stream.  Returns the
        number of tokens pushed."""
        tok_dev, finite_dev, rows = entry
        tok = np.asarray(tok_dev)
        finite = np.asarray(finite_dev)
        pushed = 0
        for req, slot, idx in rows:
            if req.admission is None or idx != req.gen_idx:
                continue
            if not finite[slot]:
                # the on-device output guard, observed async_depth ticks
                # late: the emitter's own table re-reads its NaN rows
                # every overrun step, so the flag stays false and the
                # eviction lands on exactly this request
                self._evict_poisoned(
                    req, cause=None, trigger="non-finite decode logits"
                )
                continue
            self._push_token(req, int(tok[slot]))
            pushed += 1
        return pushed

    def flush_async(self) -> None:
        """Drain what the in-flight ring can still deliver, discard the
        rest, and roll every live row's dispatch counter back to its
        host-known stream.

        ``tick`` calls this on any failure BEFORE invoking the
        supervisor: probes and replays assume sync-equivalent host state
        (``_decode_probe`` re-dispatches from ``tokens[-1]``), and
        attribution must not blame a request for a step that was merely
        in flight when an unrelated row poisoned the tick.  Runs on the
        tick thread only.  Discarded steps cost nothing —
        re-dispatching them reproduces the same tokens and the same
        idempotent pool writes.  No-op in sync mode (the ring is empty).
        """
        while self._inflight:
            entry = self._inflight.popleft()
            try:
                self._drain_entry(entry)
            except Exception:
                # the device state behind the remaining entries is part
                # of the same failure — discard, the rollback below makes
                # re-dispatch exact
                self.logger.warning(
                    "async ring drain failed mid-recovery; discarding %d "
                    "remaining in-flight step(s)", len(self._inflight),
                )
                self._inflight.clear()
                break
        self._carry_tok = None
        self._last_dispatch = None
        for req in self._slots:
            if req is not None:
                req.dispatched = req.gen_idx

    # ------------------------------------------------------------------ #
    # speculative decoding (serving/speculative.py)

    def _spec_decode_step(self) -> None:
        """One speculative round for every occupied slot, replacing the
        single-token decode step: k+1 greedy draft steps on the draft
        pool (the last a pure K/V backfill of the final proposal), one
        batched ``verify`` on FORKED block tables, exact host-side
        accept/reject, then commit-by-swap.  Emits 1..k+1 tokens per live
        request; the committed stream is token-identical to plain greedy
        decode (the parity oracle) because every emitted token is the
        TARGET's argmax — the draft only decides how many of them one
        target forward amortizes.

        Fork mechanics: the round's verify writes positions ``P..P+ke``
        (``P`` = the last committed token's position).  Positions beyond
        block ``bi = P // block_size`` land in footprint blocks that hold
        no committed data yet, so they need no protection; block ``bi``
        DOES hold committed rows ``[bi*bs, P)``, so those are CoW-copied
        into the request's private spare block and the verify runs on a
        branch table with ``table[bi] := spare``.  On commit the spare
        becomes the real block (swap inside ``block_ids`` — refcount
        accounting unchanged); the old block becomes the next round's
        spare, pristine until then (rollback-safety).  Rows a REJECTED
        proposal wrote past the commit point are harmless: every verify
        scatters all its columns before it gathers, so any position a
        later round can read is rewritten by that round first, and
        positions past its coverage are causally masked.
        """
        t0 = time.perf_counter()
        active = [req for req in self._slots if req is not None]
        self._poison_shim(active)
        W = self.slots_n
        k = self._spec.k
        bs = self._kv.block_size
        # clamp each row's proposal count to its remaining budget so no
        # verify write can land past the reserved footprint
        k_eff = {r.slot: min(k, r.max_new - r.gen_idx) for r in active}

        with span("decode_step", step=self._tick_no, active=len(active)):
            # -- draft: k+1 greedy single-token steps (step j feeds the
            # committed tail for j=0, else proposal j-1, at position
            # P+j, producing proposal j).  Step k_eff is a pure K/V
            # BACKFILL: it feeds the final proposal so its position is
            # written to the draft pool (the sample is discarded) —
            # without it that position would stay stale forever once the
            # proposal commits, and even a self-draft would drift off the
            # target (acceptance < 1 for no reason) ---------------------
            draft_tok = np.zeros((W, k), np.int32)
            pad_keys = jnp.stack([self._pad_key] * W)
            for j in range(k + 1):
                prev = np.zeros((W,), np.int32)
                pos = np.full((W,), -1, np.int32)
                dtables = np.zeros((W, self.table_blocks), np.int32)
                gi = np.zeros((W,), np.int32)
                aids = np.full((W,), -1, np.int32)
                any_row = False
                for req in active:
                    i = req.slot
                    if j > k_eff[i]:
                        continue
                    any_row = True
                    prev[i] = req.tokens[-1] if j == 0 else draft_tok[i, j - 1]
                    pos[i] = req.prompt.size + req.gen_idx - 1 + j
                    dids = req.draft_admission.block_ids
                    dtables[i, : len(dids)] = dids
                    gi[i] = req.gen_idx + j
                    if self._draft_lora:
                        aids[i] = req.adapter
                if not any_row:
                    break
                tok, _, self._draft_pool = self._draft_fns.decode_step(
                    self._draft_params, self._draft_pool, prev, pos, dtables,
                    pad_keys, gi, aids,
                )
                if j < k:
                    draft_tok[:, j] = np.asarray(tok)

            # -- fork + verify: one batched target forward over
            # [committed tail, proposals...] on branch tables ----------
            pool_rows = self._kv.num_blocks * bs
            src = np.full((W, bs), pool_rows, np.int32)  # OOB rows drop
            dst = np.full((W, bs), pool_rows, np.int32)
            ver_tok = np.zeros((W, k + 1), np.int32)
            ver_pos = np.full((W, k + 1), -1, np.int32)
            vtables = np.zeros((W, self.table_blocks), np.int32)
            aids = np.full((W,), -1, np.int32)
            offs = np.arange(bs)
            for req in active:
                i = req.slot
                ke = k_eff[i]
                P = req.prompt.size + req.gen_idx - 1
                bi = P // bs
                ids = self._table_ids(req)
                spare = req.admission.block_ids[-1]
                off = P % bs
                if off:
                    src[i, :off] = ids[bi] * bs + offs[:off]
                    dst[i, :off] = spare * bs + offs[:off]
                ver_tok[i, 0] = req.tokens[-1]
                ver_tok[i, 1 : 1 + ke] = draft_tok[i, :ke]
                ver_pos[i, : ke + 1] = np.arange(P, P + ke + 1)
                vtables[i, : len(ids)] = ids
                vtables[i, bi] = spare
                aids[i] = req.adapter
            self._pool = self._fns.copy_rows(
                self._pool, src.reshape(-1), dst.reshape(-1)
            )
            # verify ALWAYS takes the plain tree, quant mode included:
            # the target's scoring is the accuracy anchor
            logits, self._pool = self._fns.verify(
                self.params, self._pool, ver_tok, ver_pos, vtables, aids,
            )
            rb0 = time.perf_counter()
            logits = np.asarray(logits)
            self._tick_block_s += time.perf_counter() - rb0

        # -- host accept/reject + commit -------------------------------
        t1 = time.perf_counter()
        emitted_total = proposed = accepted = 0
        for req in active:
            i = req.slot
            ke = k_eff[i]
            if not np.isfinite(logits[i, : ke + 1]).all():
                self._evict_poisoned(
                    req, cause=None, trigger="non-finite verify logits"
                )
                continue
            target = logits[i, : ke + 1].argmax(-1).astype(np.int32)
            n_acc, emit = greedy_accept(draft_tok[i, :ke], target)
            if n_acc == ke and req.gen_idx + len(emit) > req.max_new:
                emit = emit[:-1]  # no room for the bonus under the cap
            proposed += ke
            accepted += n_acc
            # commit-by-swap: the branch boundary block becomes real, the
            # displaced block becomes the next round's pristine spare
            P = req.prompt.size + req.gen_idx - 1
            bi = P // bs
            ids = req.admission.block_ids
            ids[bi], ids[-1] = ids[-1], ids[bi]
            for t in emit:
                self._push_token(req, int(t))
                emitted_total += 1
                if req.admission is None:
                    break  # retired (eos / cap) mid-round
        self._bump("spec_rounds")
        if proposed:
            self._bump("spec_proposed", proposed)
        if accepted:
            self._bump("spec_accepted", accepted)
        self.metrics.record_decode(n_tokens=emitted_total, decode_s=t1 - t0)
        self.metrics.record_iteration(
            active_slots=len(active), total_slots=self.slots_n,
            blocks_in_use=self._kv.blocks_in_use,
            total_blocks=self._kv.num_blocks,
        )

    # ------------------------------------------------------------------ #
    # retirement and recovery

    def _push_token(self, req: _PagedRequest, tok: int) -> None:
        req.tokens.append(tok)
        if req.dispatched < len(req.tokens):
            req.dispatched = len(req.tokens)
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:  # a client callback must not kill the loop
                self.logger.exception("on_token callback raised; ignoring")
        if (self.eos_id is not None and tok == self.eos_id) or (
            req.gen_idx >= req.max_new
        ):
            self._retire(req)

    def _release_draft(self, req: _PagedRequest) -> None:
        if req.draft_admission is not None:
            self._dkv.release(req.draft_admission)
            req.draft_admission = None

    def _retire(self, req: _PagedRequest) -> None:
        self._slots[req.slot] = None
        self._kv.release(req.admission)
        req.admission = None
        self._release_draft(req)
        if not req.future.done():
            req.future.set_result(
                {
                    "tokens": np.asarray(req.tokens, np.int32),
                    "gen_len": len(req.tokens),
                }
            )
        self._bump("retired")
        self.metrics.record_request(
            req.enqueued_at, gen_len=len(req.tokens),
            adapter=req.adapter_name,
        )
        if self._kv.prefix_evictions:
            # drain the pool's eviction tally into the ledger (the pool
            # itself is metrics-free bookkeeping)
            self._bump("prefix_evictions", self._kv.prefix_evictions)
            self._kv.prefix_evictions = 0

    def _evict_poisoned(
        self, req: _PagedRequest, *, cause: Optional[BaseException],
        trigger: str,
    ) -> None:
        """Fail ONE request with a diagnosed :class:`PoisonedRequestError`
        and free its reservation; every other slot keeps decoding."""
        err = PoisonedRequestError(
            f"request in slot {req.slot} poisoned the engine at tick "
            f"{self._tick_no} ({trigger}) after {req.gen_idx} generated "
            "tokens"
        )
        err.__cause__ = cause
        self._slots[req.slot] = None
        self._kv.release(req.admission)
        req.admission = None
        self._release_draft(req)
        if not req.future.done():
            req.future.set_exception(err)
        self._bump("requests_poisoned")
        self.logger.error("%s", err)

    def _die(self, exc: BaseException) -> None:
        """Process a :meth:`hard_kill` on the scheduler thread: fail every
        queued and in-flight request with the replica-level error and
        close.  The router's done-callbacks see the error, classify it as
        replica loss, and fail the requests over to a survivor."""
        self.logger.error("replica hard-killed: %s", exc)
        self._bump("replica_down")
        # flags first: once _dead is visible, export/import verbs refuse
        # new work, so the _fail_inflight drain below cannot race a KV
        # transfer into a queue nobody will ever service again
        with self._cond:
            self._die_exc = None
            self._dead = True
            self._closed = True
            self._cond.notify_all()
        self._fail_inflight(exc)

    def _fail_inflight(self, exc: BaseException) -> None:
        """A device error poisons every in-flight request (their pool
        state is unknown); queued requests are failed too rather than
        retried into the same error."""
        # in-flight async steps die with the requests they belong to
        self._inflight.clear()
        self._carry_tok = None
        with self._cond:
            doomed = [s for s in self._slots if s is not None]
            doomed.extend(self._queue)
            self._queue.clear()
            self._slots = [None] * self.slots_n
            doomed_xfer = list(self._xfer_q)
            self._xfer_q.clear()
        # pending KV transfers die with the engine state they index; the
        # disagg coordinator catches the failure and degrades to local
        # recompute — a transfer error never fails a serving request
        for _verb, _arg, xfut in doomed_xfer:
            if not xfut.done():
                xfut.set_exception(exc)
        for req in doomed:
            if req.admission is not None:
                self._kv.release(req.admission)
                req.admission = None
            self._release_draft(req)
            if not req.future.done():
                req.future.set_exception(exc)
        if doomed:
            self._bump("failed_inflight", len(doomed))

    def _rebuild_and_requeue(self) -> None:
        """Hot-restart: rebuild the compiled programs and the pool, then
        push every in-flight request back onto the queue head (FCFS order
        preserved) for replay admission.  Queued requests ride along
        untouched.  Runs on the scheduler thread (inside tick's except)."""
        # the ring indexes the dead pool/programs: discard it outright
        # (the requeued requests replay their host-known streams, and the
        # discarded steps' tokens were never delivered)
        self._inflight.clear()
        self._carry_tok = None
        self._last_dispatch = None
        with self._cond:
            inflight = [s for s in self._slots if s is not None]
            self._slots = [None] * self.slots_n
            for req in reversed(inflight):
                # the reservation indexes the DEAD pool: drop it without
                # release — allocator and prefix cache are rebuilt below
                req.admission = None
                req.draft_admission = None
                req.slot = -1
                req.dispatched = req.gen_idx
                self._queue.appendleft(req)
        self._fns = build_paged_fns(
            self._model, self._block_size, self._num_blocks,
            temperature=self._temperature, quant=self._quant,
        )
        self._kv = PagedKVPool(
            self._num_blocks, self._block_size, self._prefix_cache
        )
        self._pool = self._fns.init_pool(self.params)
        if self._pool_sharding is not None:
            self._pool = jax.device_put(self._pool, self._pool_sharding)
        if self._spec is not None:
            # the draft side restarts with the target: fresh programs,
            # fresh pool, fresh allocator (requests re-prefill both)
            self._build_draft()
        if self._watchdog is not None:
            # the rebuilt programs recompile on first use — re-enter
            # warmup or the compile stall reads as another hang
            self._watchdog.reset()

    def _build_draft(self) -> None:
        """(Re)build the speculative draft side: its own compiled program
        set over its OWN paged pool (prefix cache off — draft K/V and
        target K/V must never share rows, and draft blocks are private to
        their request).  The draft is always greedy regardless of the
        engine temperature; speculative mode itself requires greedy."""
        self._draft_fns = build_paged_fns(
            self._draft_model, self._block_size, self._num_blocks,
            temperature=0.0,
        )
        self._dkv = PagedKVPool(
            self._num_blocks, self._block_size, prefix_cache=False
        )
        self._draft_pool = self._draft_fns.init_pool(self._draft_params)
        if self._pool_sharding is not None:
            self._draft_pool = jax.device_put(
                self._draft_pool, self._pool_sharding
            )

    def _on_tick_hang(self, step: int, elapsed: float, limit: float) -> None:
        # runs on the watchdog monitor thread: record the diagnosis; the
        # scheduler thread raises HungTickError when the tick returns
        with self._cond:
            self._hang_info = (int(step), float(elapsed), float(limit))
        self._bump("serve_watchdog_fires")

    # ------------------------------------------------------------------ #

    def _next_wakeup_locked(self) -> float:
        """Sleep bound while head-of-line blocked: wake for the nearest
        queued (or drain) deadline, else poll the pool at 50 ms."""
        now = time.monotonic()
        deadlines = [r.deadline for r in self._queue if r.deadline is not None]
        if self._draining and self._drain_deadline is not None:
            deadlines.append(self._drain_deadline)
        if not deadlines:
            return 0.05
        return min(0.05, max(min(deadlines) - now, 0.001))

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not (
                    self._closed
                    or self._die_exc is not None
                    or self._hang_sec is not None
                    or self._queue
                    or self._xfer_q
                    or any(s is not None for s in self._slots)
                ):
                    if self.heartbeat_path is None:
                        self._cond.wait()
                    else:
                        # bounded wait so an IDLE healthy replica keeps
                        # beating — external staleness must mean "wedged",
                        # never "merely quiet"
                        self._cond.wait(
                            timeout=max(self._hb_interval / 2.0, 0.01)
                        )
                        self._beat()
                if (
                    self._closed
                    and not self._queue
                    and not self._xfer_q
                    and all(s is None for s in self._slots)
                ):
                    return
            try:
                did = self.tick()
            except BaseException as exc:  # supervisor itself failed
                self.logger.exception("scheduler tick failed beyond recovery")
                self._fail_inflight(exc)
                did = True
            with self._cond:
                self._cond.notify_all()  # drain()/close() watchers
                if not did and not self._closed and self._queue:
                    # head-of-line blocked on pool admission with nothing
                    # decoding: sleep until a deadline can expire or the
                    # state changes instead of spinning on admit attempts
                    # (this is also what guarantees an admission-waiting
                    # request is swept AT its deadline, not at the next
                    # submit)
                    self._cond.wait(timeout=self._next_wakeup_locked())
