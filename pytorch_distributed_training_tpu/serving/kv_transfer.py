"""Host-staged KV-block transfer protocol for disaggregated serving.

The wire format between a prefill replica's paged pool and a decode
replica's (serving/disagg.py): each transferred unit is ONE physical
block — ``block_size`` token rows gathered from every per-row pool leaf
— addressed by the pool's content-chained prefix key (kv_pool.py
``_chain_keys``) and sealed with a per-block CRC-32 over the raw bytes,
the same checksum scheme the checkpoint manifest uses for corruption
detection (engine/integrity.py ``leaf_checksums``).  Content addressing
is what makes the transfer safe to dedupe and replay: equal keys imply
bitwise-equal K/V (prefill with identical config/params/bucket is a
deterministic jit program), so an imported block is interchangeable
with a locally-recomputed one and token parity holds by construction.

Host-staged on purpose: blocks round-trip through ``numpy`` arrays
(device → host gather on export, host → device scatter on import)
because the single-process fleet has no device-to-device fabric to
model — the honest cost of that staging on CPU is measured by
``bench.py disagg`` and documented in PERF.md, not hidden.

This module is pure data plumbing — no locks, no threads, no pool
mutation beyond the functional ``.at[].set`` scatter.  The scheduler
owns WHEN extraction/scattering happen (on its loop thread, at tick
boundaries); serving/disagg.py owns the recovery ladder around failed
or corrupt transfers.
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

__all__ = [
    "BlockPayload",
    "BlockRef",
    "corrupt_payload",
    "extract_block_refs",
    "extract_payloads",
    "materialize_payloads",
    "payload_checksum",
    "pool_row_leaves",
    "scatter_payloads",
    "verify_payload",
]


def _path_name(path) -> str:
    return "/".join(
        str(getattr(part, "key", getattr(part, "name", ""))) for part in path
    )


def pool_row_leaves(pool, n_rows: int) -> List[Tuple[str, Any]]:
    """``(name, leaf)`` for every per-row KV pool leaf, sorted by name.

    Identified structurally the same way the chaos SDC injector finds
    its corruption targets (scheduler ``_corrupt_pool_rows``): leading
    dimension equal to ``num_blocks * block_size`` and a path naming a
    k/v pool.  Sorted order makes the leaf set deterministic on both
    ends of a transfer, which the chained checksum relies on.
    """
    flat = jax.tree_util.tree_flatten_with_path(pool)[0]
    out: List[Tuple[str, Any]] = []
    for path, leaf in flat:
        name = _path_name(path)
        if "k_pool" not in name and "v_pool" not in name:
            continue
        if hasattr(leaf, "shape") and leaf.ndim >= 1 and leaf.shape[0] == n_rows:
            out.append((name, leaf))
    out.sort(key=lambda kv: kv[0])
    return out


def payload_checksum(key: tuple, index: int, arrays: Dict[str, np.ndarray]) -> int:
    """CRC-32 chained over the block's identity and every leaf's bytes.

    The identity (chain key + block index) is part of the digest so a
    payload cannot be silently replayed under a different address; each
    leaf contributes a ``name:dtype:shape`` header before its raw bytes
    (the integrity-manifest idiom) so truncation or a reshaped array
    fails the check, not just flipped bits.
    """
    crc = zlib.crc32(repr((key, index)).encode())
    for name in sorted(arrays):
        arr = arrays[name]
        crc = zlib.crc32(f"{name}:{arr.dtype}:{arr.shape}".encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return crc & 0xFFFFFFFF


@dataclass
class BlockPayload:
    """One physical block in flight: ``block_size`` rows of every pool
    leaf, keyed by the content-chained prefix address, CRC-sealed."""

    key: tuple
    index: int  # position of this block in the prefix chain, 0-based
    arrays: Dict[str, np.ndarray]  # leaf name -> [block_size, ...] rows
    crc: int

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self.arrays.values())


@dataclass
class BlockRef:
    """One block SELECTED for transfer but not yet host-staged: lazy
    per-leaf device slices instead of materialized numpy rows.

    The split exists so the scheduler thread only pays the cheap device
    slice dispatch (``leaf[rows]`` — an async device gather, no host
    sync) and the expensive part — device→host copies plus the CRC seal
    — runs on a staging executor (serving/disagg.py).  Safety: the
    slices are taken at a tick boundary while the pool is quiescent, and
    JAX arrays are immutable, so the snapshot stays valid even after the
    scheduler functionally replaces its pool on later ticks.
    """

    key: tuple
    index: int  # position of this block in the prefix chain, 0-based
    slices: Dict[str, Any]  # leaf name -> [block_size, ...] device rows


def extract_block_refs(
    kv, pool, prompt: Sequence[int], namespace=None
) -> List[BlockRef]:
    """Select the longest cached chain for ``prompt`` as lazy refs.

    Runs on the source scheduler's loop thread (single-thread pool
    confinement) but does NOT block on any host copy.  Cached blocks are
    fully written by construction — registration is capped at
    ``(prompt_len - 1) // block_size`` FULL blocks.
    """
    chain = kv.cached_chain(prompt, namespace)
    if not chain:
        return []
    bs = kv.block_size
    leaves = pool_row_leaves(pool, kv.num_blocks * bs)
    return [
        BlockRef(
            key=key,
            index=index,
            slices={
                name: leaf[blk * bs : (blk + 1) * bs] for name, leaf in leaves
            },
        )
        for index, (key, blk) in enumerate(chain)
    ]


def materialize_payloads(
    refs: Sequence[BlockRef], chunk_rows: Optional[int] = None
) -> List[BlockPayload]:
    """Host-stage refs into CRC-sealed payloads (any thread).

    This is the expensive half of an export — the device→host copies and
    the checksum over every byte.  ``chunk_rows`` bounds each individual
    ``np.asarray`` to that many leading rows (None = whole leaf slice in
    one copy): with several transfers sharing one bounded staging
    executor, chunking keeps any single copy from monopolizing a worker
    and caps the transient host buffer per copy.
    """
    if chunk_rows is not None and chunk_rows < 1:
        raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
    out: List[BlockPayload] = []
    for ref in refs:
        arrays: Dict[str, np.ndarray] = {}
        for name, sl in ref.slices.items():
            n = sl.shape[0]
            if chunk_rows is None or chunk_rows >= n:
                arrays[name] = np.asarray(sl)
            else:
                arrays[name] = np.concatenate(
                    [
                        np.asarray(sl[i : i + chunk_rows])
                        for i in range(0, n, chunk_rows)
                    ]
                )
        out.append(
            BlockPayload(
                key=ref.key,
                index=ref.index,
                arrays=arrays,
                crc=payload_checksum(ref.key, ref.index, arrays),
            )
        )
    return out


def extract_payloads(
    kv, pool, prompt: Sequence[int], namespace=None
) -> List[BlockPayload]:
    """Gather the longest cached chain for ``prompt`` into payloads.

    The synchronous composition of :func:`extract_block_refs` and
    :func:`materialize_payloads` — the staging cost lands on the calling
    thread.  The disaggregated transfer path splits the two phases
    instead (refs on the scheduler thread, staging on the coordinator's
    executor); this stays for callers that want a one-shot export.
    """
    return materialize_payloads(
        extract_block_refs(kv, pool, prompt, namespace=namespace)
    )


def verify_payload(payload: BlockPayload) -> bool:
    """Recompute the CRC over what actually arrived."""
    return (
        payload_checksum(payload.key, payload.index, payload.arrays)
        == payload.crc
    )


def corrupt_payload(payload: BlockPayload) -> None:
    """Flip one byte of the first leaf AFTER sealing (fault-injection
    hook for ``kv_transfer_corrupt``): the stale CRC must now reject.
    Dtype-agnostic via a bytes round-trip — bf16 has no numpy view."""
    name = sorted(payload.arrays)[0]
    arr = payload.arrays[name]
    raw = bytearray(arr.tobytes())
    raw[0] ^= 0xFF
    payload.arrays[name] = np.frombuffer(
        bytes(raw), dtype=arr.dtype
    ).reshape(arr.shape)


def scatter_payloads(pool, n_rows: int, accepted: List[Tuple[int, BlockPayload]]):
    """Write accepted payloads into their adopted blocks, one scatter
    per leaf (batched ``.at[rows].set``), returning the updated pool.

    ``accepted`` pairs each payload with the LOCAL block id the
    importing pool adopted for it — physical ids are replica-private;
    only the content keys travel.
    """
    if not accepted:
        return pool
    names = sorted(accepted[0][1].arrays)
    rows_parts: List[np.ndarray] = []
    vals: Dict[str, List[np.ndarray]] = {name: [] for name in names}
    for blk, payload in accepted:
        bsz = payload.arrays[names[0]].shape[0]
        rows_parts.append(np.arange(blk * bsz, (blk + 1) * bsz))
        for name in names:
            vals[name].append(payload.arrays[name])
    rows = np.concatenate(rows_parts)
    stacked = {name: np.concatenate(vals[name]) for name in names}

    def _write(path, leaf):
        name = _path_name(path)
        if name in stacked and hasattr(leaf, "shape") and leaf.shape[:1] == (
            n_rows,
        ):
            return leaf.at[rows].set(stacked[name].astype(leaf.dtype))
        return leaf

    return jax.tree_util.tree_map_with_path(_write, pool)
