"""Serving-side fault tolerance: classify, isolate, restart — never hang.

The training fault layer (PRs 3-5) follows one house style: deterministic
injection (engine/fault.py) → guard → bounded recovery → counters → chaos
bench.  This module is the serving half.  Before PR 9 a tick exception
failed EVERY in-flight request (`ContinuousScheduler._fail_inflight`);
now the supervisor sits between the tick and that scorched-earth
fallback and walks a recovery ladder:

1. **Attributable errors → poison-bisect.**  A Python exception raised
   from the decode dispatch while requests are active is re-driven
   against halves of the active set (``_decode_probe`` re-runs the exact
   dispatch — the pool scatter is idempotent for identical inputs, and
   per-row per-token-index ``fold_in`` sampling keys make the probe
   bit-reproducible).  The culprit is evicted with a diagnosed
   :class:`PoisonedRequestError`; its KV blocks free; every other slot
   resumes untouched.  ~log2(slots) probes, plus one reproduce and one
   confirm.  A NaN-emitting request never even raises: the decode
   programs return per-row ``isfinite`` flags (serving/decode.py) and
   the scheduler evicts on the flag — the serving mirror of the training
   anomaly guard.
2. **Non-attributable errors → hot-restart with replay.**  Device loss
   (:class:`..engine.fault.DeviceLostError`, real ``XlaRuntimeError``),
   a hung tick (:class:`HungTickError` from the tick watchdog), or a
   non-reproducible probe escalate to ``_rebuild_and_requeue``: the
   compiled prefill/decode programs and the paged pool are rebuilt and
   every in-flight request is re-admitted; the scheduler re-prefills
   ``prompt + tokens_generated_so_far`` and re-feeds the generated
   tokens through the SAME decode program that produced them, so the
   continuation is token-identical (the replay parity oracle pins it
   bitwise, greedy and sampled).
3. **Bounded budget.**  Restarts draw from ``max_restarts``; exhaustion
   fails the remaining futures with :class:`EngineRestartError` chaining
   the final cause — bounded recovery, exactly like the training-side
   rollback/retry budgets.

The supervisor holds POLICY and BUDGET only; all slot/pool mutation
stays on the scheduler thread (``handle_tick_failure`` runs inside
``tick``'s except clause), so the pool keeps its no-locks contract.
Only the counters read cross-thread (health endpoints) sit under the
supervisor's lock.
"""
from __future__ import annotations

import logging
from typing import Optional

import threading

from ..engine import fault
from ..telemetry.spans import span

__all__ = [
    "EngineRestartError",
    "HungTickError",
    "PoisonedRequestError",
    "ServingSupervisor",
]


class PoisonedRequestError(RuntimeError):
    """One request poisoned the decode step; only ITS future gets this.

    Raised with a diagnosis (slot, tick, trigger) and chained to the
    underlying cause when there was a Python exception (``__cause__`` is
    None for the isfinite output-guard path — NaNs never raise).
    """


class HungTickError(RuntimeError):
    """The tick watchdog flagged a scheduler iteration as hung.

    Converted into a diagnosed hot-restart by the supervisor: a wedged
    decode dispatch cannot be attributed to one request, and the
    compiled programs' state is suspect.
    """


class EngineRestartError(RuntimeError):
    """The restart budget is exhausted; remaining futures fail with this,
    ``__cause__`` chaining the error that burned the last restart."""


def _is_device_loss(exc: BaseException) -> bool:
    """Device-level failure: the error names the runtime, not a request."""
    if isinstance(exc, (fault.DeviceLostError, HungTickError)):
        return True
    name = type(exc).__name__
    module = type(exc).__module__ or ""
    return "XlaRuntimeError" in name or module.startswith("jaxlib")


class ServingSupervisor:
    """Recovery policy + restart budget for one :class:`ContinuousScheduler`.

    ``handle_tick_failure`` MUST be called on the scheduler thread (it
    drives slot eviction and pool rebuild); ``restarts()`` / ``exhausted()``
    are safe from any thread and feed the health snapshot.
    """

    def __init__(
        self,
        scheduler,
        *,
        max_restarts: int = 2,
        poison_bisect: bool = True,
        logger: Optional[logging.Logger] = None,
    ):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self._sched = scheduler
        self.max_restarts = int(max_restarts)
        self.poison_bisect = bool(poison_bisect)
        self._logger = logger or logging.getLogger(__name__)
        self._lock = threading.Lock()
        self._restarts = 0  # guarded by: self._lock
        self._exhausted = False  # guarded by: self._lock

    def restarts(self) -> int:
        with self._lock:
            return self._restarts

    def exhausted(self) -> bool:
        with self._lock:
            return self._exhausted

    # ------------------------------------------------------------------ #

    def handle_tick_failure(self, exc: BaseException) -> bool:
        """Recover from a failed tick; returns True (work happened).

        Ladder: device-class errors restart; decode-phase errors bisect
        down to one request and evict it; anything unattributable (prefill
        phase, non-reproducible, bisect disabled with several suspects)
        escalates to restart.  Restart past the budget fails the world
        with the chained cause.
        """
        sched = self._sched
        # the scheduler flushed its async dispatch ring before handing
        # us the failure (scheduler.tick), so probe/replay state below
        # is sync-equivalent: host-known streams match the device, and
        # dispatch counters are rolled back to gen_idx.
        if not _is_device_loss(exc) and sched._tick_phase == "decode":
            # span = the serve-side MTTR anchor (telemetry/slo.py): recovery
            # start → first post-recovery decode tick
            with span("poison_bisect", step=sched._tick_no,
                      cause=type(exc).__name__):
                isolated = self._isolate(exc)
            if isolated:
                return True
            self._logger.warning(
                "decode failure not attributable to one request "
                "(%s: %s) — escalating to hot-restart",
                type(exc).__name__, exc,
            )
        return self._restart(exc)

    # ------------------------------------------------------------------ #

    def _probe_raises(self, reqs) -> bool:
        self._sched._bump("poison_probes")
        try:
            self._sched._decode_probe(reqs)
        except Exception:
            return True
        return False

    def _isolate(self, exc: BaseException) -> bool:
        """Bisect the active set down to the request that reproduces
        ``exc``'s dispatch failure and evict it; False = cannot attribute."""
        sched = self._sched
        active = [r for r in sched._slots if r is not None]
        if not active:
            return False
        if len(active) == 1:
            # nothing to bisect: the only active request owns the failure
            sched._evict_poisoned(active[0], cause=exc, trigger="decode raise")
            return True
        if not self.poison_bisect:
            return False
        if not self._probe_raises(active):
            return False  # not reproducible — transient, restart instead
        cands = active
        while len(cands) > 1:
            half = cands[: len(cands) // 2]
            cands = half if self._probe_raises(half) else cands[len(cands) // 2 :]
        if not self._probe_raises(cands):
            return False  # the fault needed company — not one request's
        sched._evict_poisoned(cands[0], cause=exc, trigger="decode raise")
        return True

    def _restart(self, cause: BaseException) -> bool:
        sched = self._sched
        with self._lock:
            if self._restarts >= self.max_restarts:
                self._exhausted = True
                n = self._restarts
            else:
                self._restarts += 1
                n = -1
        if n >= 0:
            sched._bump("restart_budget_exhausted")
            err = EngineRestartError(
                f"serving engine restart budget exhausted ({n}/"
                f"{self.max_restarts} restarts used); failing in-flight "
                "requests"
            )
            err.__cause__ = cause
            self._logger.error("%s", err)
            sched._fail_inflight(err)
            return True
        sched._bump("engine_restarts")
        self._logger.error(
            "hot-restarting serving engine after %s: %s (restart %d/%d)",
            type(cause).__name__, cause, self.restarts(), self.max_restarts,
        )
        with span("serving_restart", step=sched._tick_no,
                  cause=type(cause).__name__):
            sched._rebuild_and_requeue()
        return True
