"""SLO-driven fleet autoscaler: grow on pressure, shrink through drain.

:class:`FleetAutoscaler` closes the loop ROADMAP item 5 describes: a
:class:`.fleet.ServingFleet` that grows and shrinks itself against a
stated SLO while the chaos harness lands faults inside the scaling
events.  Three design decisions carry the robustness story:

**Deterministic, hand-driven control loop.**  The autoscaler owns no
thread.  The driver (``bench.py autoscale``, a chaos scenario, a test)
calls :meth:`poll` on its own cadence with an injected ``clock`` — so a
scaling schedule is replayable, cooldowns are testable without sleeping,
and a decision-time hang (the ``autoscale_hang`` fault kind) lands at an
exact poll index.

**Scale-up through the one restore.**  New replicas come from
:meth:`.fleet.ServingFleet.add_replica`, which reuses the ingredients
``ServingFleet.from_config`` resolved ONCE (restored parameter tree,
mesh, constructor kwargs) and stamps the next replica identity — the
same path every original replica was born through, so an autoscaled
fleet is indistinguishable from one provisioned at that size.

**Scale-down exclusively through drain.**  Replicas are retired via
:meth:`.fleet.ServingFleet.remove_replica`: the router stops placing
onto the replica, then the replica's own ``drain(deadline_ms)`` runs its
in-flight requests to completion before ``close()``.  Nothing is
re-routed, killed, or replayed on the happy path — scale-down inherits
the token-identical-completion oracle the drain path already carries
(tests/test_fleet.py pins it against an unscaled twin).

Signals come from the telemetry side the fleet already publishes:
router backlog (outstanding requests), per-replica slot occupancy and
the process-registry ``serving_r<i>_block_util`` gauges, and the fleet
latency-p99 snapshot against ``target_p99_ms``.  Each poll mirrors what
it read into ``autoscale_*`` gauges so the bench one-liner and the soak
oracles read the same numbers the decision used.

Config (``serving.autoscale`` in serve-lm.yml) is parsed here with the
copy-pop-raise idiom; the ``workload`` sub-section is carried opaque for
:class:`.workload.TraceGenerator`.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Optional

from ..engine import fault
from ..telemetry.registry import get_registry

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """Replica-count controller over a :class:`.fleet.ServingFleet`.

    Single-threaded by contract: one driver calls :meth:`poll`; the
    fleet/router handle their own internal concurrency.  ``clock`` is
    any monotonic ``() -> float`` in seconds — trace time in the bench,
    a hand-advanced counter in tests, ``time.monotonic`` in production.
    """

    def __init__(
        self,
        fleet,
        autoscale: Optional[Dict[str, Any]] = None,
        clock: Optional[Callable[[], float]] = None,
        logger: Optional[logging.Logger] = None,
    ):
        asc = dict(autoscale or {})
        self.enabled = bool(asc.pop("enabled", True))
        self.min_replicas = int(asc.pop("min_replicas", 1))
        self.max_replicas = int(asc.pop("max_replicas", 4))
        target = asc.pop("target_p99_ms", None)
        self.target_p99_ms = float(target) if target is not None else None
        self.backlog_high = int(asc.pop("backlog_high", 8))
        self.backlog_low = int(asc.pop("backlog_low", 1))
        self.occupancy_high = float(asc.pop("occupancy_high", 0.85))
        self.occupancy_low = float(asc.pop("occupancy_low", 0.25))
        self.scale_up_cooldown_s = float(asc.pop("scale_up_cooldown_s", 2.0))
        self.scale_down_cooldown_s = float(
            asc.pop("scale_down_cooldown_s", 8.0))
        deadline = asc.pop("drain_deadline_ms", 60_000)
        self.drain_deadline_ms = (
            float(deadline) if deadline is not None else None
        )
        # the trace generator's section, carried opaque for the bench
        # driver (TraceGenerator parses + closes it)
        self.workload = asc.pop("workload", None)
        if asc:
            raise ValueError(
                f"unknown serving.autoscale keys: {sorted(asc)}"
            )
        if self.min_replicas < 1:
            raise ValueError(
                f"autoscale.min_replicas must be >= 1, got "
                f"{self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"autoscale.max_replicas ({self.max_replicas}) < "
                f"min_replicas ({self.min_replicas})"
            )
        if self.backlog_low >= self.backlog_high:
            raise ValueError(
                f"autoscale.backlog_low ({self.backlog_low}) must be < "
                f"backlog_high ({self.backlog_high}) — equal thresholds "
                "flap"
            )
        if self.occupancy_low >= self.occupancy_high:
            raise ValueError(
                f"autoscale.occupancy_low ({self.occupancy_low}) must be "
                f"< occupancy_high ({self.occupancy_high})"
            )
        self.fleet = fleet
        self.logger = logger or logging.getLogger("pdt.serving.autoscale")
        self._clock = clock or time.monotonic
        self._poll_no = 0
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        # replica-minutes ledger: integral of live-replica count over the
        # injected clock, the number static peak provisioning is judged by
        self._rm_last_t = self._clock()
        self._replica_seconds = 0.0
        self.scale_ups = 0
        self.scale_downs = 0

    # ------------------------------------------------------------------ #
    # signals

    def signals(self) -> Dict[str, float]:
        """One coherent read of the decision inputs.

        ``backlog`` is the router-level outstanding count;
        ``occupancy`` is the worst usable replica's slot occupancy
        (queue pressure saturates it to 1.0 — a replica with a waiting
        queue is full no matter what its slots say); ``block_util`` is
        the max ``serving_r<i>_block_util`` gauge over live replicas;
        ``p99_ms`` the fleet latency bound (0.0 before any request).
        """
        health = self.fleet.health()
        backlog = float(health.get("outstanding", 0))
        occupancy = 0.0
        reg = get_registry()
        block_util = 0.0
        for snap in health.get("replicas", ()):
            if snap.get("routed_down") or snap.get("retired"):
                continue
            slots = max(float(snap.get("slots", 0) or 0), 1.0)
            occ = float(snap.get("active_slots", 0) or 0) / slots
            if snap.get("queue_depth", 0):
                occ = 1.0
            occupancy = max(occupancy, occ)
            rid = snap.get("replica")
            if rid is not None:
                block_util = max(
                    block_util,
                    reg.gauge(f"serving_r{rid}_block_util").value,
                )
        p99 = 0.0
        if self.target_p99_ms is not None:
            p99 = float(
                self.fleet.snapshot()["fleet"].get("latency_ms_p99", 0.0)
            )
        sig = {
            "backlog": backlog,
            "occupancy": occupancy,
            "block_util": block_util,
            "p99_ms": p99,
            "live_replicas": float(self.fleet.live_replicas()),
        }
        for name, val in sig.items():
            reg.gauge(f"autoscale_{name}").set(val)
        return sig

    # ------------------------------------------------------------------ #
    # control loop

    def poll(self) -> str:
        """One control-loop step: read signals, maybe scale.

        Returns the decision: ``"up"``, ``"down"``, ``"heal"`` (below
        ``min_replicas`` after replica loss), or ``"hold"``.  The
        ``autoscale_hang`` fault kind lands HERE, keyed by this poll's
        1-based index — the hang delays the decision, and the signals
        are read only after it so a stale pre-hang view can never drive
        a scale action (the recovery contract the scaling chaos family
        checks).
        """
        self._poll_no += 1
        inj = fault.get_injector()
        if inj.active:
            sec = inj.take("autoscale_hang", self._poll_no)
            if sec is not None:
                fault.bump("injected_autoscale_hangs")
                self.logger.warning(
                    "fault injection: autoscale decision hang %.2fs at "
                    "poll %d", float(sec), self._poll_no,
                )
                time.sleep(float(sec))
        if not self.enabled:
            return "hold"
        now = self._clock()
        sig = self.signals()
        live = int(sig["live_replicas"])
        if live < self.min_replicas:
            # below floor (replica loss): heal immediately, no cooldown —
            # the floor IS the availability contract
            self._scale_up(now, "heal to min_replicas")
            return "heal"
        pressure = (
            sig["backlog"] >= self.backlog_high
            or sig["occupancy"] >= self.occupancy_high
            or (
                self.target_p99_ms is not None
                and sig["p99_ms"] > self.target_p99_ms
                and sig["backlog"] > 0
            )
        )
        idle = (
            sig["backlog"] <= self.backlog_low
            and sig["occupancy"] <= self.occupancy_low
            # a breached p99 vetoes shrinking even with an empty queue:
            # removing capacity while over SLO can only widen the breach
            and not (
                self.target_p99_ms is not None
                and sig["p99_ms"] > self.target_p99_ms
            )
        )
        if pressure and live < self.max_replicas:
            if self._cooled(self._last_up_t, self.scale_up_cooldown_s, now):
                self._scale_up(
                    now,
                    f"backlog={sig['backlog']:.0f} "
                    f"occupancy={sig['occupancy']:.2f} "
                    f"p99={sig['p99_ms']:.0f}ms",
                )
                return "up"
        elif idle and live > self.min_replicas and not pressure:
            # scale-down waits out BOTH cooldowns: shrinking right after
            # growing is how autoscalers flap through a flash crowd
            if self._cooled(
                self._last_down_t, self.scale_down_cooldown_s, now
            ) and self._cooled(
                self._last_up_t, self.scale_down_cooldown_s, now
            ):
                self._scale_down(now)
                return "down"
        return "hold"

    @staticmethod
    def _cooled(last: Optional[float], cooldown_s: float,
                now: float) -> bool:
        return last is None or (now - last) >= cooldown_s

    def _scale_up(self, now: float, why: str) -> None:
        self._account(now)
        idx = self.fleet.add_replica()
        self._last_up_t = now
        self.scale_ups += 1
        get_registry().counter("autoscale_ups").inc()
        get_registry().gauge("autoscale_replicas").set(
            float(self.fleet.live_replicas()))
        self.logger.warning(
            "autoscale UP -> replica %d (%d live): %s",
            idx, self.fleet.live_replicas(), why)

    def _scale_down(self, now: float) -> None:
        idx = self.fleet.pick_retire_candidate()
        if idx is None:
            return
        self._account(now)
        drain_ms = self.fleet.remove_replica(
            idx, deadline_ms=self.drain_deadline_ms)
        self._last_down_t = now
        self.scale_downs += 1
        get_registry().counter("autoscale_downs").inc()
        get_registry().gauge("autoscale_replicas").set(
            float(self.fleet.live_replicas()))
        self.logger.warning(
            "autoscale DOWN: replica %d drained in %.1f ms (%d live)",
            idx, drain_ms, self.fleet.live_replicas())

    # ------------------------------------------------------------------ #
    # replica-minutes ledger

    def _account(self, now: float) -> None:
        live = self.fleet.live_replicas()
        self._replica_seconds += max(0.0, now - self._rm_last_t) * live
        self._rm_last_t = now

    def replica_minutes(self) -> float:
        """Integral of live replicas over the injected clock, in
        replica-minutes — the cost axis of the autoscale A/B."""
        self._account(self._clock())
        return self._replica_seconds / 60.0
