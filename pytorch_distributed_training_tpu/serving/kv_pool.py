"""Paged KV-cache pool: block allocator, prefix cache, admission control.

Host-side bookkeeping for the paged attention mode (ops/attention.py
``MultiHeadAttention.paged``): device memory is ONE preallocated pool of
``num_blocks`` blocks of ``block_size`` token rows per layer, and each
in-flight request owns a list of physical block ids covering its prompt
plus its whole generation budget.  The vLLM construction (PagedAttention,
Kwon et al. SOSP'23) — cache memory stops being per-batch contiguous
slabs sized for the worst case and becomes a recyclable heap, which is
what lets the iteration-level scheduler (serving/scheduler.py) keep
admitting new requests while long generations run.

Admission control instead of OOM: :meth:`PagedKVPool.admit` reserves a
request's ENTIRE worst-case footprint (``ceil((prompt + max_new) /
block_size)`` blocks, minus prefix-cache reuse) up front and returns
``None`` when the pool cannot cover it — the request waits in the queue;
the pool can never over-commit and a running request can never be killed
mid-generation for memory.  (The alternative — allocate-on-demand with
preempt-and-recompute eviction — buys higher occupancy at the cost of
wasted work; documented as future work in the ROADMAP.)

Prefix caching: completed prefills register their FULL prompt blocks
under a chained key of the exact token contents, so a later request whose
prompt shares a block-aligned prefix reuses those blocks without
recomputing them (refcounted: shared blocks are read-only by construction
because the paged attention scatter only covers suffix positions).  At
least the last prompt token is always recomputed (the first sampled token
needs its logits), so reuse is capped at ``(prompt_len - 1) // block_size``
blocks.  Cache entries hold their own reference; when the allocator runs
dry, least-recently-used entries whose only holder is the cache are
evicted to the free list.  Evicting a chain-middle entry strands its
descendants (unreachable by lookup) — they are reclaimed by the same LRU
sweep when their turn comes.

No locks: all mutation happens on the scheduler's single loop thread.
Counters (admitted / prefix hits / evictions) are the scheduler's job and
flow through ``ServingMetrics`` / the telemetry registry, keeping this
module pure bookkeeping.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

__all__ = ["Admission", "BlockAllocator", "PagedKVPool"]


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` physical block ids."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO recycling: recently-freed blocks are re-issued first, which
        # keeps the working set of pool rows small
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._allocated: set = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` block ids, or ``None`` when the free list cannot cover it
        (all-or-nothing: a partial grant could deadlock two waiters)."""
        if n < 0:
            raise ValueError(f"alloc count must be >= 0, got {n}")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._allocated.update(out)
        return out

    def free(self, block_ids: Sequence[int]) -> None:
        for b in block_ids:
            if b not in self._allocated:
                raise ValueError(f"double free of block {b}")
            self._allocated.discard(b)
            self._free.append(b)


class Admission:
    """One admitted request's block reservation.

    ``block_ids`` covers the whole worst-case sequence in logical order;
    the first ``n_shared`` entries are refcounted prefix-cache blocks
    (read-only), holding positions ``[0, cached_len)``.
    """

    __slots__ = ("block_ids", "n_shared", "cached_len")

    def __init__(self, block_ids: List[int], n_shared: int, block_size: int):
        self.block_ids = block_ids
        self.n_shared = n_shared
        self.cached_len = n_shared * block_size


class PagedKVPool:
    """Allocator + refcounts + prefix cache over one block pool."""

    def __init__(
        self, num_blocks: int, block_size: int, prefix_cache: bool = True
    ):
        self._alloc = BlockAllocator(num_blocks, block_size)
        self.prefix_cache = bool(prefix_cache)
        self._ref: dict = {}  # block id -> holders (requests + cache)
        # chained-content key -> block id, in LRU order (see _chain_keys)
        self._cache: "OrderedDict[tuple, int]" = OrderedDict()
        self.prefix_evictions = 0

    @property
    def num_blocks(self) -> int:
        return self._alloc.num_blocks

    @property
    def block_size(self) -> int:
        return self._alloc.block_size

    @property
    def blocks_in_use(self) -> int:
        return self._alloc.num_blocks - self._alloc.num_free

    def blocks_needed(self, prompt_len: int, max_new: int) -> int:
        bs = self.block_size
        return -(-(prompt_len + max_new) // bs)

    # ------------------------------------------------------------------ #

    def _chain_keys(self, prompt: Sequence[int], namespace=None):
        """(key, block_index) for each reusable FULL prompt block: the key
        chains the exact token contents of every block up to this one, so
        equal keys imply bitwise-equal cached K/V.  Capped below the last
        prompt token — its logits must always be recomputed.

        ``namespace`` seeds the chain: two requests share cached blocks
        only when BOTH their namespace and their token prefix match.  The
        multi-LoRA scheduler passes the adapter id here — identical
        prompts under different adapters produce different K/V (the
        adapter delta feeds the qkv projection), so cross-tenant reuse
        would be silent corruption, not a cache hit."""
        bs = self.block_size
        key: tuple = (namespace,)
        for i in range((len(prompt) - 1) // bs):
            key = (key, tuple(int(t) for t in prompt[i * bs : (i + 1) * bs]))
            yield key, i

    def lookup_prefix(
        self, prompt: Sequence[int], namespace=None
    ) -> List[int]:
        """Longest cached chain of full prompt blocks (no refs taken)."""
        if not self.prefix_cache:
            return []
        out: List[int] = []
        for key, _ in self._chain_keys(prompt, namespace):
            blk = self._cache.get(key)
            if blk is None:
                break
            self._cache.move_to_end(key)
            out.append(blk)
        return out

    def admit(
        self,
        prompt: Sequence[int],
        max_new: int,
        namespace=None,
        extra_blocks: int = 0,
    ) -> Optional[Admission]:
        """Reserve the request's full footprint; ``None`` = wait.

        The shared prefix (if any) is refcounted rather than copied; the
        remaining blocks come from the free list, evicting LRU prefix-cache
        entries if that is what it takes.  A request whose footprint
        exceeds the whole pool raises — waiting would never help.

        ``extra_blocks`` private scratch blocks are appended after the
        footprint (the speculative fork's spare block rides here so its
        lifetime and refcount accounting are the admission's own).
        """
        if extra_blocks < 0:
            raise ValueError(f"extra_blocks must be >= 0, got {extra_blocks}")
        total = self.blocks_needed(len(prompt), max_new) + extra_blocks
        if total > self.num_blocks:
            raise ValueError(
                f"request needs {total} blocks but the pool only has "
                f"{self.num_blocks} (prompt {len(prompt)} + max_new "
                f"{max_new} @ block_size {self.block_size})"
            )
        shared = self.lookup_prefix(prompt, namespace)
        fresh = self._alloc_with_evict(total - len(shared))
        if fresh is None:
            return None
        for b in shared:
            self._ref[b] += 1
        for b in fresh:
            self._ref[b] = 1
        return Admission(shared + fresh, len(shared), self.block_size)

    def register_prefix(
        self, prompt: Sequence[int], admission: Admission, namespace=None
    ) -> None:
        """Publish this prefill's full prompt blocks for future reuse.
        First-writer-wins: a chain link another request already registered
        keeps its block (ours stays private and is freed at release)."""
        if not self.prefix_cache:
            return
        for key, i in self._chain_keys(prompt, namespace):
            if key in self._cache:
                continue
            blk = admission.block_ids[i]
            self._cache[key] = blk
            self._ref[blk] += 1  # the cache's own reference

    def cached_chain(
        self, prompt: Sequence[int], namespace=None
    ) -> List[Tuple[tuple, int]]:
        """Longest cached chain as ``(chain_key, block_id)`` pairs.

        The KV-transfer exporter's view (serving/kv_transfer.py): the
        keys travel with the block payloads so the importing pool can
        publish them under identical content addresses — equal keys
        imply bitwise-equal K/V, which is what makes a transferred
        prefix interchangeable with a locally-computed one.  Touches
        LRU recency like :meth:`lookup_prefix` (an exported block is a
        hot block); takes no references — the cache's own ref keeps the
        blocks alive for the duration of the host-side copy because
        extraction happens synchronously on the scheduler thread."""
        out: List[Tuple[tuple, int]] = []
        if not self.prefix_cache:
            return out
        for key, _ in self._chain_keys(prompt, namespace):
            blk = self._cache.get(key)
            if blk is None:
                break
            self._cache.move_to_end(key)
            out.append((key, blk))
        return out

    def is_cached(self, key: tuple) -> bool:
        """Whether a chain key is already published (first-writer-wins:
        the importer skips blocks some local prefill beat it to)."""
        return key in self._cache

    def adopt_block(self, key: tuple) -> Optional[int]:
        """Allocate one block to hold a TRANSFERRED cache entry.

        The cache holds the only reference (exactly the state a
        registered-then-released local prefill leaves behind), so the
        adopted block competes in the same LRU eviction order as native
        entries.  ``None`` when the pool cannot free a block even after
        LRU eviction, or when prefix caching is disabled — the importer
        stops the chain there and the decode side recomputes the rest."""
        if not self.prefix_cache:
            return None
        if key in self._cache:
            raise ValueError(
                f"chain key already cached (check is_cached first): {key!r}"
            )
        got = self._alloc_with_evict(1)
        if got is None:
            return None
        blk = got[0]
        self._ref[blk] = 1
        self._cache[key] = blk
        return blk

    def release(self, admission: Admission) -> None:
        """Drop the request's references; zero-ref blocks recycle."""
        for b in admission.block_ids:
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                self._alloc.free([b])

    def check_invariants(self) -> None:
        """Assert the pool's accounting is consistent (test hook).

        Called by the resilience tests after every tick across fault
        scenarios — an eviction or restart path that leaks a block or a
        refcount shows up here immediately instead of as a slow pool
        exhaustion.  Raises ``AssertionError`` on the first violation.
        """
        allocated = self._alloc._allocated
        free = set(self._alloc._free)
        assert not (allocated & free), (
            f"blocks both allocated and free: {sorted(allocated & free)}"
        )
        assert len(free) == len(self._alloc._free), "duplicate free-list entries"
        everything = allocated | free
        expected = set(range(self.num_blocks))
        assert everything == expected, (
            f"lost blocks: {sorted(expected - everything)}"
        )
        assert set(self._ref) == allocated, (
            f"refcount/allocation mismatch: refs without allocation "
            f"{sorted(set(self._ref) - allocated)}, allocation without refs "
            f"{sorted(allocated - set(self._ref))}"
        )
        assert all(v >= 1 for v in self._ref.values()), (
            f"non-positive refcounts: "
            f"{ {b: v for b, v in self._ref.items() if v < 1} }"
        )
        cached = set(self._cache.values())
        assert cached <= set(self._ref), (
            f"cache entries pointing at unallocated blocks: "
            f"{sorted(cached - set(self._ref))}"
        )

    def _alloc_with_evict(self, n: int) -> Optional[List[int]]:
        if n == 0:
            return []
        got = self._alloc.alloc(n)
        if got is not None:
            return got
        # reclaim LRU cache entries whose ONLY holder is the cache itself
        for key in list(self._cache):
            if self._alloc.num_free >= n:
                break
            blk = self._cache[key]
            if self._ref.get(blk) == 1:
                del self._cache[key]
                del self._ref[blk]
                self._alloc.free([blk])
                self.prefix_evictions += 1
        return self._alloc.alloc(n)
