"""Trace-driven workload generator: seeded diurnal + flash-crowd traffic.

The north star serves *millions of users* of bursty, diurnal traffic —
but a bench has minutes, not days.  :class:`TraceGenerator` compresses
that operating regime into a deterministic request trace the autoscaler
bench (``bench.py autoscale``) replays against a real
:class:`.fleet.ServingFleet`:

**Diurnal cycle.**  The arrival rate follows one sinusoidal "day"
(``diurnal_period_s`` of trace time per cycle, amplitude as a fraction
of ``base_rps``), so the trace has a trough the autoscaler should scale
down into and a peak it must provision for.

**Flash crowds.**  Seeded burst windows multiply the instantaneous rate
by ``flash_multiplier`` for ``flash_duration_s`` — the replica-death-
mid-burst scenario the chaos scaling family anchors on.  Window starts
are drawn once up front (a fixed number of draws independent of how much
of the trace is materialized), which is what keeps prefixes stable.

**Heavy-tailed mixes.**  Prompt and generation lengths are Pareto-tailed
(bounded by the serving bucket grid), matching the long-tail request
mixes production LM serving sees, and a seeded fraction of requests
share a prefix group so the router's affinity placement stays load-
bearing under the trace.

Like the chaos schedule (engine/chaos.py), the whole trace is a pure
function of its seed: all randomness flows from one explicit
``random.Random(seed)``, no wall clock, no module state.  The tier-1
pins (tests/test_autoscaler.py) hold :meth:`TraceGenerator.trace_json`
byte-identical per seed and prefix-stable under truncation — a red
autoscale bench rerun with the same seed replays the identical trace.

Stdlib-only on purpose: the bench driver materializes prompt token ids
itself (numpy), keyed by each request's ``prompt_seed`` — also a pure
function of the trace seed, so two arms of an A/B serve bit-identical
prompts.
"""
from __future__ import annotations

import json
import math
from dataclasses import asdict, dataclass
from random import Random
from typing import Any, Dict, List, Optional

__all__ = ["TraceGenerator", "TraceRequest"]


@dataclass(frozen=True)
class TraceRequest:
    """One arrival in the trace (times are trace seconds, not wall)."""

    index: int
    t: float            # arrival offset from trace start
    prompt_len: int     # heavy-tailed, clamped to [prompt_min, prompt_max]
    gen_len: int        # heavy-tailed, clamped to [gen_min, gen_max]
    group: Optional[int]  # shared-prefix group (None = i.i.d. prompt)
    prompt_seed: int    # seeds the prompt's token ids deterministically

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


class TraceGenerator:
    """Deterministic request traces from one explicit seed.

    ``workload`` carries the ``serving.autoscale.workload`` config keys
    (copy-pop-raise idiom so a typo'd key fails at build time, and the
    config-schema pass extracts the accepted surface from this body).
    """

    def __init__(self, seed: int = 0, workload: Optional[Dict] = None):
        wl = dict(workload or {})
        self.seed = int(seed)
        self.duration_s = float(wl.pop("duration_s", 60.0))
        self.base_rps = float(wl.pop("base_rps", 6.0))
        self.diurnal_period_s = float(wl.pop("diurnal_period_s", 40.0))
        self.diurnal_amplitude = float(wl.pop("diurnal_amplitude", 0.6))
        self.flash_crowds = int(wl.pop("flash_crowds", 2))
        self.flash_duration_s = float(wl.pop("flash_duration_s", 4.0))
        self.flash_multiplier = float(wl.pop("flash_multiplier", 4.0))
        self.prompt_min = int(wl.pop("prompt_min", 4))
        self.prompt_max = int(wl.pop("prompt_max", 16))
        self.gen_min = int(wl.pop("gen_min", 2))
        self.gen_max = int(wl.pop("gen_max", 8))
        self.tail_alpha = float(wl.pop("tail_alpha", 1.8))
        self.prefix_groups = int(wl.pop("prefix_groups", 4))
        self.prefix_fraction = float(wl.pop("prefix_fraction", 0.5))
        if wl:
            raise ValueError(
                f"unknown serving.autoscale.workload keys: {sorted(wl)}"
            )
        if self.duration_s <= 0:
            raise ValueError(
                f"workload.duration_s must be > 0, got {self.duration_s}"
            )
        if self.base_rps <= 0:
            raise ValueError(
                f"workload.base_rps must be > 0, got {self.base_rps}"
            )
        if not 0.0 <= self.diurnal_amplitude < 1.0:
            raise ValueError(
                "workload.diurnal_amplitude must be in [0, 1), got "
                f"{self.diurnal_amplitude}"
            )
        if self.prompt_min < 1 or self.prompt_max < self.prompt_min:
            raise ValueError(
                f"bad prompt length bounds [{self.prompt_min}, "
                f"{self.prompt_max}]"
            )
        if self.gen_min < 1 or self.gen_max < self.gen_min:
            raise ValueError(
                f"bad gen length bounds [{self.gen_min}, {self.gen_max}]"
            )
        if self.tail_alpha <= 1.0:
            # alpha <= 1 has infinite mean: a single request could eat the
            # whole trace budget, which is noise, not a heavy tail
            raise ValueError(
                f"workload.tail_alpha must be > 1.0, got {self.tail_alpha}"
            )

    # ------------------------------------------------------------- rate model

    def _flash_windows(self, rng: Random) -> List[float]:
        """Burst-start offsets — a FIXED number of draws per trace so the
        arrival stream after them is prefix-stable under truncation."""
        if self.flash_crowds < 1:
            return []
        # one burst per equal slice of the trace, jittered inside it and
        # kept clear of the very end (a burst the trace cannot finish
        # proves nothing about scale-up)
        span = self.duration_s / self.flash_crowds
        usable = max(0.0, span - self.flash_duration_s)
        return [
            i * span + rng.uniform(0.1 * span, max(0.1 * span, usable))
            for i in range(self.flash_crowds)
        ]

    def rate_at(self, t: float, flash_starts: Optional[List[float]] = None
                ) -> float:
        """Instantaneous arrival rate (req/s) at trace offset ``t``.

        Deterministic given the flash windows; the trough of the diurnal
        sine is placed at t=0 so every trace opens in scale-down
        territory and earns its way up.
        """
        if flash_starts is None:
            flash_starts = self._flash_windows(Random(self.seed))
        phase = 2.0 * math.pi * t / self.diurnal_period_s
        rate = self.base_rps * (
            1.0 - self.diurnal_amplitude * math.cos(phase)
        )
        for start in flash_starts:
            if start <= t < start + self.flash_duration_s:
                rate *= self.flash_multiplier
                break
        return rate

    # ------------------------------------------------------------ generation

    def _tail_len(self, rng: Random, lo: int, hi: int) -> int:
        """Pareto-tailed integer length in [lo, hi]."""
        return min(hi, max(lo, int(lo * rng.paretovariate(self.tail_alpha))))

    def generate(self, limit: Optional[int] = None) -> List[TraceRequest]:
        """Materialize the trace (all arrivals inside ``duration_s``, or
        the first ``limit`` of them).

        A fresh ``Random(seed)`` per call, flash windows drawn first with
        a trace-length-independent number of draws, then one request at a
        time — so ``generate(k) == generate()[:k]``: growing a trace
        never reshuffles the prefix already replayed.
        """
        rng = Random(self.seed)
        flash_starts = self._flash_windows(rng)
        out: List[TraceRequest] = []
        t = 0.0
        while limit is None or len(out) < limit:
            # non-homogeneous Poisson via the instantaneous-rate
            # exponential: deterministic, sequential, prefix-stable
            t += rng.expovariate(self.rate_at(t, flash_starts))
            if t >= self.duration_s:
                break
            grouped = (
                self.prefix_groups > 0
                and rng.random() < self.prefix_fraction
            )
            group = rng.randrange(self.prefix_groups) if grouped else None
            prompt_len = self._tail_len(rng, self.prompt_min, self.prompt_max)
            gen_len = self._tail_len(rng, self.gen_min, self.gen_max)
            # grouped requests share their group's prompt seed so they
            # actually share a prefix; i.i.d. requests get a per-index
            # stream.  Both are pure functions of (seed, index/group).
            prompt_seed = (
                self.seed * 1_000_003 + (
                    group if group is not None else 7919 + len(out)
                )
            )
            out.append(TraceRequest(
                index=len(out),
                t=round(t, 6),
                prompt_len=prompt_len,
                gen_len=gen_len,
                group=group,
                prompt_seed=prompt_seed,
            ))
        return out

    def trace_json(self, limit: Optional[int] = None) -> str:
        """Byte-stable trace dump: same seed ⇒ identical string."""
        return json.dumps(
            [r.to_dict() for r in self.generate(limit)],
            sort_keys=True, separators=(",", ":"),
        )

    def peak_rate(self) -> float:
        """Max of the rate model over the trace (flash peaks included) —
        what static peak provisioning sizes for."""
        flash_starts = self._flash_windows(Random(self.seed))
        step = self.diurnal_period_s / 64.0
        peak, t = 0.0, 0.0
        while t < self.duration_s:
            peak = max(peak, self.rate_at(t, flash_starts))
            t += step
        for start in flash_starts:
            mid = min(start + self.flash_duration_s / 2.0, self.duration_s)
            peak = max(peak, self.rate_at(mid, flash_starts))
        return peak
