"""Multi-LoRA adapter registry: many tenants, one base model, one batch.

Per-tenant finetunes that share a base model differ only by low-rank
deltas (LoRA, Hu et al. 2021), so serving N of them does NOT need N
engines: the registry stacks every adapter's factors into the params
tree (``[N, din, r]`` / ``[N, r, dout]`` leaves next to each Dense
kernel, ops/attention.py) and the paged programs select each batch row's
adapter by id at runtime (ops/lora.py gather-einsum) — requests of
different tenants decode in the SAME iteration-level batch, which is the
whole multiplexing win: one pool, one program set, one compile count.

Adapters here are SYNTHESIZED deterministically from their config seed
(``jax.random.normal * 0.02`` for both factors, keyed per leaf) — the
smoke/bench analog of the engine's random-init serving mode; restoring
real adapter checkpoints over the same stacked leaves is the follow-up
(ROADMAP).  Synthesized factors are deliberately NONZERO on both sides
so the multi-LoRA parity oracle tests a real delta, not a no-op.

:meth:`merged_params` is the oracle's other half: fold adapter ``k``
into the base kernels (``W + A_k B_k``) to get a PLAIN params tree a
base engine can serve — the multiplexed engine's per-adapter token
stream must match that single-tenant engine token for token
(tests/test_serving.py).
"""
from __future__ import annotations

import zlib
from collections.abc import Mapping
from typing import List

import jax
import jax.numpy as jnp

__all__ = ["LoraRegistry"]

_LORA_SUFFIXES = ("_lora_a", "_lora_b")


def _path_str(path) -> str:
    return "/".join(
        str(getattr(p, "key", getattr(p, "name", p))) for p in path
    )


class LoraRegistry:
    """Fixed adapter set (name -> id) + params-tree grafting.

    ``adapters`` entries are dicts ``{name, seed?}`` (or bare name
    strings); the set is FROZEN at engine build — ``lora_adapters`` is a
    static model field, so adding an adapter means rebuilding the
    programs, exactly like changing a bucket grid.
    """

    def __init__(self, rank: int, adapters):
        if int(rank) < 1:
            raise ValueError(f"serving.lora.rank must be >= 1, got {rank}")
        entries = list(adapters or [])
        if not entries:
            raise ValueError(
                "serving.lora.adapters must list at least one adapter"
            )
        self.rank = int(rank)
        self.names: List[str] = []
        self.seeds: List[int] = []
        for i, ent in enumerate(entries):
            if isinstance(ent, str):
                name, seed = ent, i
            else:
                e = dict(ent)
                name = e.pop("name", None)
                if name is None:
                    raise ValueError(
                        f"serving.lora.adapters[{i}] needs a name"
                    )
                seed = int(e.pop("seed", i))
                if e:
                    raise ValueError(
                        f"unknown serving.lora.adapters keys for {name!r}: "
                        f"{sorted(e)}"
                    )
            name = str(name)
            if name in self.names:
                raise ValueError(f"duplicate adapter name {name!r}")
            self.names.append(name)
            self.seeds.append(seed)
        self._ids = {n: i for i, n in enumerate(self.names)}

    def __len__(self) -> int:
        return len(self.names)

    def id_of(self, name: str) -> int:
        """Adapter id (the row index of its stacked factors)."""
        if name not in self._ids:
            raise ValueError(
                f"unknown adapter {name!r}; registered: {self.names}"
            )
        return self._ids[name]

    # ------------------------------------------------------------------ #

    def graft(self, model, params):
        """``(lora_model, lora_params)``: the base model cloned with this
        registry's static LoRA fields, and the base params tree with the
        stacked factor leaves added (every base leaf passes through by
        reference — grafting never copies the base weights).

        The target structure comes from ``jax.eval_shape`` over the LoRA
        model's init (correct flax paths, no device compute); factor
        leaves are then synthesized per adapter seed, everything else is
        looked up in ``params`` by path.
        """
        lora_model = model.clone(
            lora_rank=self.rank, lora_adapters=len(self)
        )
        shapes = jax.eval_shape(
            lora_model.init,
            jax.random.PRNGKey(0),
            jnp.zeros((1, 1), jnp.int32),
        )["params"]
        flat = {}
        jax.tree_util.tree_map_with_path(
            lambda p, leaf: flat.__setitem__(_path_str(p), leaf), params
        )

        def fill(path, shape_leaf):
            ps = _path_str(path)
            if ps.rsplit("/", 1)[-1].endswith(_LORA_SUFFIXES):
                return self._factor(ps, shape_leaf)
            base = flat.get(ps)
            if base is None or tuple(base.shape) != tuple(shape_leaf.shape):
                raise ValueError(
                    f"LoRA graft: base params have no leaf {ps!r} of shape "
                    f"{tuple(shape_leaf.shape)}"
                )
            return base

        lora_params = jax.tree_util.tree_map_with_path(fill, shapes)
        return lora_model, lora_params

    def _factor(self, path_str: str, shape_leaf):
        """One stacked ``[N, ...]`` factor leaf: row ``k`` is adapter
        ``k``'s factor, keyed by (adapter seed, leaf path) so every leaf
        of every adapter is an independent deterministic draw."""
        tag = zlib.crc32(path_str.encode()) & 0x7FFFFFFF
        rows = [
            jax.random.normal(
                jax.random.fold_in(jax.random.PRNGKey(seed), tag),
                shape_leaf.shape[1:],
                jnp.float32,
            )
            * 0.02
            for seed in self.seeds
        ]
        return jnp.stack(rows).astype(shape_leaf.dtype)

    # ------------------------------------------------------------------ #

    def merged_params(self, lora_params, name: str):
        """Fold adapter ``name`` into the base kernels: a PLAIN params
        tree (no factor leaves) with ``kernel += A_k @ B_k`` wherever the
        grafted tree carries factors — structurally identical to the base
        params, so a base (non-LoRA) engine serves it directly.  The
        multi-LoRA parity oracle's reference construction."""
        k = self.id_of(name)

        def visit(node):
            if not isinstance(node, Mapping):
                return node
            out = {
                key: visit(val)
                for key, val in node.items()
                if not key.endswith(_LORA_SUFFIXES)
            }
            for key in node:
                if not key.endswith("_lora_a"):
                    continue
                stem = key[: -len("_lora_a")]
                a = jnp.asarray(node[key])[k].astype(jnp.float32)
                b = jnp.asarray(node[stem + "_lora_b"])[k].astype(jnp.float32)
                kern = out[stem]["kernel"]
                sub = dict(out[stem])
                sub["kernel"] = (kern.astype(jnp.float32) + a @ b).astype(
                    kern.dtype
                )
                out[stem] = sub
            return out

        return visit(lora_params)
