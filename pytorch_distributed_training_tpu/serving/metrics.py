"""Serving-side metrics: latency percentiles, throughput, batch shape.

Latency is recorded per REQUEST (enqueue -> result set), so batching
delay is included — the number a client actually observes.  Throughput
counts work items (images for classification, generated tokens for LM)
over the window from the first to the last recorded request.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Thread-safe accumulator; ``record_batch`` runs on the flush thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._latencies_ms: List[float] = []
        self._batch_sizes: List[int] = []
        self._items = 0
        self._first_t: Optional[float] = None
        self._last_t: Optional[float] = None
        self._max_depth = 0
        # LM phase split (round 6): per-request generated-token counts plus
        # accumulated prefill/decode device seconds and prompt tokens, so
        # the snapshot can report prefill vs decode tokens/s separately
        self._gen_lens: List[int] = []
        self._prompt_tokens = 0
        self._prefill_s = 0.0
        self._decode_s = 0.0
        # degradation/recovery event counters (timeouts, sheds, ...)
        self._counters: Dict[str, int] = {}

    def incr(self, name: str, n: int = 1) -> None:
        """Bump a named degradation counter (e.g. ``timeouts``, ``sheds``)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def record_batch(
        self,
        enqueued_ats: List[float],
        n_items: int,
        queue_depth: int = 0,
        gen_lens: Optional[List[int]] = None,
        prompt_tokens: int = 0,
        prefill_s: float = 0.0,
        decode_s: float = 0.0,
    ) -> None:
        """One flushed batch: per-request enqueue stamps + work-item count.

        LM batches additionally pass ``gen_lens`` (generated tokens per
        request), ``prompt_tokens`` (REAL prompt tokens consumed, not the
        padded bucket area), and the measured ``prefill_s`` / ``decode_s``
        phase wall times.
        """
        now = time.monotonic()
        with self._lock:
            for t0 in enqueued_ats:
                self._latencies_ms.append((now - t0) * 1000.0)
            self._batch_sizes.append(len(enqueued_ats))
            self._items += n_items
            if self._first_t is None:
                self._first_t = now
            self._last_t = now
            self._max_depth = max(self._max_depth, queue_depth)
            if gen_lens is not None:
                self._gen_lens.extend(int(g) for g in gen_lens)
            self._prompt_tokens += int(prompt_tokens)
            self._prefill_s += float(prefill_s)
            self._decode_s += float(decode_s)

    def observe_depth(self, depth: int) -> None:
        with self._lock:
            self._max_depth = max(self._max_depth, depth)

    def snapshot(self) -> Dict[str, float]:
        """Aggregate view: p50/p99 latency, items/sec, batch occupancy."""
        with self._lock:
            lat = np.asarray(self._latencies_ms, np.float64)
            sizes = np.asarray(self._batch_sizes, np.float64)
            span = (
                (self._last_t - self._first_t)
                if self._first_t is not None and self._last_t > self._first_t
                else 0.0
            )
            items = self._items
            depth = self._max_depth
            gen = np.asarray(self._gen_lens, np.float64)
            prompt_tokens = self._prompt_tokens
            prefill_s = self._prefill_s
            decode_s = self._decode_s
            counters = dict(self._counters)
        out = {
            "requests": int(lat.size),
            "batches": int(sizes.size),
            "items": int(items),
            "max_queue_depth": int(depth),
        }
        out.update(counters)
        if lat.size:
            out["latency_ms_p50"] = float(np.percentile(lat, 50))
            out["latency_ms_p99"] = float(np.percentile(lat, 99))
            out["latency_ms_mean"] = float(lat.mean())
        if sizes.size:
            out["batch_size_mean"] = float(sizes.mean())
        # open-loop throughput needs a time span; a single flush has none,
        # so fall back to unreported rather than divide-by-zero noise
        if span > 0:
            out["items_per_sec"] = float(items / span)
        if gen.size:
            out["gen_tokens"] = int(gen.sum())
            out["gen_len_mean"] = float(gen.mean())
            out["gen_len_p50"] = float(np.percentile(gen, 50))
            # phase rates: prefill consumes real prompt tokens, decode emits
            # generated tokens (token 0 is sampled by the prefill program —
            # one token per request of attribution noise, documented rather
            # than corrected)
            if prefill_s > 0:
                out["prefill_tokens_per_sec"] = float(prompt_tokens / prefill_s)
            if decode_s > 0:
                out["decode_tokens_per_sec"] = float(gen.sum() / decode_s)
        return out

    def log_summary(self, logger, prefix: str = "serving") -> Dict[str, float]:
        snap = self.snapshot()
        parts = ", ".join(
            f"{k}={v:.2f}" if isinstance(v, float) else f"{k}={v}"
            for k, v in sorted(snap.items())
        )
        logger.info("%s metrics: %s", prefix, parts)
        return snap
