"""Serving-side metrics: latency percentiles, throughput, batch shape.

Latency is recorded per REQUEST (enqueue -> result set), so batching
delay is included — the number a client actually observes.  Throughput
counts work items (images for classification, generated tokens for LM)
over the window from the first to the last recorded request.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Thread-safe accumulator; ``record_batch`` runs on the flush thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._latencies_ms: List[float] = []
        self._batch_sizes: List[int] = []
        self._items = 0
        self._first_t: Optional[float] = None
        self._last_t: Optional[float] = None
        self._max_depth = 0

    def record_batch(
        self, enqueued_ats: List[float], n_items: int, queue_depth: int = 0
    ) -> None:
        """One flushed batch: per-request enqueue stamps + work-item count."""
        now = time.monotonic()
        with self._lock:
            for t0 in enqueued_ats:
                self._latencies_ms.append((now - t0) * 1000.0)
            self._batch_sizes.append(len(enqueued_ats))
            self._items += n_items
            if self._first_t is None:
                self._first_t = now
            self._last_t = now
            self._max_depth = max(self._max_depth, queue_depth)

    def observe_depth(self, depth: int) -> None:
        with self._lock:
            self._max_depth = max(self._max_depth, depth)

    def snapshot(self) -> Dict[str, float]:
        """Aggregate view: p50/p99 latency, items/sec, batch occupancy."""
        with self._lock:
            lat = np.asarray(self._latencies_ms, np.float64)
            sizes = np.asarray(self._batch_sizes, np.float64)
            span = (
                (self._last_t - self._first_t)
                if self._first_t is not None and self._last_t > self._first_t
                else 0.0
            )
            items = self._items
            depth = self._max_depth
        out = {
            "requests": int(lat.size),
            "batches": int(sizes.size),
            "items": int(items),
            "max_queue_depth": int(depth),
        }
        if lat.size:
            out["latency_ms_p50"] = float(np.percentile(lat, 50))
            out["latency_ms_p99"] = float(np.percentile(lat, 99))
            out["latency_ms_mean"] = float(lat.mean())
        if sizes.size:
            out["batch_size_mean"] = float(sizes.mean())
        # open-loop throughput needs a time span; a single flush has none,
        # so fall back to unreported rather than divide-by-zero noise
        if span > 0:
            out["items_per_sec"] = float(items / span)
        return out

    def log_summary(self, logger, prefix: str = "serving") -> Dict[str, float]:
        snap = self.snapshot()
        parts = ", ".join(
            f"{k}={v:.2f}" if isinstance(v, float) else f"{k}={v}"
            for k, v in sorted(snap.items())
        )
        logger.info("%s metrics: %s", prefix, parts)
        return snap
