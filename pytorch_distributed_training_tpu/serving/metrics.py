"""Serving-side metrics: latency percentiles, throughput, batch shape.

Latency is recorded per REQUEST (enqueue -> result set), so batching
delay is included — the number a client actually observes.  Throughput
counts work items (images for classification, generated tokens for LM)
over the window from the first to the last recorded request.

Storage is BOUNDED (telemetry/registry.py): per-request latencies,
batch sizes, and generated-token lengths land in Algorithm-R reservoir
histograms instead of the lists that previously grew one float per
request forever under sustained traffic.  Counts, sums, and means in the
snapshot stay exact (tracked outside the reservoir); the reported
percentiles are estimates of the TRUE stream percentiles once the stream
exceeds the reservoir (and exact below it, which keeps the snapshot
byte-stable for short runs and the existing tests).

Instruments live in a PRIVATE :class:`MetricsRegistry` (not the process
one): each engine owns its counts, and two engines in one process must
not share a ledger.

Fleet mode (PR 12): N replicas in one process each mirror their counters
into the PROCESS-global registry too (``ContinuousScheduler._bump``),
which used to collide on the shared ``serving_*`` names.  A
:class:`ServingMetrics` constructed with ``replica_id`` namespaces that
mirror (``serving_r<id>_*`` via :meth:`global_name`), and
:func:`aggregate_snapshots` folds the per-replica sub-snapshots into one
fleet view for ``ServingFleet.snapshot()``.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from ..telemetry.registry import MetricsRegistry

__all__ = ["ServingMetrics", "aggregate_snapshots"]

# reservoir per distribution: big enough that p99 of a uniform sample is a
# tight estimate, small enough to cap memory at a few KB per engine
_RESERVOIR = 2048


class ServingMetrics:
    """Thread-safe accumulator; ``record_batch`` runs on the flush thread."""

    def __init__(self, replica_id: Optional[int] = None):
        self.replica_id = replica_id
        self._lock = threading.Lock()
        self._registry = MetricsRegistry()
        self._latency_ms = self._registry.histogram("latency_ms", _RESERVOIR)
        self._batch_size = self._registry.histogram("batch_size", _RESERVOIR)
        self._gen_len = self._registry.histogram("gen_len", _RESERVOIR)
        # continuous-scheduler shape: per-iteration slot occupancy and
        # block-pool utilization, both recorded as fractions in [0, 1]
        self._slot_occ = self._registry.histogram("slot_occupancy", _RESERVOIR)
        self._block_util = self._registry.histogram("block_util", _RESERVOIR)
        # disaggregated serving (PR 19): per-import host-staging wall
        # time; the byte/block counters ride the counter namespace
        self._kv_transfer_ms = self._registry.histogram(
            "kv_transfer_ms", _RESERVOIR
        )
        # async decode pipeline (PR 20): per-tick host overhead (tick
        # wall minus device-readback waits) and the host gap between
        # consecutive decode dispatch enqueues — the pair that makes the
        # pipeline win observable instead of inferred: async mode should
        # shrink the dispatch gap toward pure bookkeeping cost while
        # tick_host_ms stays flat
        self._tick_host_ms = self._registry.histogram(
            "tick_host_ms", _RESERVOIR
        )
        self._dispatch_gap_ms = self._registry.histogram(
            "decode_dispatch_gap_ms", _RESERVOIR
        )
        self._items = 0  # guarded by: self._lock
        self._first_t: Optional[float] = None  # guarded by: self._lock
        self._last_t: Optional[float] = None  # guarded by: self._lock
        self._max_depth = 0  # guarded by: self._lock
        # LM phase split (round 6): accumulated prefill/decode device
        # seconds and the tokens each phase is RESPONSIBLE for.  Generated
        # token 0 is sampled by the prefill program, so it counts as a
        # prefill token (the attribution fix of PR 7 — it was previously
        # lumped into decode throughput and documented-not-corrected).
        self._prefill_tokens = 0  # guarded by: self._lock
        self._decode_tokens = 0  # guarded by: self._lock
        self._prefill_s = 0.0  # guarded by: self._lock
        self._decode_s = 0.0  # guarded by: self._lock
        # multi-tenant (serving.lora): per-adapter latency/len histograms,
        # lazily created in THIS private registry under adapter_<name>_*
        # — the same namespacing move replica_id makes in the process
        # registry, one level down.  Base-model requests stay in the flat
        # instruments only.
        self._adapter_hists: Dict[str, tuple] = {}  # guarded by: self._lock
        # speculative-decode acceptance floor (serving.speculative.
        # min_acceptance, plumbed in by the engine): a measured rate
        # below it makes snapshot() warn ONCE that speculation is
        # costing latency rather than saving it — the bench round that
        # motivated the gate measured 0.371x end-to-end throughput at a
        # 3.4% acceptance rate.  0.0 disables the gate.
        self.spec_min_acceptance = 0.0
        self._spec_floor_warned = False  # guarded by: self._lock
        # autoscaler scale-up readiness: wall ms from replica construction
        # to warm (every program compiled) — set once by the fleet's
        # add_replica after InferenceEngine.warmup()
        self._scale_up_ready_ms: Optional[float] = None  # guarded by: self._lock

    def adapter_name(self, adapter: str, name: str) -> str:
        """Registry name for adapter-scoped instrument ``name``."""
        return f"adapter_{adapter}_{name}"

    def _adapter_instruments(self, adapter: str):
        with self._lock:
            pair = self._adapter_hists.get(adapter)
            if pair is None:
                pair = (
                    self._registry.histogram(
                        self.adapter_name(adapter, "latency_ms"), _RESERVOIR
                    ),
                    self._registry.histogram(
                        self.adapter_name(adapter, "gen_len"), _RESERVOIR
                    ),
                )
                self._adapter_hists[adapter] = pair
            return pair

    def incr(self, name: str, n: int = 1) -> None:
        """Bump a named degradation counter (e.g. ``timeouts``, ``sheds``)."""
        self._registry.counter(name).inc(n)

    def global_name(self, name: str) -> str:
        """The PROCESS-registry mirror name for a serving instrument.

        Replica-less engines keep the historical flat ``serving_<name>``
        namespace (every existing test and bench reads it); a fleet
        replica gets ``serving_r<id>_<name>`` so N replicas in one
        process stop colliding in the shared ledger.
        """
        if self.replica_id is None:
            return f"serving_{name}"
        return f"serving_r{self.replica_id}_{name}"

    def record_batch(
        self,
        enqueued_ats: List[float],
        n_items: int,
        queue_depth: int = 0,
        gen_lens: Optional[List[int]] = None,
        prompt_tokens: int = 0,
        prefill_s: float = 0.0,
        decode_s: float = 0.0,
    ) -> None:
        """One flushed batch: per-request enqueue stamps + work-item count.

        LM batches additionally pass ``gen_lens`` (generated tokens per
        request), ``prompt_tokens`` (REAL prompt tokens consumed, not the
        padded bucket area), and the measured ``prefill_s`` / ``decode_s``
        phase wall times.
        """
        now = time.monotonic()
        for t0 in enqueued_ats:
            self._latency_ms.observe((now - t0) * 1000.0)
        self._batch_size.observe(len(enqueued_ats))
        if gen_lens is not None:
            for g in gen_lens:
                self._gen_len.observe(int(g))
        with self._lock:
            self._items += n_items
            if self._first_t is None:
                self._first_t = now
            self._last_t = now
            self._max_depth = max(self._max_depth, queue_depth)
            self._prefill_s += float(prefill_s)
            self._decode_s += float(decode_s)
            # prefill answers for the real prompt tokens it consumed PLUS
            # the first generated token of each request (it sampled them);
            # decode answers for the rest
            n_req = len(gen_lens) if gen_lens else 0
            self._prefill_tokens += int(prompt_tokens) + n_req
            if gen_lens:
                self._decode_tokens += int(sum(gen_lens)) - n_req

    # ------------------------------------------------------------------ #
    # continuous-scheduler instruments (serving/scheduler.py): the
    # scheduler has no "batch" — requests retire one by one and device
    # time accrues per prefill call / per decode step

    def record_request(
        self, enqueued_at: float, gen_len: int,
        adapter: Optional[str] = None,
    ) -> None:
        """One RETIRED request: end-to-end latency + generated length.

        ``adapter`` (the request's LoRA adapter name) additionally lands
        the observation in that tenant's own instruments, so one snapshot
        answers per-tenant latency questions without a second ledger."""
        now = time.monotonic()
        self._latency_ms.observe((now - enqueued_at) * 1000.0)
        self._gen_len.observe(int(gen_len))
        if adapter is not None:
            lat_h, gen_h = self._adapter_instruments(adapter)
            lat_h.observe((now - enqueued_at) * 1000.0)
            gen_h.observe(int(gen_len))
            self._registry.counter(
                self.adapter_name(adapter, "requests")
            ).inc()
        with self._lock:
            self._items += int(gen_len)
            if self._first_t is None:
                self._first_t = now
            self._last_t = now

    def record_prefill(
        self, prompt_tokens: int, n_requests: int, prefill_s: float
    ) -> None:
        """One prefill call: suffix tokens consumed + token 0 per row."""
        with self._lock:
            self._prefill_tokens += int(prompt_tokens) + int(n_requests)
            self._prefill_s += float(prefill_s)

    def record_decode(self, n_tokens: int, decode_s: float) -> None:
        """One decode step: tokens sampled across the occupied slots."""
        with self._lock:
            self._decode_tokens += int(n_tokens)
            self._decode_s += float(decode_s)

    def record_iteration(
        self,
        active_slots: int,
        total_slots: int,
        blocks_in_use: int,
        total_blocks: int,
    ) -> None:
        """Scheduler-state sample at one decode iteration."""
        self._slot_occ.observe(active_slots / max(total_slots, 1))
        self._block_util.observe(blocks_in_use / max(total_blocks, 1))

    def record_tick(self, host_ms: float) -> None:
        """One scheduler tick's HOST overhead: wall time minus the spans
        spent blocked on device readbacks — what the accelerator idles
        through between dispatches on the sync path."""
        self._tick_host_ms.observe(float(host_ms))

    def record_dispatch_gap(self, gap_ms: float) -> None:
        """Host wall time between two consecutive decode dispatch
        enqueues during back-to-back decode ticks.  The sync path's gap
        includes the full readback + bookkeeping window; the async
        pipeline's is bookkeeping only."""
        self._dispatch_gap_ms.observe(float(gap_ms))

    def record_scale_up_ready(self, ms: float) -> None:
        """Wall ms from replica construction to warm (all programs
        compiled) at autoscaler scale-up — the cold-compile TTFT a
        warmed ``add_replica`` no longer pays on first traffic."""
        with self._lock:
            self._scale_up_ready_ms = float(ms)
        self._registry.gauge("scale_up_ready_ms").set(float(ms))

    def record_kv_transfer(
        self, *, nbytes: int, seconds: float, blocks: int
    ) -> None:
        """One serviced KV-block import (disaggregated serving): bytes
        and blocks that actually landed plus the host-staging wall time.
        Rejected payloads are counted by the scheduler's
        ``kv_transfer_rejects`` counter, not here."""
        if nbytes:
            self._registry.counter("kv_transfer_bytes").inc(int(nbytes))
        if blocks:
            self._registry.counter("kv_transfer_blocks").inc(int(blocks))
        self._kv_transfer_ms.observe(float(seconds) * 1000.0)

    def observe_depth(self, depth: int) -> None:
        with self._lock:
            self._max_depth = max(self._max_depth, depth)

    def record_health(self, health: Dict[str, object]) -> None:
        """Mirror a scheduler health snapshot into ``health_*`` gauges.

        The prefix keeps gauges out of the counter namespace
        (``engine_restarts`` is already a counter in this registry and
        :class:`MetricsRegistry` rejects cross-type name reuse).  Only
        numeric/bool fields are mirrored; a None ``last_tick_age_s``
        (no tick yet) is skipped rather than encoded as a sentinel.
        """
        for key, val in health.items():
            if isinstance(val, bool):
                self._registry.gauge(f"health_{key}").set(1.0 if val else 0.0)
            elif isinstance(val, (int, float)):
                self._registry.gauge(f"health_{key}").set(float(val))

    def snapshot(self) -> Dict[str, float]:
        """Aggregate view: p50/p99 latency, items/sec, batch occupancy."""
        lat = self._latency_ms.snapshot()
        sizes = self._batch_size.snapshot()
        gen = self._gen_len.snapshot()
        occ = self._slot_occ.snapshot()
        util = self._block_util.snapshot()
        with self._lock:
            span = (
                (self._last_t - self._first_t)
                if self._first_t is not None and self._last_t > self._first_t
                else 0.0
            )
            items = self._items
            depth = self._max_depth
            prefill_tokens = self._prefill_tokens
            decode_tokens = self._decode_tokens
            prefill_s = self._prefill_s
            decode_s = self._decode_s
        out = {
            "requests": int(lat["count"]),
            "batches": int(sizes["count"]),
            "items": int(items),
            "max_queue_depth": int(depth),
        }
        out.update({k: v for k, v in self._registry.counters().items() if v})
        if lat["count"]:
            out["latency_ms_p50"] = float(lat["p50"])
            out["latency_ms_p99"] = float(lat["p99"])
            out["latency_ms_mean"] = float(lat["mean"])
        if sizes["count"]:
            out["batch_size_mean"] = float(sizes["mean"])
        # open-loop throughput needs a time span; a single flush has none,
        # so fall back to unreported rather than divide-by-zero noise
        if span > 0:
            out["items_per_sec"] = float(items / span)
        if gen["count"]:
            out["gen_tokens"] = int(gen["sum"])
            out["gen_len_mean"] = float(gen["mean"])
            out["gen_len_p50"] = float(gen["p50"])
        # phase rates: each phase is divided by the tokens it actually
        # produced/consumed — generated token 0 is a PREFILL token (the
        # prefill program samples it), the remaining gen tokens are
        # decode's.  Fixes the round-6 attribution skew that inflated
        # decode throughput by one token per request.
        if prefill_s > 0 and prefill_tokens:
            out["prefill_tokens_per_sec"] = float(prefill_tokens / prefill_s)
        if decode_s > 0 and decode_tokens:
            out["decode_tokens_per_sec"] = float(decode_tokens / decode_s)
        # continuous-scheduler shape (absent on the batcher path)
        if occ["count"]:
            out["slot_occupancy_mean"] = float(occ["mean"])
        if util["count"]:
            out["block_util_mean"] = float(util["mean"])
            out["block_util_max"] = float(util["max"])
        xfer = self._kv_transfer_ms.snapshot()
        if xfer["count"]:
            out["kv_transfer_ms_p50"] = float(xfer["p50"])
            out["kv_transfer_ms_p99"] = float(xfer["p99"])
        # async-pipeline observability (absent until a tick/dispatch-gap
        # sample lands, keeping batcher-path snapshots byte-stable)
        tick = self._tick_host_ms.snapshot()
        if tick["count"]:
            out["tick_host_ms_p50"] = float(tick["p50"])
            out["tick_host_ms_p99"] = float(tick["p99"])
            out["tick_host_ms_mean"] = float(tick["mean"])
        gap = self._dispatch_gap_ms.snapshot()
        if gap["count"]:
            out["decode_dispatch_gap_ms_p50"] = float(gap["p50"])
            out["decode_dispatch_gap_ms_p99"] = float(gap["p99"])
            out["decode_dispatch_gap_ms_mean"] = float(gap["mean"])
        with self._lock:
            ready_ms = self._scale_up_ready_ms
        if ready_ms is not None:
            out["scale_up_ready_ms"] = float(ready_ms)
        counters = self._registry.counters()
        hits = counters.get("prefix_hit_blocks", 0)
        misses = counters.get("prefix_miss_blocks", 0)
        if hits + misses:
            out["prefix_hit_rate"] = float(hits / (hits + misses))
        # speculative decode: fraction of draft proposals the target kept
        # (the bonus token is free and not counted on either side)
        proposed = counters.get("spec_proposed", 0)
        if proposed:
            rate = float(counters.get("spec_accepted", 0) / proposed)
            out["spec_acceptance_rate"] = rate
            floor = float(self.spec_min_acceptance or 0.0)
            if floor > 0.0 and rate < floor:
                out["spec_acceptance_below_floor"] = 1.0
                with self._lock:
                    warn = not self._spec_floor_warned
                    self._spec_floor_warned = True
                if warn:
                    logging.getLogger(__name__).warning(
                        "speculative acceptance rate %.1f%% is below the "
                        "configured serving.speculative.min_acceptance "
                        "floor %.1f%% — draft verification is costing "
                        "decode latency, not saving it; disable "
                        "serving.speculative or use a stronger draft",
                        100.0 * rate, 100.0 * floor,
                    )
        # per-adapter (multi-LoRA) views: same shape as the flat latency
        # fields, one set per tenant that retired at least one request
        with self._lock:
            adapter_hists = dict(self._adapter_hists)
        for name, (lat_h, gen_h) in sorted(adapter_hists.items()):
            a_lat = lat_h.snapshot()
            a_gen = gen_h.snapshot()
            if a_lat["count"]:
                pre = self.adapter_name(name, "latency_ms")
                out[f"{pre}_p50"] = float(a_lat["p50"])
                out[f"{pre}_p99"] = float(a_lat["p99"])
                out[f"{pre}_mean"] = float(a_lat["mean"])
            if a_gen["count"]:
                out[self.adapter_name(name, "gen_tokens")] = int(a_gen["sum"])
        # health gauges ride along once record_health has run (absent
        # otherwise, keeping pre-resilience snapshots byte-stable)
        gauges = self._registry.snapshot()["gauges"]
        for name, g in gauges.items():
            out[name] = float(g["value"])
        return out

    def log_summary(self, logger, prefix: str = "serving") -> Dict[str, float]:
        snap = self.snapshot()
        parts = ", ".join(
            f"{k}={v:.2f}" if isinstance(v, float) else f"{k}={v}"
            for k, v in sorted(snap.items())
        )
        logger.info("%s metrics: %s", prefix, parts)
        return snap


# --------------------------------------------------------------------- #
# fleet aggregation

# additive fields: exact under summation (counts and token totals; every
# counter key not otherwise classified is summed too)
_AGG_SUM = ("requests", "batches", "items", "gen_tokens")
# distribution fields where the fleet view takes the worst replica: a
# percentile of merged samples cannot be recovered from per-replica
# percentiles, but the MAX is a valid (and operationally honest) bound
_AGG_MAX = (
    "latency_ms_p50", "latency_ms_p99", "max_queue_depth",
    "block_util_max", "kv_transfer_ms_p50", "kv_transfer_ms_p99",
    "tick_host_ms_p50", "tick_host_ms_p99",
    "decode_dispatch_gap_ms_p50", "decode_dispatch_gap_ms_p99",
    "scale_up_ready_ms",
)


def aggregate_snapshots(
    snapshots: Dict[str, Dict[str, float]]
) -> Dict[str, float]:
    """Fold per-replica :meth:`ServingMetrics.snapshot` dicts into one
    fleet view.

    Counts/token totals sum exactly; rates (``items_per_sec``,
    ``*_tokens_per_sec``) sum because the replicas serve concurrently;
    latency percentiles take the max across replicas (a bound, labeled as
    such by keeping the per-replica snapshots alongside); the prefix-cache
    hit rate is recomputed from the summed hit/miss block counters rather
    than averaged.  ``health_*``/gauge-like fields are per-replica state
    and are left to the sub-snapshots.
    """
    out: Dict[str, float] = {"replicas": len(snapshots)}
    sums: Dict[str, float] = {}
    maxes: Dict[str, float] = {}
    for snap in snapshots.values():
        for key, val in snap.items():
            if not isinstance(val, (int, float)) or isinstance(val, bool):
                continue
            if key in _AGG_MAX:
                maxes[key] = max(maxes.get(key, val), val)
            elif key.endswith("_per_sec") or key in _AGG_SUM or (
                not key.startswith("health_")
                and not key.endswith(("_mean", "_p50", "_p99", "_rate"))
            ):
                sums[key] = sums.get(key, 0) + val
    out.update(sums)
    out.update(maxes)
    hits = sums.get("prefix_hit_blocks", 0)
    misses = sums.get("prefix_miss_blocks", 0)
    if hits + misses:
        out["prefix_hit_rate"] = float(hits / (hits + misses))
    return out
