"""Serving-side metrics: latency percentiles, throughput, batch shape.

Latency is recorded per REQUEST (enqueue -> result set), so batching
delay is included — the number a client actually observes.  Throughput
counts work items (images for classification, generated tokens for LM)
over the window from the first to the last recorded request.

Storage is BOUNDED (telemetry/registry.py): per-request latencies,
batch sizes, and generated-token lengths land in Algorithm-R reservoir
histograms instead of the lists that previously grew one float per
request forever under sustained traffic.  Counts, sums, and means in the
snapshot stay exact (tracked outside the reservoir); the reported
percentiles are estimates of the TRUE stream percentiles once the stream
exceeds the reservoir (and exact below it, which keeps the snapshot
byte-stable for short runs and the existing tests).

Instruments live in a PRIVATE :class:`MetricsRegistry` (not the process
one): each engine owns its counts, and two engines in one process must
not share a ledger.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..telemetry.registry import MetricsRegistry

__all__ = ["ServingMetrics"]

# reservoir per distribution: big enough that p99 of a uniform sample is a
# tight estimate, small enough to cap memory at a few KB per engine
_RESERVOIR = 2048


class ServingMetrics:
    """Thread-safe accumulator; ``record_batch`` runs on the flush thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._registry = MetricsRegistry()
        self._latency_ms = self._registry.histogram("latency_ms", _RESERVOIR)
        self._batch_size = self._registry.histogram("batch_size", _RESERVOIR)
        self._gen_len = self._registry.histogram("gen_len", _RESERVOIR)
        self._items = 0
        self._first_t: Optional[float] = None
        self._last_t: Optional[float] = None
        self._max_depth = 0
        # LM phase split (round 6): accumulated prefill/decode device seconds
        # and prompt tokens, so the snapshot can report prefill vs decode
        # tokens/s separately
        self._prompt_tokens = 0
        self._prefill_s = 0.0
        self._decode_s = 0.0

    def incr(self, name: str, n: int = 1) -> None:
        """Bump a named degradation counter (e.g. ``timeouts``, ``sheds``)."""
        self._registry.counter(name).inc(n)

    def record_batch(
        self,
        enqueued_ats: List[float],
        n_items: int,
        queue_depth: int = 0,
        gen_lens: Optional[List[int]] = None,
        prompt_tokens: int = 0,
        prefill_s: float = 0.0,
        decode_s: float = 0.0,
    ) -> None:
        """One flushed batch: per-request enqueue stamps + work-item count.

        LM batches additionally pass ``gen_lens`` (generated tokens per
        request), ``prompt_tokens`` (REAL prompt tokens consumed, not the
        padded bucket area), and the measured ``prefill_s`` / ``decode_s``
        phase wall times.
        """
        now = time.monotonic()
        for t0 in enqueued_ats:
            self._latency_ms.observe((now - t0) * 1000.0)
        self._batch_size.observe(len(enqueued_ats))
        if gen_lens is not None:
            for g in gen_lens:
                self._gen_len.observe(int(g))
        with self._lock:
            self._items += n_items
            if self._first_t is None:
                self._first_t = now
            self._last_t = now
            self._max_depth = max(self._max_depth, queue_depth)
            self._prompt_tokens += int(prompt_tokens)
            self._prefill_s += float(prefill_s)
            self._decode_s += float(decode_s)

    def observe_depth(self, depth: int) -> None:
        with self._lock:
            self._max_depth = max(self._max_depth, depth)

    def snapshot(self) -> Dict[str, float]:
        """Aggregate view: p50/p99 latency, items/sec, batch occupancy."""
        lat = self._latency_ms.snapshot()
        sizes = self._batch_size.snapshot()
        gen = self._gen_len.snapshot()
        with self._lock:
            span = (
                (self._last_t - self._first_t)
                if self._first_t is not None and self._last_t > self._first_t
                else 0.0
            )
            items = self._items
            depth = self._max_depth
            prompt_tokens = self._prompt_tokens
            prefill_s = self._prefill_s
            decode_s = self._decode_s
        out = {
            "requests": int(lat["count"]),
            "batches": int(sizes["count"]),
            "items": int(items),
            "max_queue_depth": int(depth),
        }
        out.update({k: v for k, v in self._registry.counters().items() if v})
        if lat["count"]:
            out["latency_ms_p50"] = float(lat["p50"])
            out["latency_ms_p99"] = float(lat["p99"])
            out["latency_ms_mean"] = float(lat["mean"])
        if sizes["count"]:
            out["batch_size_mean"] = float(sizes["mean"])
        # open-loop throughput needs a time span; a single flush has none,
        # so fall back to unreported rather than divide-by-zero noise
        if span > 0:
            out["items_per_sec"] = float(items / span)
        if gen["count"]:
            out["gen_tokens"] = int(gen["sum"])
            out["gen_len_mean"] = float(gen["mean"])
            out["gen_len_p50"] = float(gen["p50"])
            # phase rates: prefill consumes real prompt tokens, decode emits
            # generated tokens (token 0 is sampled by the prefill program —
            # one token per request of attribution noise, documented rather
            # than corrected)
            if prefill_s > 0:
                out["prefill_tokens_per_sec"] = float(prompt_tokens / prefill_s)
            if decode_s > 0:
                out["decode_tokens_per_sec"] = float(gen["sum"] / decode_s)
        return out

    def log_summary(self, logger, prefix: str = "serving") -> Dict[str, float]:
        snap = self.snapshot()
        parts = ", ".join(
            f"{k}={v:.2f}" if isinstance(v, float) else f"{k}={v}"
            for k, v in sorted(snap.items())
        )
        logger.info("%s metrics: %s", prefix, parts)
        return snap
