"""Autoregressive generation over the TransformerLM KV-cache decode mode.

One jit program pair per (batch bucket, sequence bucket): ``prefill``
consumes the prompt batch in a single pass (filling the KV cache and
sampling the first token), ``decode`` runs a ``lax.while_loop`` of
single-token steps.  The whole batch shares the programs, but every row
carries its own ``prompt_len`` — prompts are right-padded to the bucket's
sequence length and the per-row cache positions (ops/attention.py) keep
padded rows exact.

``while_loop`` rather than ``scan`` so a batch whose rows all hit EOS
stops paying decode steps (the EOS early-exit of the ISSUE): the carry is
scan-shaped, the trip count is data-dependent.

The two phases are separate XLA programs (round 6) so the engine can time
them independently — prefill is compute-bound (one big batched forward),
decode is latency-bound (max_new_tokens tiny steps); one fused program
hides which side a serving regression lives on.  ``build_generate_fn``
returns a callable object: ``__call__`` chains the phases (the original
contract), ``.prefill`` / ``.decode`` expose them for phase-timed serving.

Sampling is keyed PER ROW, PER TOKEN INDEX: row ``r`` of a batch draws
token ``i`` with ``fold_in(fold_in(rng, r), i)`` (token 0 is the one the
prefill program samples).  A row's token stream therefore depends only on
its own key and its own logits — never on batch composition — which is
what lets the continuous scheduler (serving/scheduler.py) re-batch rows
between decode steps and still reproduce the whole-batch path token for
token (the sampled-mode half of the decode-parity oracle).

``build_paged_fns`` is the paged twin over the block-table cache mode of
``ops/attention.py``: one prefill program per (batch, seq) bucket and ONE
single-token step program shared by every decode iteration, both over a
pool pytree threaded through the calls instead of a per-batch cache.

Multi-tenant decode modes (PR 17), all default-off:

  - ``quant=True`` (ops/quant.py): the DECODE programs expect the
    int8-quantized params tree and dequantize in-graph — weights rest in
    device memory at half/quarter the bytes, which is what memory-bound
    decode streams every step.  Prefill (compute-bound) keeps the plain
    tree, so each builder's two phases take DIFFERENT trees in quant
    mode; the engine/scheduler hold both.
  - ``adapter_ids`` (ops/lora.py): every paged program takes the per-row
    adapter-id array; it reaches the model only when the model was
    cloned with LoRA factors (-1 rows run the base model), so non-LoRA
    builds trace it as an ignored input and program counts are
    unchanged.
  - ``verify`` (serving/speculative.py): a prefill-shaped program that
    returns the FULL per-position logits instead of sampling one token —
    the target model scores a draft's k proposals in one batched step
    and the host does exact accept/reject on the logits.
  - ``copy_rows``: pool row gather/scatter for the speculative branch
    fork — copies a boundary block's committed rows into the branch's
    spare block (serving/kv_pool.py fork pattern) in one fixed-shape
    program.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.quant import dequantize_tree

__all__ = ["build_generate_fn", "build_paged_fns"]


def _make_sampler(temperature: float):
    """``sample(logits [B, V], keys [B]) -> tok [B]``: greedy argmax at
    temperature 0 (keys ignored), else a per-row categorical draw — vmapped
    so row r's draw consumes ONLY ``keys[r]`` and ``logits[r]`` and is
    bitwise independent of every other row."""
    if temperature == 0.0:
        return lambda logits, keys: jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sample(logits, keys):
        draw = lambda k, l: jax.random.categorical(k, l / temperature)
        return jax.vmap(draw)(keys, logits).astype(jnp.int32)

    return sample


def _row_keys(rng, b: int):
    """One independent PRNG key per batch row: ``fold_in(rng, row)``."""
    return jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        rng, jnp.arange(b, dtype=jnp.int32)
    )


def _token_keys(row_keys, index):
    """Key for generated-token ``index`` (scalar or [B]) of each row."""
    axis = 0 if jnp.ndim(index) else None
    return jax.vmap(jax.random.fold_in, in_axes=(0, axis))(row_keys, index)


class _GenerateFn:
    """``prefill`` + ``decode`` jit pair with the fused-call contract.

    ``prefill(params, tokens, prompt_len, rng) -> carry`` — fills the KV
    cache from the padded prompts and samples generated token 0.
    ``decode(params, prompt_len, carry) -> (out_tokens, gen_len)`` — the
    EOS-early-exit while_loop over single-token steps.
    ``__call__`` chains them, matching the pre-split ``generate`` contract
    (``decode_params`` overrides the tree the decode phase gets — the
    int8 tree when the builder was made with ``quant=True``).
    """

    def __init__(self, prefill, decode):
        self.prefill = prefill
        self.decode = decode

    def __call__(self, params, tokens, prompt_len, rng, decode_params=None):
        carry = self.prefill(params, tokens, prompt_len, rng)
        dp = params if decode_params is None else decode_params
        return self.decode(dp, prompt_len, carry)

    def _cache_size(self) -> int:
        """Total distinct XLA programs compiled (both phases) — feeds the
        engine's ``compile_count`` bucket-grid bound."""
        return self.prefill._cache_size() + self.decode._cache_size()


def build_generate_fn(
    model,
    max_new_tokens: int,
    temperature: float = 0.0,
    eos_id: Optional[int] = None,
    quant: bool = False,
):
    """Compile ``generate(params, tokens, prompt_len, rng)``.

    ``model``: a :class:`..models.transformer_lm.TransformerLM` (decode
    flag irrelevant — it is cloned with ``decode=True`` here).

    Returns a :class:`_GenerateFn` whose ``__call__`` maps ``tokens``
    [B, S] int32 (prompts right-padded to S) and ``prompt_len`` [B] int32
    (1 <= len <= S) to ``(out_tokens [B, max_new_tokens] int32,
    gen_len [B] int32)`` where ``gen_len`` counts valid generated tokens
    per row (including the EOS token when one was produced); positions
    past ``gen_len`` are 0.

    ``temperature == 0.0`` (static) is greedy argmax and ignores ``rng``;
    otherwise tokens are drawn from ``softmax(logits / temperature)``.

    ``quant=True``: the DECODE program's ``params`` argument is the
    int8-quantized tree (ops/quant.quantize_tree) and is dequantized
    in-graph; prefill still takes the plain tree.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    decode_model = model.clone(decode=True)
    max_len = model.max_len
    sample = _make_sampler(temperature)

    def hit_eos(tok):
        if eos_id is None:
            return jnp.zeros(tok.shape, bool)
        return tok == eos_id

    @jax.jit
    def prefill(params, tokens, prompt_len, rng):
        b, s = tokens.shape
        if s + max_new_tokens > max_len:
            # the last generated token's position is prompt_len-1+max_new
            # <= s-1+max_new; beyond the table the position gather would
            # clamp and silently reuse rows (same guard as training)
            raise ValueError(
                f"seq bucket {s} + max_new_tokens {max_new_tokens} exceeds "
                f"max_len {max_len}"
            )
        prefill_logits, variables = decode_model.apply(
            {"params": params}, tokens, mutable=["cache"]
        )
        cache = variables["cache"]
        # the first generated token comes from the prefill logits at each
        # row's last REAL position (right-padding means that is not s-1)
        last = jnp.take_along_axis(
            prefill_logits, (prompt_len - 1)[:, None, None], axis=1
        )[:, 0]
        row_keys = _row_keys(rng, b)
        tok = sample(last, _token_keys(row_keys, 0))
        done = hit_eos(tok)
        out = jnp.zeros((b, max_new_tokens), jnp.int32).at[:, 0].set(tok)
        gen_len = jnp.ones((b,), jnp.int32)
        return cache, tok, out, done, gen_len, row_keys

    @jax.jit
    def decode(params, prompt_len, carry):
        if quant:
            params = dequantize_tree(params, jnp.float32)
        cache0, tok0, out0, done0, gen_len0, row_keys0 = carry

        def cond(c):
            i, _, _, _, done, _, _ = c
            return (i < max_new_tokens) & ~done.all()

        def body(c):
            i, cache, prev, out, done, gen_len, row_keys = c
            # prev = generated token i-1, which sits at sequence position
            # prompt_len + i - 1; feeding it yields the logits for token i
            pos = prompt_len + i - 1
            logits, variables = decode_model.apply(
                {"params": params, "cache": cache},
                prev[:, None],
                jnp.minimum(pos, max_len - 1),
                mutable=["cache"],
            )
            cache = variables["cache"]
            tok = sample(logits[:, 0], _token_keys(row_keys, i))
            out = out.at[:, i].set(jnp.where(done, 0, tok))
            gen_len = gen_len + jnp.where(done, 0, 1).astype(jnp.int32)
            done = done | hit_eos(tok) | (pos + 1 >= max_len)
            return (i + 1, cache, tok, out, done, gen_len, row_keys)

        full = (jnp.int32(1), cache0, tok0, out0, done0, gen_len0, row_keys0)
        _, _, _, out, _, gen_len, _ = jax.lax.while_loop(cond, body, full)
        return out, gen_len

    return _GenerateFn(prefill, decode)


class _PagedFns:
    """Jit set + pool factory for the paged (block-table) cache mode.

    ``prefill(params, pool, tokens, positions, block_tables, last_col,
    row_keys, gen_index, adapter_ids) -> (tok, finite, pool)`` — scatter
    the suffix K/V into the pool and sample each row's token
    ``gen_index[r]`` from the logits at ``last_col`` (0 for a fresh
    prompt; the hot-restart replay path passes the index of the last
    already-delivered token so the resample is bitwise reproducible).
    ``decode_step(params, pool, prev_tok, pos, block_tables, row_keys,
    gen_index, adapter_ids) -> (tok, finite, pool)`` — ONE single-token
    step for every slot; the scheduler's host loop supplies fresh inputs
    per iteration, so this one program serves any mix of in-flight
    requests.  In quant mode ``params`` here is the int8 tree.
    ``decode_step_fed(params, pool, prev_tok, fresh_mask, fresh_tok, pos,
    block_tables, row_keys, gen_index, adapter_ids)`` — the async-pipeline
    twin of ``decode_step``: ``prev_tok`` is the PREVIOUS step's on-device
    token output fed back without a host round-trip, and rows whose last
    token the host knows better (just prefilled, refilled, or replayed)
    are spliced in-graph via ``where(fresh_mask, fresh_tok, prev_tok)``.
    Output carry (tok) is a valid ``prev_tok`` input to itself, so step
    k+1 can be dispatched before step k's tokens are read back.
    ``finite`` [B] bool is the on-device output guard: True iff every
    logit the row sampled from is finite — the serving mirror of the
    training anomaly guard, letting the scheduler evict a NaN-producing
    request without a Python exception (padding rows read stale pool
    rows, so only ACTIVE rows' flags are meaningful).
    ``verify(params, pool, tokens, positions, block_tables, adapter_ids)
    -> (logits [B, S, V] f32, pool)`` — the speculative-decoding scoring
    program: prefill-shaped (scatters the fed tokens' K/V), but returns
    EVERY position's logits so the host can accept/reject a draft's k
    proposals from one call.  Always takes the PLAIN params tree, even
    in quant mode: verification is the accuracy anchor.
    ``copy_rows(pool, src, dst) -> pool`` — copy pool rows ``src[i]`` to
    ``dst[i]`` across every cache leaf (OOB ``dst`` entries drop): the
    speculative fork's boundary-block CoW into the spare block.
    ``init_pool(params)`` — the zero pool pytree (``jax.eval_shape`` over
    the apply: correct flax cache paths, no throwaway compile).
    """

    def __init__(self, prefill, decode_step, init_pool, verify, copy_rows,
                 decode_step_fed):
        self.prefill = prefill
        self.decode_step = decode_step
        self.init_pool = init_pool
        self.verify = verify
        self.copy_rows = copy_rows
        self.decode_step_fed = decode_step_fed

    def _cache_size(self) -> int:
        """Distinct XLA programs compiled across all phases — the
        scheduler's compile count is bounded by the bucket grid for
        prefill plus ONE program each for decode/verify/copy (plus one
        for the self-feeding async decode step, compiled only when the
        pipeline is enabled), independent of traffic."""
        return (
            self.prefill._cache_size()
            + self.decode_step._cache_size()
            + self.verify._cache_size()
            + self.copy_rows._cache_size()
            + self.decode_step_fed._cache_size()
        )


def build_paged_fns(
    model,
    block_size: int,
    num_blocks: int,
    temperature: float = 0.0,
    quant: bool = False,
):
    """Compile the paged prefill/decode/verify set over a shared block pool.

    Shapes are the scheduler's contract: ``tokens``/``positions`` are
    [B, S] (positions are GLOBAL sequence positions per token, -1 =
    padding — one program handles cold prefill, prefix-hit suffix prefill,
    and S=1 decode alike), ``block_tables`` is [B, T] physical block ids
    covering each row's whole reserved footprint, ``last_col`` [B] is the
    column of each row's final real token, ``row_keys`` [B] the per-row
    PRNG keys, ``gen_index`` [B] each row's generated-token index (rows
    sit at DIFFERENT indices under continuous batching).  Every array is
    fixed-width; inactive rows ride along with position -1 (their scatter
    drops, their sampled token is ignored host-side).

    ``adapter_ids`` [B] int32 (-1 = base model) reaches the model only
    when it was cloned with LoRA factors — non-LoRA builds trace it as an
    unused input, so signatures (and compile counts) stay uniform across
    modes.  ``quant=True`` makes ``decode_step`` expect the int8 tree
    (ops/quant.quantize_tree) and dequantize in-graph; prefill and verify
    keep the plain tree.
    """
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    if num_blocks < 1:
        raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    paged_model = model.clone(
        decode=True, paged=True,
        kv_block_size=int(block_size), kv_num_blocks=int(num_blocks),
    )
    has_lora = getattr(paged_model, "lora_adapters", 0) > 0
    pool_rows = int(num_blocks) * int(block_size)
    # no eos_id here: EOS detection is the HOST's job in paged mode — the
    # scheduler reads every token anyway (to stream it and retire slots),
    # so the programs stay pure token-samplers and the stop conditions
    # (eos / per-request max_new) live in one place
    sample = _make_sampler(temperature)

    def _apply(params, pool, tokens, positions, block_tables, adapter_ids):
        args = (tokens, positions, block_tables)
        if has_lora:
            args = args + (adapter_ids,)
        return paged_model.apply(
            {"params": params, "cache": pool}, *args, mutable=["cache"],
        )

    @jax.jit
    def prefill(
        params, pool, tokens, positions, block_tables, last_col, row_keys,
        gen_index, adapter_ids=None,
    ):
        logits, variables = _apply(
            params, pool, tokens, positions, block_tables, adapter_ids
        )
        last = jnp.take_along_axis(logits, last_col[:, None, None], axis=1)[:, 0]
        tok = sample(last, _token_keys(row_keys, gen_index))
        return tok, jnp.isfinite(last).all(axis=-1), variables["cache"]

    @jax.jit
    def decode_step(
        params, pool, prev_tok, pos, block_tables, row_keys, gen_index,
        adapter_ids=None,
    ):
        if quant:
            params = dequantize_tree(params, jnp.float32)
        logits, variables = _apply(
            params, pool, prev_tok[:, None], pos[:, None], block_tables,
            adapter_ids,
        )
        tok = sample(logits[:, 0], _token_keys(row_keys, gen_index))
        return tok, jnp.isfinite(logits[:, 0]).all(axis=-1), variables["cache"]

    @jax.jit
    def decode_step_fed(
        params, pool, prev_tok, fresh_mask, fresh_tok, pos, block_tables,
        row_keys, gen_index, adapter_ids=None,
    ):
        if quant:
            params = dequantize_tree(params, jnp.float32)
        # prev_tok is the previous step's ON-DEVICE token output; rows the
        # host just (re)filled get their known last token spliced in here,
        # so the pipeline never needs a host round-trip to mix fresh rows
        # into the carried batch
        prev = jnp.where(fresh_mask, fresh_tok, prev_tok)
        logits, variables = _apply(
            params, pool, prev[:, None], pos[:, None], block_tables,
            adapter_ids,
        )
        tok = sample(logits[:, 0], _token_keys(row_keys, gen_index))
        return tok, jnp.isfinite(logits[:, 0]).all(axis=-1), variables["cache"]

    @jax.jit
    def verify(params, pool, tokens, positions, block_tables, adapter_ids=None):
        logits, variables = _apply(
            params, pool, tokens, positions, block_tables, adapter_ids
        )
        return logits.astype(jnp.float32), variables["cache"]

    @jax.jit
    def copy_rows(pool, src, dst):
        src_c = jnp.clip(src, 0, pool_rows - 1)

        def cp(leaf):
            if (
                hasattr(leaf, "ndim") and leaf.ndim >= 1
                and leaf.shape[0] == pool_rows
            ):
                return leaf.at[dst].set(leaf[src_c], mode="drop")
            return leaf

        return jax.tree_util.tree_map(cp, pool)

    def init_pool(params):
        # any concrete shapes work — the pool's shape depends only on the
        # model config, and eval_shape never touches device memory
        init_args = [
            jnp.zeros((1, 1), jnp.int32),
            jnp.zeros((1, 1), jnp.int32),
            jnp.zeros((1, 1), jnp.int32),
        ]
        if has_lora:
            init_args.append(jnp.zeros((1,), jnp.int32))
        shapes = jax.eval_shape(
            lambda p: paged_model.apply(
                {"params": p}, *init_args, mutable=["cache"],
            )[1]["cache"],
            params,
        )
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes
        )

    return _PagedFns(
        prefill, decode_step, init_pool, verify, copy_rows, decode_step_fed
    )
