"""Autoregressive generation over the TransformerLM KV-cache decode mode.

One jit program per (batch bucket, sequence bucket): prefill the prompt
batch in a single pass, then a ``lax.while_loop`` of single-token steps.
The whole batch shares the program, but every row carries its own
``prompt_len`` — prompts are right-padded to the bucket's sequence length
and the per-row cache positions (ops/attention.py) keep padded rows exact.

``while_loop`` rather than ``scan`` so a batch whose rows all hit EOS
stops paying decode steps (the EOS early-exit of the ISSUE): the carry is
scan-shaped, the trip count is data-dependent.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["build_generate_fn"]


def build_generate_fn(
    model,
    max_new_tokens: int,
    temperature: float = 0.0,
    eos_id: Optional[int] = None,
):
    """Compile ``generate(params, tokens, prompt_len, rng)``.

    ``model``: a :class:`..models.transformer_lm.TransformerLM` (decode
    flag irrelevant — it is cloned with ``decode=True`` here).

    Returns a jitted function mapping ``tokens`` [B, S] int32 (prompts
    right-padded to S) and ``prompt_len`` [B] int32 (1 <= len <= S) to
    ``(out_tokens [B, max_new_tokens] int32, gen_len [B] int32)`` where
    ``gen_len`` counts valid generated tokens per row (including the EOS
    token when one was produced); positions past ``gen_len`` are 0.

    ``temperature == 0.0`` (static) is greedy argmax and ignores ``rng``;
    otherwise tokens are drawn from ``softmax(logits / temperature)``.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    decode_model = model.clone(decode=True)
    max_len = model.max_len

    def sample(logits, rng):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(rng, logits / temperature).astype(jnp.int32)

    def hit_eos(tok):
        if eos_id is None:
            return jnp.zeros(tok.shape, bool)
        return tok == eos_id

    @jax.jit
    def generate(params, tokens, prompt_len, rng):
        b, s = tokens.shape
        if s + max_new_tokens > max_len:
            # the last generated token's position is prompt_len-1+max_new
            # <= s-1+max_new; beyond the table the position gather would
            # clamp and silently reuse rows (same guard as training)
            raise ValueError(
                f"seq bucket {s} + max_new_tokens {max_new_tokens} exceeds "
                f"max_len {max_len}"
            )
        prefill_logits, variables = decode_model.apply(
            {"params": params}, tokens, mutable=["cache"]
        )
        cache = variables["cache"]
        # the first generated token comes from the prefill logits at each
        # row's last REAL position (right-padding means that is not s-1)
        last = jnp.take_along_axis(
            prefill_logits, (prompt_len - 1)[:, None, None], axis=1
        )[:, 0]
        rng, sub = jax.random.split(rng)
        tok = sample(last, sub)
        done = hit_eos(tok)
        out = jnp.zeros((b, max_new_tokens), jnp.int32).at[:, 0].set(tok)
        gen_len = jnp.ones((b,), jnp.int32)

        def cond(carry):
            i, _, _, _, done, _, _ = carry
            return (i < max_new_tokens) & ~done.all()

        def body(carry):
            i, cache, prev, out, done, gen_len, rng = carry
            # prev = generated token i-1, which sits at sequence position
            # prompt_len + i - 1; feeding it yields the logits for token i
            pos = prompt_len + i - 1
            logits, variables = decode_model.apply(
                {"params": params, "cache": cache},
                prev[:, None],
                jnp.minimum(pos, max_len - 1),
                mutable=["cache"],
            )
            cache = variables["cache"]
            rng, sub = jax.random.split(rng)
            tok = sample(logits[:, 0], sub)
            out = out.at[:, i].set(jnp.where(done, 0, tok))
            gen_len = gen_len + jnp.where(done, 0, 1).astype(jnp.int32)
            done = done | hit_eos(tok) | (pos + 1 >= max_len)
            return (i + 1, cache, tok, out, done, gen_len, rng)

        carry = (jnp.int32(1), cache, tok, out, done, gen_len, rng)
        _, _, _, out, _, gen_len, _ = jax.lax.while_loop(cond, body, carry)
        return out, gen_len

    return generate
