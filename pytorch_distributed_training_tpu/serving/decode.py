"""Autoregressive generation over the TransformerLM KV-cache decode mode.

One jit program pair per (batch bucket, sequence bucket): ``prefill``
consumes the prompt batch in a single pass (filling the KV cache and
sampling the first token), ``decode`` runs a ``lax.while_loop`` of
single-token steps.  The whole batch shares the programs, but every row
carries its own ``prompt_len`` — prompts are right-padded to the bucket's
sequence length and the per-row cache positions (ops/attention.py) keep
padded rows exact.

``while_loop`` rather than ``scan`` so a batch whose rows all hit EOS
stops paying decode steps (the EOS early-exit of the ISSUE): the carry is
scan-shaped, the trip count is data-dependent.

The two phases are separate XLA programs (round 6) so the engine can time
them independently — prefill is compute-bound (one big batched forward),
decode is latency-bound (max_new_tokens tiny steps); one fused program
hides which side a serving regression lives on.  ``build_generate_fn``
returns a callable object: ``__call__`` chains the phases (the original
contract), ``.prefill`` / ``.decode`` expose them for phase-timed serving.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["build_generate_fn"]


class _GenerateFn:
    """``prefill`` + ``decode`` jit pair with the fused-call contract.

    ``prefill(params, tokens, prompt_len, rng) -> carry`` — fills the KV
    cache from the padded prompts and samples generated token 0.
    ``decode(params, prompt_len, carry) -> (out_tokens, gen_len)`` — the
    EOS-early-exit while_loop over single-token steps.
    ``__call__`` chains them, matching the pre-split ``generate`` contract.
    """

    def __init__(self, prefill, decode):
        self.prefill = prefill
        self.decode = decode

    def __call__(self, params, tokens, prompt_len, rng):
        carry = self.prefill(params, tokens, prompt_len, rng)
        return self.decode(params, prompt_len, carry)

    def _cache_size(self) -> int:
        """Total distinct XLA programs compiled (both phases) — feeds the
        engine's ``compile_count`` bucket-grid bound."""
        return self.prefill._cache_size() + self.decode._cache_size()


def build_generate_fn(
    model,
    max_new_tokens: int,
    temperature: float = 0.0,
    eos_id: Optional[int] = None,
):
    """Compile ``generate(params, tokens, prompt_len, rng)``.

    ``model``: a :class:`..models.transformer_lm.TransformerLM` (decode
    flag irrelevant — it is cloned with ``decode=True`` here).

    Returns a :class:`_GenerateFn` whose ``__call__`` maps ``tokens``
    [B, S] int32 (prompts right-padded to S) and ``prompt_len`` [B] int32
    (1 <= len <= S) to ``(out_tokens [B, max_new_tokens] int32,
    gen_len [B] int32)`` where ``gen_len`` counts valid generated tokens
    per row (including the EOS token when one was produced); positions
    past ``gen_len`` are 0.

    ``temperature == 0.0`` (static) is greedy argmax and ignores ``rng``;
    otherwise tokens are drawn from ``softmax(logits / temperature)``.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    decode_model = model.clone(decode=True)
    max_len = model.max_len

    def sample(logits, rng):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(rng, logits / temperature).astype(jnp.int32)

    def hit_eos(tok):
        if eos_id is None:
            return jnp.zeros(tok.shape, bool)
        return tok == eos_id

    @jax.jit
    def prefill(params, tokens, prompt_len, rng):
        b, s = tokens.shape
        if s + max_new_tokens > max_len:
            # the last generated token's position is prompt_len-1+max_new
            # <= s-1+max_new; beyond the table the position gather would
            # clamp and silently reuse rows (same guard as training)
            raise ValueError(
                f"seq bucket {s} + max_new_tokens {max_new_tokens} exceeds "
                f"max_len {max_len}"
            )
        prefill_logits, variables = decode_model.apply(
            {"params": params}, tokens, mutable=["cache"]
        )
        cache = variables["cache"]
        # the first generated token comes from the prefill logits at each
        # row's last REAL position (right-padding means that is not s-1)
        last = jnp.take_along_axis(
            prefill_logits, (prompt_len - 1)[:, None, None], axis=1
        )[:, 0]
        rng, sub = jax.random.split(rng)
        tok = sample(last, sub)
        done = hit_eos(tok)
        out = jnp.zeros((b, max_new_tokens), jnp.int32).at[:, 0].set(tok)
        gen_len = jnp.ones((b,), jnp.int32)
        return cache, tok, out, done, gen_len, rng

    @jax.jit
    def decode(params, prompt_len, carry):
        cache0, tok0, out0, done0, gen_len0, rng0 = carry

        def cond(c):
            i, _, _, _, done, _, _ = c
            return (i < max_new_tokens) & ~done.all()

        def body(c):
            i, cache, prev, out, done, gen_len, rng = c
            # prev = generated token i-1, which sits at sequence position
            # prompt_len + i - 1; feeding it yields the logits for token i
            pos = prompt_len + i - 1
            logits, variables = decode_model.apply(
                {"params": params, "cache": cache},
                prev[:, None],
                jnp.minimum(pos, max_len - 1),
                mutable=["cache"],
            )
            cache = variables["cache"]
            rng, sub = jax.random.split(rng)
            tok = sample(logits[:, 0], sub)
            out = out.at[:, i].set(jnp.where(done, 0, tok))
            gen_len = gen_len + jnp.where(done, 0, 1).astype(jnp.int32)
            done = done | hit_eos(tok) | (pos + 1 >= max_len)
            return (i + 1, cache, tok, out, done, gen_len, rng)

        full = (jnp.int32(1), cache0, tok0, out0, done0, gen_len0, rng0)
        _, _, _, out, _, gen_len, _ = jax.lax.while_loop(cond, body, full)
        return out, gen_len

    return _GenerateFn(prefill, decode)
