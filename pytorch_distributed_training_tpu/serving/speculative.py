"""Speculative decoding: draft-proposed tokens, target-verified exactly.

Decode is latency-bound because each new token costs one full serial
forward pass of the target model.  Speculative decoding (Leviathan et
al. 2023; Chen et al. 2023) breaks the serial chain: a cheap DRAFT model
proposes ``k`` tokens autoregressively, then the target model scores all
``k+1`` positions in ONE batched forward (the ``verify`` program in
serving/decode.py — prefill-shaped, full logits out) and the host keeps
the longest prefix the target agrees with.  Every committed token is the
TARGET's own choice, so the output distribution is exactly the target
model's — the draft only decides how many target-forwards one round
amortizes.

The scheduler (serving/scheduler.py) runs the greedy (temperature-0)
specialization: the draft proposes its argmax chain, the target's
per-position argmax is computed host-side from the verify logits, and
:func:`greedy_accept` keeps proposals while they match — equivalent to
the general rule below with a point-mass draft distribution, and what
makes the committed stream token-identical to plain greedy decode (the
parity oracle).  :func:`sampled_accept` is the full Leviathan
rejection-sampling rule for temperature > 0, kept as a pure,
unit-tested function until the scheduler grows a sampled mode.

:class:`SpeculativeSpec` carries the engine's choices: ``k`` and an
optional dedicated draft model + params.  No draft configured means
SELF-draft (draft == target): useless for speedup, but its acceptance
rate is 1.0 by construction — the end-to-end pin that verification and
pool forking are exact.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

__all__ = ["SpeculativeSpec", "greedy_accept", "sampled_accept"]


class SpeculativeSpec:
    """Engine-level speculative config: draft length + draft model.

    ``draft_model``/``draft_params`` come as a pair or not at all (absent
    = self-draft).  The draft gets its OWN compiled program set and its
    OWN paged pool in the scheduler — draft K/V and target K/V must never
    share rows.
    """

    __slots__ = ("k", "draft_model", "draft_params")

    def __init__(self, k: int, draft_model=None, draft_params=None):
        k = int(k)
        if k < 1:
            raise ValueError(f"serving.speculative.k must be >= 1, got {k}")
        if (draft_model is None) != (draft_params is None):
            raise ValueError(
                "draft_model and draft_params must be given together"
            )
        self.k = k
        self.draft_model = draft_model
        self.draft_params = draft_params


def greedy_accept(draft_tokens, target_tokens) -> Tuple[int, List[int]]:
    """Temperature-0 accept rule: ``(n_accepted, emitted_tokens)``.

    ``draft_tokens`` are the draft's ``k`` proposals for generated-token
    indices ``g .. g+k-1``; ``target_tokens`` are the target's argmax at
    the ``k+1`` verify positions (``target_tokens[j]`` is the target's
    choice for index ``g+j``, the bonus row included).  Proposals are
    kept while they equal the target's choice; the first mismatch emits
    the target's correction and stops; a clean sweep emits the bonus.
    Every emitted token is the target's argmax, so
    ``1 <= len(emitted) <= k+1`` and the committed stream equals plain
    greedy decode regardless of the draft.  (The caller trims the bonus
    when the per-request ``max_new`` cap has no room for it.)
    """
    draft = [int(t) for t in draft_tokens]
    target = [int(t) for t in target_tokens]
    if len(target) != len(draft) + 1:
        raise ValueError(
            f"need k+1 target tokens for k draft tokens, got "
            f"{len(target)} for {len(draft)}"
        )
    emitted: List[int] = []
    for j, d in enumerate(draft):
        t = target[j]
        emitted.append(t)
        if d != t:
            return j, emitted
    emitted.append(target[len(draft)])
    return len(draft), emitted


def sampled_accept(
    draft_tokens, draft_probs, target_probs, rng: np.random.Generator
) -> Tuple[int, List[int]]:
    """Leviathan rejection sampling: ``(n_accepted, emitted_tokens)``.

    ``draft_probs`` [k, V] are the draft's sampling distributions q, one
    per proposal; ``target_probs`` [k+1, V] the target's p at the verify
    positions.  Proposal ``d_j`` is accepted with probability
    ``min(1, p_j(d_j) / q_j(d_j))``; on rejection a correction is drawn
    from the residual ``normalize(max(p_j - q_j, 0))`` and the round
    stops; a clean sweep draws the bonus from ``p_k``.  The emitted
    marginals are EXACTLY p — the property that makes speculative
    decoding a latency optimization rather than an approximation.  With
    a point-mass q (greedy draft) this degenerates to
    :func:`greedy_accept` in distribution.
    """
    draft = [int(t) for t in draft_tokens]
    p = np.asarray(target_probs, np.float64)
    q = np.asarray(draft_probs, np.float64)
    if p.ndim != 2 or q.ndim != 2 or p.shape[0] != len(draft) + 1:
        raise ValueError(
            f"need target_probs [k+1, V] and draft_probs [k, V], got "
            f"{p.shape} / {q.shape} for k={len(draft)}"
        )
    emitted: List[int] = []
    for j, d in enumerate(draft):
        accept = min(1.0, p[j, d] / max(q[j, d], 1e-300))
        if rng.random() < accept:
            emitted.append(d)
            continue
        resid = np.maximum(p[j] - q[j], 0.0)
        z = resid.sum()
        dist = resid / z if z > 0.0 else p[j] / p[j].sum()
        emitted.append(int(rng.choice(dist.size, p=dist)))
        return j, emitted
    bonus = p[len(draft)] / p[len(draft)].sum()
    emitted.append(int(rng.choice(bonus.size, p=bonus)))
    return len(draft), emitted
