"""Dynamic micro-batching: the serving-side analog of large-batch training.

Requests land on a queue and a single flush thread groups them into
batches, releasing a batch when either (a) ``max_batch_size`` requests are
waiting — the accelerator-saturation bound — or (b) the OLDEST waiting
request has been queued for ``max_delay_ms`` — the latency bound.  Each
``submit`` returns a ``concurrent.futures.Future`` resolved with that
request's slice of the batch result (or its exception), so callers block
only on their own request.

The batcher is shape-agnostic: it hands the runner a list of
``(payload, meta)`` pairs and the runner (``InferenceEngine._run_batch``)
does the bucketing/padding, so the number of distinct XLA compiles stays
bounded by the engine's bucket grid, not by client batch arithmetic.

Graceful degradation under overload (both off by default):

  - per-request deadlines (``deadline_ms``): a request still queued past
    its deadline resolves with ``TimeoutError`` at collection time instead
    of occupying a flush slot — under backlog, work nobody is waiting for
    anymore stops displacing work somebody is;
  - bounded-queue load shedding (``max_backlog``): beyond the configured
    backlog, ``submit`` fails fast with :class:`OverloadedError` rather
    than growing an unbounded queue of doomed requests.

Both are counted (``timeouts``/``sheds``) and surfaced through optional
callbacks so ``ServingMetrics`` can aggregate them.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence, Tuple

__all__ = ["DynamicBatcher", "OverloadedError", "Request"]


class OverloadedError(RuntimeError):
    """Rejected by load shedding: the batcher's backlog is full."""


class Request:
    """One queued payload plus its result future and enqueue timestamp."""

    __slots__ = ("payload", "meta", "future", "enqueued_at", "deadline")

    def __init__(self, payload, meta, deadline: Optional[float] = None):
        self.payload = payload
        self.meta = dict(meta)
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()
        # absolute time.monotonic() deadline; None = wait forever
        self.deadline = deadline


class DynamicBatcher:
    """Queue + flush thread grouping requests into bounded batches.

    ``run_batch(requests)`` is called on the flush thread with 1..max_batch
    requests and must return one result per request (same order); it may
    instead set futures itself and return None.  Exceptions it raises are
    propagated to every future in the batch.
    """

    def __init__(
        self,
        run_batch: Callable[[Sequence[Request]], Optional[List[Any]]],
        max_batch_size: int,
        max_delay_ms: float,
        deadline_ms: Optional[float] = None,
        max_backlog: Optional[int] = None,
        on_timeout: Optional[Callable[[], None]] = None,
        on_shed: Optional[Callable[[], None]] = None,
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got {max_batch_size}")
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        if max_backlog is not None and max_backlog < 1:
            raise ValueError(f"max_backlog must be >= 1, got {max_backlog}")
        self._run_batch = run_batch
        self.max_batch_size = int(max_batch_size)
        self.max_delay = max_delay_ms / 1000.0
        self.deadline_ms = deadline_ms
        self.max_backlog = max_backlog
        self.timeouts = 0
        self.sheds = 0
        self._on_timeout = on_timeout
        self._on_shed = on_shed
        self._queue: "queue.Queue[Optional[Request]]" = queue.Queue()
        self._closed = False
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, name="serving-batcher", daemon=True
        )
        self._thread.start()

    def submit(self, payload, deadline_ms: Optional[float] = None, **meta) -> Future:
        """Enqueue one request; the future resolves with its result.

        ``deadline_ms`` overrides the batcher-level default; a request
        still queued when its deadline passes resolves with
        ``TimeoutError``.  Raises ``RuntimeError`` once closed and
        :class:`OverloadedError` when the backlog bound rejects the
        request.
        """
        dl = deadline_ms if deadline_ms is not None else self.deadline_ms
        if dl is not None and dl <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {dl}")
        with self._lock:
            # under the same lock close() takes: a submit that wins the
            # race lands before the sentinel and is drained; one that
            # loses raises — a Future can never be enqueued behind a dead
            # loop to hang forever
            if self._closed:
                raise RuntimeError("batcher is closed")
            if (
                self.max_backlog is not None
                and self._queue.qsize() >= self.max_backlog
            ):
                self.sheds += 1
                if self._on_shed is not None:
                    self._on_shed()
                raise OverloadedError(
                    f"serving backlog full ({self.max_backlog} waiting); "
                    "request shed"
                )
            req = Request(
                payload, meta,
                deadline=(time.monotonic() + dl / 1000.0) if dl else None,
            )
            self._queue.put(req)
        return req.future

    def depth(self) -> int:
        """Requests currently waiting (approximate, by nature)."""
        return self._queue.qsize()

    def close(self) -> None:
        """Drain remaining requests, then stop the flush thread."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)  # sentinel wakes a blocked get
        self._thread.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------ #

    def _expired(self, req: Request) -> bool:
        """Resolve an over-deadline request with ``TimeoutError``; True if
        it expired (the caller must not batch it)."""
        if req.deadline is None or time.monotonic() < req.deadline:
            return False
        self.timeouts += 1
        if self._on_timeout is not None:
            self._on_timeout()
        if not req.future.done():
            req.future.set_exception(
                TimeoutError(
                    "serving request exceeded its deadline after "
                    f"{time.monotonic() - req.enqueued_at:.3f}s in queue"
                )
            )
        return True

    def _collect(self) -> Tuple[List[Request], bool]:
        """Block for the first request, then gather until a flush trigger.

        Returns ``(batch, stop)``; stop means the sentinel was seen (any
        gathered batch is still flushed first — close() drains).  Requests
        past their deadline are expired here instead of batched.
        """
        while True:
            first = self._queue.get()
            if first is None:
                return [], True
            if not self._expired(first):
                break
        batch = [first]
        # a backlog that built while the previous batch ran must flush at
        # full width immediately — grab whatever already waits before ever
        # consulting the delay deadline (which the oldest request may well
        # have passed by now; timing out to a singleton batch here would
        # serialize the whole backlog one request at a time)
        while len(batch) < self.max_batch_size:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is None:
                return batch, True
            if not self._expired(req):
                batch.append(req)
        deadline = first.enqueued_at + self.max_delay
        while len(batch) < self.max_batch_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                req = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if req is None:
                return batch, True
            if not self._expired(req):
                batch.append(req)
        return batch, False

    def _flush(self, batch: List[Request]) -> None:
        try:
            results = self._run_batch(batch)
        except BaseException as exc:  # propagate, don't kill the thread
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(exc)
            return
        if results is None:
            return  # runner resolved the futures itself
        if len(results) != len(batch):
            exc = RuntimeError(
                f"run_batch returned {len(results)} results for "
                f"{len(batch)} requests"
            )
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(exc)
            return
        for req, res in zip(batch, results):
            if not req.future.done():
                req.future.set_result(res)

    def _loop(self) -> None:
        while True:
            batch, stop = self._collect()
            if batch:
                self._flush(batch)
            if stop:
                # drain anything enqueued before close() won the race
                while True:
                    try:
                        req = self._queue.get_nowait()
                    except queue.Empty:
                        return
                    if req is not None and not self._expired(req):
                        self._flush([req])
