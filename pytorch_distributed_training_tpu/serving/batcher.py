"""Dynamic micro-batching: the serving-side analog of large-batch training.

Requests land on a queue and a single flush thread groups them into
batches, releasing a batch when either (a) ``max_batch_size`` requests are
waiting — the accelerator-saturation bound — or (b) the OLDEST waiting
request has been queued for ``max_delay_ms`` — the latency bound.  Each
``submit`` returns a ``concurrent.futures.Future`` resolved with that
request's slice of the batch result (or its exception), so callers block
only on their own request.

The batcher is shape-agnostic: it hands the runner a list of
``(payload, meta)`` pairs and the runner (``InferenceEngine._run_batch``)
does the bucketing/padding, so the number of distinct XLA compiles stays
bounded by the engine's bucket grid, not by client batch arithmetic.

Graceful degradation under overload (both off by default):

  - per-request deadlines (``deadline_ms``): a request still queued past
    its deadline resolves with ``TimeoutError`` at collection time instead
    of occupying a flush slot — under backlog, work nobody is waiting for
    anymore stops displacing work somebody is;
  - bounded-queue load shedding (``max_backlog``): beyond the configured
    backlog, ``submit`` fails fast with :class:`OverloadedError` rather
    than growing an unbounded queue of doomed requests.

Both are counted (``timeouts``/``sheds``) and surfaced through optional
callbacks so ``ServingMetrics`` can aggregate them.

The backlog is a ``deque`` under a ``Condition`` rather than a
``queue.Queue``: the backlog-depth check must count LIVE requests only,
which means ``submit`` has to sweep already-expired entries out of the
queue before comparing against ``max_backlog`` — an opaque ``Queue``
cannot be swept, so under sustained overload it shed live requests to
protect doomed ones (the PR 7 fix).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence, Tuple

__all__ = ["DynamicBatcher", "OverloadedError", "Request"]


class OverloadedError(RuntimeError):
    """Rejected by load shedding: the batcher's backlog is full."""


class Request:
    """One queued payload plus its result future and enqueue timestamp."""

    __slots__ = ("payload", "meta", "future", "enqueued_at", "deadline")

    def __init__(self, payload, meta, deadline: Optional[float] = None):
        self.payload = payload
        self.meta = dict(meta)
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()
        # absolute time.monotonic() deadline; None = wait forever
        self.deadline = deadline


class DynamicBatcher:
    """Queue + flush thread grouping requests into bounded batches.

    ``run_batch(requests)`` is called on the flush thread with 1..max_batch
    requests and must return one result per request (same order); it may
    instead set futures itself and return None.  Exceptions it raises are
    propagated to every future in the batch.
    """

    def __init__(
        self,
        run_batch: Callable[[Sequence[Request]], Optional[List[Any]]],
        max_batch_size: int,
        max_delay_ms: float,
        deadline_ms: Optional[float] = None,
        max_backlog: Optional[int] = None,
        on_timeout: Optional[Callable[[], None]] = None,
        on_shed: Optional[Callable[[], None]] = None,
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got {max_batch_size}")
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        if max_backlog is not None and max_backlog < 1:
            raise ValueError(f"max_backlog must be >= 1, got {max_backlog}")
        self._run_batch = run_batch
        self.max_batch_size = int(max_batch_size)
        self.max_delay = max_delay_ms / 1000.0
        self.deadline_ms = deadline_ms
        self.max_backlog = max_backlog
        self.timeouts = 0  # guarded by: self._cond
        self.sheds = 0  # guarded by: self._cond
        self._on_timeout = on_timeout
        self._on_shed = on_shed
        self._queue: "deque[Request]" = deque()  # guarded by: self._cond
        self._closed = False  # guarded by: self._cond
        self._cond = threading.Condition()
        self._thread = threading.Thread(
            target=self._loop, name="serving-batcher", daemon=True
        )
        self._thread.start()

    def submit(self, payload, deadline_ms: Optional[float] = None, **meta) -> Future:
        """Enqueue one request; the future resolves with its result.

        ``deadline_ms`` overrides the batcher-level default; a request
        still queued when its deadline passes resolves with
        ``TimeoutError``.  Raises ``RuntimeError`` once closed and
        :class:`OverloadedError` when the backlog bound rejects the
        request.
        """
        dl = deadline_ms if deadline_ms is not None else self.deadline_ms
        if dl is not None and dl <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {dl}")
        with self._cond:
            # under the same lock close() takes: a submit that wins the
            # race lands before close flips the flag and is drained; one
            # that loses raises — a Future can never be enqueued behind a
            # dead loop to hang forever
            if self._closed:
                raise RuntimeError("batcher is closed")
            # expired entries are dead weight, not backlog: resolve and
            # drop them FIRST so the depth check below counts only live
            # requests (otherwise doomed requests shed live ones)
            self._sweep_expired_locked()
            if (
                self.max_backlog is not None
                and len(self._queue) >= self.max_backlog
            ):
                self.sheds += 1
                if self._on_shed is not None:
                    self._on_shed()
                raise OverloadedError(
                    f"serving backlog full ({self.max_backlog} waiting); "
                    "request shed"
                )
            req = Request(
                payload, meta,
                deadline=(time.monotonic() + dl / 1000.0) if dl else None,
            )
            self._queue.append(req)
            self._cond.notify_all()
        return req.future

    def depth(self) -> int:
        """Requests currently waiting (approximate, by nature)."""
        with self._cond:
            return len(self._queue)

    def close(self) -> None:
        """Drain remaining requests, then stop the flush thread."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()  # wake a blocked collect
        self._thread.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------ #

    def _expired(self, req: Request) -> bool:  # guarded by: self._cond
        """Resolve an over-deadline request with ``TimeoutError``; True if
        it expired (the caller must not batch it)."""
        if req.deadline is None or time.monotonic() < req.deadline:
            return False
        self.timeouts += 1
        if self._on_timeout is not None:
            self._on_timeout()
        if not req.future.done():
            req.future.set_exception(
                TimeoutError(
                    "serving request exceeded its deadline after "
                    f"{time.monotonic() - req.enqueued_at:.3f}s in queue"
                )
            )
        return True

    def _sweep_expired_locked(self) -> None:
        """Resolve + remove every over-deadline request (cond held)."""
        now = time.monotonic()
        if any(r.deadline is not None and now >= r.deadline for r in self._queue):
            self._queue = deque(r for r in self._queue if not self._expired(r))

    def _collect(self) -> Tuple[List[Request], bool]:
        """Block for the first request, then gather until a flush trigger.

        Returns ``(batch, stop)``; stop means close() was seen and the
        queue is drained (any gathered batch is still flushed first —
        close() drains).  Requests past their deadline are expired here
        instead of batched.
        """
        with self._cond:
            while True:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue:
                    return [], True  # closed and fully drained
                first = self._queue.popleft()
                if not self._expired(first):
                    break
            batch = [first]
            # a backlog that built while the previous batch ran must flush
            # at full width immediately — grab whatever already waits
            # before ever consulting the delay deadline (which the oldest
            # request may well have passed by now; timing out to a
            # singleton batch here would serialize the whole backlog one
            # request at a time)
            while len(batch) < self.max_batch_size and self._queue:
                req = self._queue.popleft()
                if not self._expired(req):
                    batch.append(req)
            deadline = first.enqueued_at + self.max_delay
            while len(batch) < self.max_batch_size and not self._closed:
                if self._queue:
                    req = self._queue.popleft()
                    if not self._expired(req):
                        batch.append(req)
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(timeout=remaining):
                    break
            return batch, False

    def _flush(self, batch: List[Request]) -> None:
        try:
            results = self._run_batch(batch)
        except BaseException as exc:  # propagate, don't kill the thread
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(exc)
            return
        if results is None:
            return  # runner resolved the futures itself
        if len(results) != len(batch):
            exc = RuntimeError(
                f"run_batch returned {len(results)} results for "
                f"{len(batch)} requests"
            )
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(exc)
            return
        for req, res in zip(batch, results):
            if not req.future.done():
                req.future.set_result(res)

    def _loop(self) -> None:
        # drain-on-close falls out of _collect: once closed it keeps
        # returning batches (without the timed fill) until the queue is
        # empty, and only then reports stop
        while True:
            batch, stop = self._collect()
            if batch:
                self._flush(batch)
            if stop:
                return
