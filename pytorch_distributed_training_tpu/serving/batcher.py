"""Dynamic micro-batching: the serving-side analog of large-batch training.

Requests land on a queue and a single flush thread groups them into
batches, releasing a batch when either (a) ``max_batch_size`` requests are
waiting — the accelerator-saturation bound — or (b) the OLDEST waiting
request has been queued for ``max_delay_ms`` — the latency bound.  Each
``submit`` returns a ``concurrent.futures.Future`` resolved with that
request's slice of the batch result (or its exception), so callers block
only on their own request.

The batcher is shape-agnostic: it hands the runner a list of
``(payload, meta)`` pairs and the runner (``InferenceEngine._run_batch``)
does the bucketing/padding, so the number of distinct XLA compiles stays
bounded by the engine's bucket grid, not by client batch arithmetic.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence, Tuple

__all__ = ["DynamicBatcher", "Request"]


class Request:
    """One queued payload plus its result future and enqueue timestamp."""

    __slots__ = ("payload", "meta", "future", "enqueued_at")

    def __init__(self, payload, meta):
        self.payload = payload
        self.meta = dict(meta)
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()


class DynamicBatcher:
    """Queue + flush thread grouping requests into bounded batches.

    ``run_batch(requests)`` is called on the flush thread with 1..max_batch
    requests and must return one result per request (same order); it may
    instead set futures itself and return None.  Exceptions it raises are
    propagated to every future in the batch.
    """

    def __init__(
        self,
        run_batch: Callable[[Sequence[Request]], Optional[List[Any]]],
        max_batch_size: int,
        max_delay_ms: float,
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got {max_batch_size}")
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        self._run_batch = run_batch
        self.max_batch_size = int(max_batch_size)
        self.max_delay = max_delay_ms / 1000.0
        self._queue: "queue.Queue[Optional[Request]]" = queue.Queue()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="serving-batcher", daemon=True
        )
        self._thread.start()

    def submit(self, payload, **meta) -> Future:
        """Enqueue one request; the future resolves with its result."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        req = Request(payload, meta)
        self._queue.put(req)
        return req.future

    def depth(self) -> int:
        """Requests currently waiting (approximate, by nature)."""
        return self._queue.qsize()

    def close(self) -> None:
        """Drain remaining requests, then stop the flush thread."""
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)  # sentinel wakes a blocked get
        self._thread.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------ #

    def _collect(self) -> Tuple[List[Request], bool]:
        """Block for the first request, then gather until a flush trigger.

        Returns ``(batch, stop)``; stop means the sentinel was seen (any
        gathered batch is still flushed first — close() drains).
        """
        first = self._queue.get()
        if first is None:
            return [], True
        batch = [first]
        # a backlog that built while the previous batch ran must flush at
        # full width immediately — grab whatever already waits before ever
        # consulting the delay deadline (which the oldest request may well
        # have passed by now; timing out to a singleton batch here would
        # serialize the whole backlog one request at a time)
        while len(batch) < self.max_batch_size:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is None:
                return batch, True
            batch.append(req)
        deadline = first.enqueued_at + self.max_delay
        while len(batch) < self.max_batch_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                req = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if req is None:
                return batch, True
            batch.append(req)
        return batch, False

    def _flush(self, batch: List[Request]) -> None:
        try:
            results = self._run_batch(batch)
        except BaseException as exc:  # propagate, don't kill the thread
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(exc)
            return
        if results is None:
            return  # runner resolved the futures itself
        if len(results) != len(batch):
            exc = RuntimeError(
                f"run_batch returned {len(results)} results for "
                f"{len(batch)} requests"
            )
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(exc)
            return
        for req, res in zip(batch, results):
            if not req.future.done():
                req.future.set_result(res)

    def _loop(self) -> None:
        while True:
            batch, stop = self._collect()
            if batch:
                self._flush(batch)
            if stop:
                # drain anything enqueued before close() won the race
                while True:
                    try:
                        req = self._queue.get_nowait()
                    except queue.Empty:
                        return
                    if req is not None:
                        self._flush([req])
