"""Synthetic open-loop serving demo / smoke entrypoint.

    python -m pytorch_distributed_training_tpu.serving \
        --config config/serve-lm.yml [--requests 32] [--log-dir /tmp/serve]

Builds an :class:`.engine.InferenceEngine` from the config, fires
``--requests`` synthetic requests at it open-loop (LM: random prompts of
varying length within the seq buckets; classification: random images),
waits on every future, and reports p50/p99 latency, max queue depth, and
items/sec through the repo's logging funnel — the final line is one JSON
object, same convention as ``bench.py``.
"""
from __future__ import annotations

import argparse
import json
import sys
from functools import partial

import numpy as np

from ..config_parsing import get_serve_cfg, get_train_logger
from ..logger import MultiProcessLoggerListener
from .engine import InferenceEngine


def _synthetic_payloads(cfg, engine: InferenceEngine, n: int, seed: int):
    rng = np.random.default_rng(seed)
    vocab = cfg["dataset"]["n_classes"]
    if engine.is_lm:
        max_prompt = engine.seq_buckets[-1]
        for _ in range(n):
            ln = int(rng.integers(1, max_prompt + 1))
            yield rng.integers(0, vocab, ln).astype(np.int32)
    else:
        size = engine.image_size
        for _ in range(n):
            yield rng.integers(0, 256, (size, size, 3)).astype(np.uint8)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pytorch_distributed_training_tpu.serving",
        description="serve a checkpoint against a synthetic request stream",
    )
    parser.add_argument("--config", required=True, help="serve-*.yml path")
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--log-dir", default="/tmp/pdt-serve")
    args = parser.parse_args(argv)

    cfg = get_serve_cfg(args.config)
    listener = MultiProcessLoggerListener(
        partial(get_train_logger, args.log_dir, "serve"), "spawn"
    )
    logger = listener.get_logger()
    try:
        with InferenceEngine.from_config(cfg, logger=logger) as engine:
            # SIGTERM -> graceful drain (stop admitting, finish in-flight
            # under resilience.drain_deadline_ms, then close) — the
            # orchestrated-shutdown path, wired here because signal
            # handlers must install from the main thread
            engine.install_drain_handler()
            modes = [m for m, on in engine.serving_modes.items() if on]
            logger.info(
                "engine up: task=%s batch_buckets=%s seq_buckets=%s modes=%s",
                "lm" if engine.is_lm else "image",
                engine.batch_buckets,
                engine.seq_buckets if engine.is_lm else "-",
                "+".join(modes) if modes else "baseline",
            )
            futures = [
                engine.submit(p)
                for p in _synthetic_payloads(cfg, engine, args.requests, args.seed)
            ]
            for fut in futures:
                fut.result(timeout=300)
            snap = engine.metrics.log_summary(logger)
            snap["compile_count"] = engine.compile_count()
        logger.info("served %d requests, %d XLA programs compiled",
                    args.requests, snap["compile_count"])
        print(json.dumps({"serving": snap}))
        return 0
    finally:
        listener.stop()


if __name__ == "__main__":
    sys.exit(main())
