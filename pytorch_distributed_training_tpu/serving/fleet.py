"""N serving replicas + one router = a fleet that survives replica loss.

:class:`ServingFleet` owns the replica lifecycle the router deliberately
does not: it builds N :class:`.engine.InferenceEngine` replicas from ONE
config resolution (``InferenceEngine.resolve_config`` restores the
checkpoint once; every replica shares the parameter tree and mesh, and
compiles its own decode programs), stamps each with its fleet identity
(``replica_id`` for metric namespacing, a per-replica heartbeat file for
external liveness), fronts them with a :class:`.router.FleetRouter`, and
provides the fleet-wide lifecycle verbs — concurrent ``drain``, SIGTERM
via ``install_drain_handler``, aggregate ``health()``/``snapshot()``.

Config (``serving.fleet`` in serve-lm.yml)::

    serving:
      scheduler: {enabled: true, ...}     # fleet requires the scheduler path
      fleet:
        replicas: 2                # engine replicas in this process
        affinity: true             # prefix-sticky placement
        hedge_ms: 200              # straggler re-dispatch (null = off)
        max_backlog: 64            # fleet-level shed threshold (null = off)
        heartbeat_dir: /tmp/hb     # default: a fresh temp dir
        heartbeat_interval_s: 0.25
        heartbeat_timeout_s: 2.0   # router marks staler replicas down
        liveness_timeout_s: 5.0    # in-process health() stall clock

Single-process by design, matching the scheduler: the fleet is N slot
arrays + N pools in one process, which is exactly the shape the chaos
harness needs to kill and revive replicas deterministically.  Splitting
replicas across processes changes only who writes the heartbeat files.
"""
from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional, Sequence

from .engine import InferenceEngine
from .metrics import aggregate_snapshots
from .router import FleetRouter

__all__ = ["ServingFleet"]


class ServingFleet:
    """Replica lifecycle + fleet-level verbs over a :class:`FleetRouter`."""

    def __init__(
        self,
        replicas: Sequence[Any],
        router: FleetRouter,
        heartbeat_dir: Optional[str] = None,
        logger: Optional[logging.Logger] = None,
    ):
        if not replicas:
            raise ValueError("ServingFleet needs at least one replica")
        self.replicas = list(replicas)
        self.router = router
        self.heartbeat_dir = heartbeat_dir
        self.logger = logger or logging.getLogger("pdt.serving.fleet")
        self._closed = False
        self._close_lock = threading.Lock()

    # ------------------------------------------------------------------ #

    @classmethod
    def from_config(cls, cfg: Dict[str, Any], logger=None) -> "ServingFleet":
        """Build N replicas from one ``serve-*.yml`` resolution.

        The checkpoint restore / random init happens ONCE; each replica
        gets a copy of the constructor kwargs with its ``replica_id``,
        heartbeat file, and liveness clock stamped in.
        """
        logger = logger or logging.getLogger(__name__)
        serve = cfg["serving"]
        fleet_cfg = dict(serve.get("fleet") or {})
        n = int(fleet_cfg.pop("replicas", 2))
        if n < 1:
            raise ValueError(f"serving.fleet.replicas must be >= 1, got {n}")
        affinity = bool(fleet_cfg.pop("affinity", True))
        hedge_ms = fleet_cfg.pop("hedge_ms", None)
        max_backlog = fleet_cfg.pop("max_backlog", None)
        heartbeat_dir = fleet_cfg.pop("heartbeat_dir", None)
        hb_interval = float(fleet_cfg.pop("heartbeat_interval_s", 0.25))
        hb_timeout = fleet_cfg.pop("heartbeat_timeout_s", 2.0)
        liveness = fleet_cfg.pop("liveness_timeout_s", None)
        poll_s = float(fleet_cfg.pop("poll_interval_s", 0.05))
        if fleet_cfg:
            raise ValueError(
                f"unknown serving.fleet keys: {sorted(fleet_cfg)}"
            )
        model, params, batch_stats, mesh, kwargs = (
            InferenceEngine.resolve_config(cfg, logger)
        )
        sched_cfg = kwargs.get("scheduler") or {}
        if not kwargs.get("is_lm") or not sched_cfg.get("enabled"):
            raise ValueError(
                "serving.fleet requires an LM with serving.scheduler.enabled "
                "(failover replays token streams through the continuous "
                "scheduler; the batcher path cannot continue a request)"
            )
        if heartbeat_dir is None:
            heartbeat_dir = tempfile.mkdtemp(prefix="pdt-fleet-hb-")
        os.makedirs(heartbeat_dir, exist_ok=True)
        replicas = []
        for i in range(n):
            kw = dict(kwargs)
            kw.update(
                replica_id=i,
                heartbeat_path=os.path.join(
                    heartbeat_dir, f"replica_{i}.json"),
                heartbeat_interval_s=hb_interval,
                liveness_timeout_s=liveness,
            )
            replicas.append(
                InferenceEngine(model, params, batch_stats, mesh, **kw))
        router = FleetRouter(
            replicas,
            seed=int(serve.get("seed", 0)),
            affinity=affinity,
            max_backlog=(int(max_backlog) if max_backlog is not None else None),
            hedge_ms=(float(hedge_ms) if hedge_ms is not None else None),
            heartbeat_timeout_s=(
                float(hb_timeout) if hb_timeout is not None else None),
            poll_interval_s=poll_s,
            logger=logger,
        )
        logger.info(
            "serving fleet up: %d replica(s), affinity=%s, hedge_ms=%s, "
            "heartbeats in %s", n, affinity, hedge_ms, heartbeat_dir)
        return cls(replicas, router, heartbeat_dir=heartbeat_dir,
                   logger=logger)

    # ------------------------------------------------------------------ #
    # client verbs (router passthrough)

    def submit(
        self,
        prompt,
        deadline_ms: Optional[float] = None,
        max_new_tokens: Optional[int] = None,
        on_token: Optional[Callable[[int], None]] = None,
        rng=None,
    ) -> Future:
        return self.router.submit(
            prompt, deadline_ms=deadline_ms, max_new_tokens=max_new_tokens,
            on_token=on_token, rng=rng,
        )

    def depth(self) -> int:
        return self.router.depth()

    def health(self) -> Dict[str, Any]:
        return self.router.health()

    def snapshot(self) -> Dict[str, Any]:
        """Fleet metrics: the per-replica sub-snapshots plus the
        cross-replica aggregate (sums for throughput counters, maxes for
        tail percentiles — see :func:`.metrics.aggregate_snapshots`)."""
        per = {
            f"r{i}": rep.metrics.snapshot()
            for i, rep in enumerate(self.replicas)
            if hasattr(rep, "metrics")
        }
        return {"fleet": aggregate_snapshots(per), "replicas": per}

    # ------------------------------------------------------------------ #
    # lifecycle

    def drain(self, deadline_ms: Optional[float] = None) -> float:
        """Graceful fleet shutdown: refuse new submits at the router,
        drain every replica CONCURRENTLY (each bounds itself with
        ``deadline_ms``; serial drains would stack the deadlines), then
        stop the router's monitor.  Returns wall ms spent.  Idempotent;
        safe from any thread."""
        t0 = time.monotonic()
        with self._close_lock:
            if self._closed:
                return 0.0
            self._closed = True
        self.router.stop_submissions()
        threads = [
            threading.Thread(
                target=rep.drain, args=(deadline_ms,),
                name=f"fleet-drain-{i}", daemon=True,
            )
            for i, rep in enumerate(self.replicas)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.router.shutdown()
        ms = (time.monotonic() - t0) * 1000.0
        self.logger.info("fleet drained in %.1f ms", ms)
        return ms

    def install_drain_handler(self, signum=None) -> None:
        """Route SIGTERM (or ``signum``) to a graceful fleet drain.

        Same contract as the engine's handler: the signal handler only
        spawns a daemon thread — drain joins scheduler threads, which a
        handler must not do inline.  Call from the main thread."""
        import signal

        signum = signal.SIGTERM if signum is None else signum

        def _handler(sig, frame):
            self.logger.warning(
                "signal %s received — draining serving fleet", sig)
            threading.Thread(
                target=self.drain, name="fleet-drain", daemon=True
            ).start()

        signal.signal(signum, _handler)

    def close(self) -> None:
        """Hard stop: router first (so nothing re-dispatches into a
        closing replica), then every replica."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self.router.shutdown()
        for rep in self.replicas:
            try:
                rep.close()
            except Exception:
                self.logger.exception("replica close failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
