"""N serving replicas + one router = a fleet that survives replica loss.

:class:`ServingFleet` owns the replica lifecycle the router deliberately
does not: it builds N :class:`.engine.InferenceEngine` replicas from ONE
config resolution (``InferenceEngine.resolve_config`` restores the
checkpoint once; every replica shares the parameter tree and mesh, and
compiles its own decode programs), stamps each with its fleet identity
(``replica_id`` for metric namespacing, a per-replica heartbeat file for
external liveness), fronts them with a :class:`.router.FleetRouter`, and
provides the fleet-wide lifecycle verbs — concurrent ``drain``, SIGTERM
via ``install_drain_handler``, aggregate ``health()``/``snapshot()``.

Config (``serving.fleet`` in serve-lm.yml)::

    serving:
      scheduler: {enabled: true, ...}     # fleet requires the scheduler path
      fleet:
        replicas: 2                # engine replicas in this process
        affinity: true             # prefix-sticky placement
        hedge_ms: 200              # straggler re-dispatch (null = off)
        max_backlog: 64            # fleet-level shed threshold (null = off)
        heartbeat_dir: /tmp/hb     # default: a fresh temp dir
        heartbeat_interval_s: 0.25
        heartbeat_timeout_s: 2.0   # router marks staler replicas down
        liveness_timeout_s: 5.0    # in-process health() stall clock

Single-process by design, matching the scheduler: the fleet is N slot
arrays + N pools in one process, which is exactly the shape the chaos
harness needs to kill and revive replicas deterministically.  Splitting
replicas across processes changes only who writes the heartbeat files.
"""
from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional, Sequence

from .engine import InferenceEngine
from .metrics import aggregate_snapshots
from .router import FleetRouter

__all__ = ["ServingFleet"]


class ServingFleet:
    """Replica lifecycle + fleet-level verbs over a :class:`FleetRouter`."""

    def __init__(
        self,
        replicas: Sequence[Any],
        router: FleetRouter,
        heartbeat_dir: Optional[str] = None,
        logger: Optional[logging.Logger] = None,
        replica_factory: Optional[Callable[[int], Any]] = None,
    ):
        if not replicas:
            raise ValueError("ServingFleet needs at least one replica")
        # mirrors the router's append-only list (same stable indices);
        # the autoscaler appends while drain handlers iterate
        self._replicas = list(replicas)  # guarded by: self._close_lock
        self._removed: set = set()  # guarded by: self._close_lock
        self.router = router
        self.heartbeat_dir = heartbeat_dir
        self.logger = logger or logging.getLogger("pdt.serving.fleet")
        # builds one started replica for a given replica_id — the
        # autoscaler's scale-up path; from_config installs one that
        # reuses its single checkpoint resolution
        self.replica_factory = replica_factory
        # fleet-shared prefix-cache directory (serving/disagg.py): when a
        # DisaggFleet wraps this fleet it installs its FleetCacheDirectory
        # here so membership changes keep the directory coherent —
        # remove_replica evicts the retiree's entries BEFORE drain starts
        self.cache_directory = None
        self._next_replica_id = len(self._replicas)
        self._closed = False
        self._close_lock = threading.Lock()

    @property
    def replicas(self):
        """Locked snapshot, index-aligned with the router's list."""
        with self._close_lock:
            return list(self._replicas)

    # ------------------------------------------------------------------ #

    @classmethod
    def from_config(cls, cfg: Dict[str, Any], logger=None) -> "ServingFleet":
        """Build N replicas from one ``serve-*.yml`` resolution.

        The checkpoint restore / random init happens ONCE; each replica
        gets a copy of the constructor kwargs with its ``replica_id``,
        heartbeat file, and liveness clock stamped in.
        """
        logger = logger or logging.getLogger(__name__)
        serve = cfg["serving"]
        fleet_cfg = dict(serve.get("fleet") or {})
        n = int(fleet_cfg.pop("replicas", 2))
        if n < 1:
            raise ValueError(f"serving.fleet.replicas must be >= 1, got {n}")
        affinity = bool(fleet_cfg.pop("affinity", True))
        hedge_ms = fleet_cfg.pop("hedge_ms", None)
        max_backlog = fleet_cfg.pop("max_backlog", None)
        heartbeat_dir = fleet_cfg.pop("heartbeat_dir", None)
        hb_interval = float(fleet_cfg.pop("heartbeat_interval_s", 0.25))
        hb_timeout = fleet_cfg.pop("heartbeat_timeout_s", 2.0)
        liveness = fleet_cfg.pop("liveness_timeout_s", None)
        poll_s = float(fleet_cfg.pop("poll_interval_s", 0.05))
        if fleet_cfg:
            raise ValueError(
                f"unknown serving.fleet keys: {sorted(fleet_cfg)}"
            )
        model, params, batch_stats, mesh, kwargs = (
            InferenceEngine.resolve_config(cfg, logger)
        )
        sched_cfg = kwargs.get("scheduler") or {}
        if not kwargs.get("is_lm") or not sched_cfg.get("enabled"):
            raise ValueError(
                "serving.fleet requires an LM with serving.scheduler.enabled "
                "(failover replays token streams through the continuous "
                "scheduler; the batcher path cannot continue a request)"
            )
        if heartbeat_dir is None:
            heartbeat_dir = tempfile.mkdtemp(prefix="pdt-fleet-hb-")
        os.makedirs(heartbeat_dir, exist_ok=True)

        def _make_replica(rid: int) -> InferenceEngine:
            # closes over the ONE resolution: an autoscaled replica is
            # built from the very same restored tree/mesh/kwargs as the
            # originals, just stamped with the next fleet identity
            kw = dict(kwargs)
            kw.update(
                replica_id=rid,
                heartbeat_path=os.path.join(
                    heartbeat_dir, f"replica_{rid}.json"),
                heartbeat_interval_s=hb_interval,
                liveness_timeout_s=liveness,
            )
            return InferenceEngine(model, params, batch_stats, mesh, **kw)

        replicas = [_make_replica(i) for i in range(n)]
        router = FleetRouter(
            replicas,
            seed=int(serve.get("seed", 0)),
            affinity=affinity,
            max_backlog=(int(max_backlog) if max_backlog is not None else None),
            hedge_ms=(float(hedge_ms) if hedge_ms is not None else None),
            heartbeat_timeout_s=(
                float(hb_timeout) if hb_timeout is not None else None),
            poll_interval_s=poll_s,
            logger=logger,
        )
        logger.info(
            "serving fleet up: %d replica(s), affinity=%s, hedge_ms=%s, "
            "heartbeats in %s", n, affinity, hedge_ms, heartbeat_dir)
        return cls(replicas, router, heartbeat_dir=heartbeat_dir,
                   logger=logger, replica_factory=_make_replica)

    # ------------------------------------------------------------------ #
    # client verbs (router passthrough)

    def submit(
        self,
        prompt,
        deadline_ms: Optional[float] = None,
        max_new_tokens: Optional[int] = None,
        on_token: Optional[Callable[[int], None]] = None,
        rng=None,
    ) -> Future:
        return self.router.submit(
            prompt, deadline_ms=deadline_ms, max_new_tokens=max_new_tokens,
            on_token=on_token, rng=rng,
        )

    def depth(self) -> int:
        return self.router.depth()

    def health(self) -> Dict[str, Any]:
        return self.router.health()

    def snapshot(self) -> Dict[str, Any]:
        """Fleet metrics: the per-replica sub-snapshots plus the
        cross-replica aggregate (sums for throughput counters, maxes for
        tail percentiles — see :func:`.metrics.aggregate_snapshots`)."""
        per = {
            f"r{i}": rep.metrics.snapshot()
            for i, rep in enumerate(self.replicas)
            if hasattr(rep, "metrics")
        }
        return {"fleet": aggregate_snapshots(per), "replicas": per}

    # ------------------------------------------------------------------ #
    # elastic membership (the autoscaler's two verbs)

    def live_replicas(self) -> int:
        """Replicas usable for placement: not down, not retired."""
        return len(self.router.live_indices())

    def pick_retire_candidate(self) -> Optional[int]:
        """Which replica a scale-down should take: the HIGHEST live
        index (LIFO — burst capacity added last leaves first, so the
        long-lived low indices keep their warm prefix caches and sticky
        placement).  None when only one live replica remains."""
        live = self.router.live_indices()
        if len(live) <= 1:
            return None
        return max(live)

    def add_replica(self) -> int:
        """Scale up by one replica, built by the stored factory from the
        SAME config resolution as the original fleet (checkpoint is not
        re-read).  Returns the new replica's stable index.  The replica
        is WARMED before it joins placement — ``InferenceEngine.warmup``
        compiles every (prefill-bucket × decode) program up front, so the
        first routed request never pays cold-compile TTFT; the
        construction-to-warm wall time lands in the replica's
        ``scale_up_ready_ms`` gauge (prefix-cache priming stays the
        caller's job)."""
        if self.replica_factory is None:
            raise RuntimeError(
                "fleet has no replica_factory (build via from_config, or "
                "pass replica_factory= to the constructor) — cannot scale "
                "up"
            )
        with self._close_lock:
            if self._closed:
                raise RuntimeError("fleet is closed")
            rid = self._next_replica_id
            self._next_replica_id = rid + 1
        t0 = time.monotonic()
        rep = self.replica_factory(rid)
        try:
            if hasattr(rep, "warmup"):
                rep.warmup()
            ready_ms = (time.monotonic() - t0) * 1000.0
            if hasattr(rep, "metrics"):
                rep.metrics.record_scale_up_ready(ready_ms)
            self.logger.info(
                "replica %d warm in %.0f ms (construction + compile)",
                rid, ready_ms)
            idx = self.router.add_replica(rep)
        except BaseException:
            rep.close()
            raise
        with self._close_lock:
            self._replicas.append(rep)
        if idx != rid:  # both lists are append-only; drift is a bug
            self.logger.error(
                "fleet/router replica index drift: router says %d, fleet "
                "says %d", idx, rid)
        self.logger.info("fleet scaled up to replica %d", idx)
        return idx

    def remove_replica(self, idx: int,
                       deadline_ms: Optional[float] = None) -> float:
        """Scale down replica ``idx`` through the graceful path — the
        ONLY path: retire from placement, drain its in-flight requests
        to completion (bounded by ``deadline_ms``), then close it.
        Returns wall ms spent draining.  Token streams in flight on the
        retiree finish on the retiree, bitwise-identical to an unscaled
        run — scale-down inherits the drain parity oracle."""
        self.router.retire_replica(idx)
        if self.cache_directory is not None:
            # coherence before drain: a directory hit must never name a
            # retiree — once retired it can no longer export its blocks
            evicted = self.cache_directory.evict_replica(idx)
            if evicted:
                self.logger.info(
                    "evicted %d fleet-cache entr%s held by retiring "
                    "replica %d", evicted, "y" if evicted == 1 else "ies",
                    idx)
        with self._close_lock:
            rep = self._replicas[idx]
            already = idx in self._removed
            self._removed.add(idx)
        if already:
            return 0.0
        t0 = time.monotonic()
        try:
            rep.drain(deadline_ms)
        finally:
            try:
                rep.close()
            except Exception:
                self.logger.exception(
                    "replica %d close failed after drain", idx)
        ms = (time.monotonic() - t0) * 1000.0
        self.logger.info(
            "fleet scaled down: replica %d drained+closed in %.1f ms",
            idx, ms)
        return ms

    # ------------------------------------------------------------------ #
    # lifecycle

    def drain(self, deadline_ms: Optional[float] = None) -> float:
        """Graceful fleet shutdown: refuse new submits at the router,
        drain every replica CONCURRENTLY (each bounds itself with
        ``deadline_ms``; serial drains would stack the deadlines), then
        stop the router's monitor.  Returns wall ms spent.  Idempotent;
        safe from any thread."""
        t0 = time.monotonic()
        with self._close_lock:
            if self._closed:
                return 0.0
            self._closed = True
            live = [
                (i, rep) for i, rep in enumerate(self._replicas)
                if i not in self._removed  # already drained+closed
            ]
        self.router.stop_submissions()
        threads = [
            threading.Thread(
                target=rep.drain, args=(deadline_ms,),
                name=f"fleet-drain-{i}", daemon=True,
            )
            for i, rep in live
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.router.shutdown()
        ms = (time.monotonic() - t0) * 1000.0
        self.logger.info("fleet drained in %.1f ms", ms)
        return ms

    def install_drain_handler(self, signum=None) -> None:
        """Route SIGTERM (or ``signum``) to a graceful fleet drain.

        Same contract as the engine's handler: the signal handler only
        spawns a daemon thread — drain joins scheduler threads, which a
        handler must not do inline.  Call from the main thread."""
        import signal

        signum = signal.SIGTERM if signum is None else signum

        def _handler(sig, frame):
            self.logger.warning(
                "signal %s received — draining serving fleet", sig)
            threading.Thread(
                target=self.drain, name="fleet-drain", daemon=True
            ).start()

        signal.signal(signum, _handler)

    def close(self) -> None:
        """Hard stop: router first (so nothing re-dispatches into a
        closing replica), then every replica."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
            live = [
                rep for i, rep in enumerate(self._replicas)
                if i not in self._removed
            ]
        self.router.shutdown()
        for rep in live:
            try:
                rep.close()
            except Exception:
                self.logger.exception("replica close failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
