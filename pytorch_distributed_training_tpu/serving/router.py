"""Fleet router: health-gated, affinity-aware placement + replica failover.

:class:`FleetRouter` fronts N replicas (each an
:class:`..serving.engine.InferenceEngine`, or a bare
:class:`..serving.scheduler.ContinuousScheduler` in tests — the router is
duck-typed over ``submit``/``health``/``drain``/``close``) and owns four
fleet-level behaviors no single replica can provide:

**Placement.**  Requests whose prompt shares a prefix-cache key (the
first full KV block — the same chained-key rule kv_pool.py caches on) are
routed to the SAME replica via a bounded sticky map, so the
content-addressed prefix cache actually hits; bench Round 7 measured a
0 hit-rate on i.i.d. streams precisely because nothing co-located shared
prefixes.  Everything else goes to the least-loaded healthy replica
(queue depth + active slots from ``health()``, tie-broken by the
``block_util`` gauge each replica publishes).

**Health gating.**  A replica is eligible only while ``health()`` says
ready AND its heartbeat file is fresh.  The heartbeat is written by the
replica's own scheduler thread (never a side thread — a daemon beater
would keep beating while the scheduler is wedged in a device call), so a
stale mtime is evidence no Python progress is being made even when the
process looks alive from inside: the ElasticCoordinator trick applied to
serving.

**Failover with token-identical continuation.**  The router records
every delivered token per request.  When a replica dies (its futures
fail with a replica-level error, its heartbeat goes stale, or the
``replica_down``/``replica_hang`` fault kinds fire), in-flight requests
are re-submitted to a survivor with ``replay_tokens=<delivered>`` and
the ORIGINAL rng: the survivor re-prefills the prompt, re-derives the
KV state for the delivered tokens through its own decode program
(verifying each against the stream — ``replay_parity_mismatch``), and
continues sampling from the exact per-token fold_in keys the dead
replica would have used.  ``on_token`` never refires for replayed
tokens, and the client future resolves with a stream bitwise-equal to an
unkilled run.

**Hedging + backpressure.**  A request with no token progress for
``hedge_ms`` gets a duplicate dispatch on another healthy replica with
first-writer-wins delivery (per-token dedupe against the delivered
list; disagreement bumps ``serving_fleet_parity_mismatch``).  A fleet
backlog cap sheds at the router with the batcher's ``OverloadedError``
before any replica queue saturates.

**Elastic membership.**  The replica list itself is router state: the
autoscaler appends replicas (``add_replica``) and retires them
(``retire_replica``) while the monitor thread sweeps health and client
threads place work.  Membership is therefore held behind ``self._lock``
like every other mutable field — the list is APPEND-ONLY (an index is a
stable replica identity for the life of the router) and a parallel
``_retired`` set excludes drained-out replicas from placement, sweeps,
failover, and the live count without ever renumbering survivors.
Readers take a locked snapshot (:attr:`replicas`) and then call into
replicas outside the lock, preserving the ordering rule below.

Lock discipline: all router state is guarded by ``self._lock``.  The
one ordering rule — NEVER call into a replica (``submit``/``health``/
``drain``/``hard_kill``; they take the scheduler's condition) while
holding ``self._lock``: replica done-callbacks can run under that
condition and take ``self._lock``, so nesting the other way deadlocks.
Client futures are resolved and ``on_token`` fired outside any replica
lock; ``on_token`` runs under ``self._lock`` to keep token order (keep
it cheap, and never call back into the fleet from it).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..engine import fault
from ..telemetry.registry import get_registry
from .batcher import OverloadedError
from .resilience import EngineRestartError

__all__ = ["FleetRouter", "ReplicaDownError", "FleetDownError"]


class ReplicaDownError(RuntimeError):
    """A whole replica is gone (hard-killed, heartbeat stale, or restart
    budget exhausted).  Replica-level, not request-level: the router
    fails the affected requests over to a survivor instead of
    propagating this to clients."""


class FleetDownError(RuntimeError):
    """No healthy replica remains to fail over to; the request cannot
    complete anywhere."""


#: errors that condemn the REPLICA, not the request
_REPLICA_ERRORS = (ReplicaDownError, EngineRestartError)


class _Assignment:
    """One dispatch of a request onto one replica."""

    __slots__ = ("replica_idx", "next_idx", "removed")

    def __init__(self, replica_idx: int, next_idx: int):
        self.replica_idx = replica_idx
        # index into the fleet-level delivered stream this assignment's
        # NEXT token corresponds to (starts past the replayed prefix)
        self.next_idx = next_idx  # guarded by: self._lock (router's)
        self.removed = False  # guarded by: self._lock (router's)


class _FleetRequest:
    """Router-side state for one client request across failovers."""

    __slots__ = (
        "prompt", "max_new", "deadline_ms", "rng", "on_token", "future",
        "delivered", "assignments", "affinity_key", "last_progress",
        "done", "pending_failover", "hedged",
    )

    def __init__(self, prompt, max_new, deadline_ms, rng, on_token,
                 affinity_key):
        self.prompt = prompt  # 1-D np.int32, immutable after submit
        self.max_new = max_new
        self.deadline_ms = deadline_ms
        self.rng = rng  # the ONE sampling key every dispatch reuses
        self.on_token = on_token
        self.future: Future = Future()
        self.delivered: List[int] = []  # guarded by: self._lock (router's)
        self.assignments: List[_Assignment] = []  # guarded by: self._lock (router's)
        self.affinity_key = affinity_key
        self.last_progress = time.monotonic()  # guarded by: self._lock (router's)
        self.done = False  # guarded by: self._lock (router's)
        self.pending_failover = False  # guarded by: self._lock (router's)
        self.hedged = False  # guarded by: self._lock (router's)


class FleetRouter:
    """Health-aware front end over N serving replicas.

    ``submit`` mirrors the single-replica API (prompt / deadline_ms /
    max_new_tokens / on_token / rng) and returns a Future resolving to
    the same ``{"tokens", "gen_len"}`` result shape, so a client cannot
    tell one replica from a fleet — except that replica death no longer
    fails its requests.
    """

    def __init__(
        self,
        replicas: Sequence[Any],
        base_rng=None,
        seed: int = 0,
        affinity: bool = True,
        affinity_capacity: int = 256,
        max_backlog: Optional[int] = None,
        hedge_ms: Optional[float] = None,
        heartbeat_timeout_s: Optional[float] = 2.0,
        poll_interval_s: float = 0.05,
        start_monitor: bool = True,
        logger: Optional[logging.Logger] = None,
    ):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        if max_backlog is not None and max_backlog < 1:
            raise ValueError(f"max_backlog must be >= 1, got {max_backlog}")
        if hedge_ms is not None and hedge_ms <= 0:
            raise ValueError(f"hedge_ms must be > 0, got {hedge_ms}")
        # append-only: an index is a stable replica identity forever
        self._replicas: List[Any] = list(replicas)  # guarded by: self._lock
        self._retired: set = set()  # guarded by: self._lock
        self.logger = logger or logging.getLogger("pdt.serving.fleet")
        self.affinity = bool(affinity)
        self.affinity_capacity = int(affinity_capacity)
        self.max_backlog = max_backlog
        self.hedge_ms = hedge_ms
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.poll_interval_s = float(poll_interval_s)
        if base_rng is None:
            import jax

            base_rng = jax.random.PRNGKey(seed)
        self._base_rng = base_rng
        self._lock = threading.Lock()
        self._seq_no = 0  # guarded by: self._lock
        self._outstanding: List[_FleetRequest] = []  # guarded by: self._lock
        self._down: set = set()  # guarded by: self._lock
        self._failover_q: deque = deque()  # guarded by: self._lock
        self._sticky: OrderedDict = OrderedDict()  # guarded by: self._lock
        self._closed = False  # guarded by: self._lock
        self._poll_no = 0  # monitor-thread confined
        self._start_wall = time.time()
        self._stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        if start_monitor:
            self._monitor_thread = threading.Thread(
                target=self._monitor, name="fleet-monitor", daemon=True
            )
            self._monitor_thread.start()

    # ------------------------------------------------------------------ #
    # membership (elastic: the autoscaler grows/shrinks the fleet while
    # the monitor sweeps and clients place — all behind self._lock)

    @property
    def replicas(self) -> List[Any]:
        """Locked snapshot of the replica list.  Append-only, so an
        index taken from one snapshot stays valid against any later
        snapshot; retired replicas remain in place (renumbering would
        corrupt every in-flight ``_Assignment.replica_idx``)."""
        with self._lock:
            return list(self._replicas)

    def add_replica(self, rep: Any) -> int:
        """Join a new replica to the fleet; returns its (stable) index.
        The replica is immediately eligible for placement, failover, and
        hedging — callers hand over a started, warmed replica."""
        with self._lock:
            if self._closed:
                raise RuntimeError("fleet router is closed")
            self._replicas.append(rep)
            idx = len(self._replicas) - 1
        self._bump("replicas_added")
        self.logger.warning("replica %d joined the fleet", idx)
        return idx

    def retire_replica(self, idx: int) -> None:
        """Remove replica ``idx`` from placement (scale-down step 1).

        In-flight requests on it are left to COMPLETE — retirement is
        not failure; the owner drains the replica afterwards, which is
        what preserves token-identical completion.  Refuses to retire
        the last live replica: an autoscaler bug must degrade to an
        oversized fleet, never to an empty one."""
        with self._lock:
            if not 0 <= idx < len(self._replicas):
                raise IndexError(
                    f"no replica {idx} (fleet has {len(self._replicas)})"
                )
            if idx in self._retired:
                return
            unusable = self._down | self._retired
            live = [
                i for i in range(len(self._replicas)) if i not in unusable
            ]
            if live == [idx]:
                raise ValueError(
                    f"refusing to retire replica {idx}: it is the last "
                    "live replica"
                )
            self._retired.add(idx)
            # placement must not chase a retiree through the sticky map
            for key in [k for k, v in self._sticky.items() if v == idx]:
                del self._sticky[key]
        self._bump("replicas_retired")
        self.logger.warning("replica %d retired from placement", idx)

    def retired(self) -> set:
        with self._lock:
            return set(self._retired)

    def live_indices(self) -> List[int]:
        """Indices neither down nor retired — the fleet's actual size."""
        with self._lock:
            unusable = self._down | self._retired
            return [
                i for i in range(len(self._replicas)) if i not in unusable
            ]

    # ------------------------------------------------------------------ #
    # client side

    def submit(
        self,
        prompt,
        deadline_ms: Optional[float] = None,
        max_new_tokens: Optional[int] = None,
        on_token: Optional[Callable[[int], None]] = None,
        rng=None,
    ) -> Future:
        """Route one prompt to a healthy replica; the future survives
        that replica's death."""
        import jax

        prompt = np.asarray(prompt, np.int32)
        healthy = self._healthy()  # replica calls — before taking _lock
        with self._lock:
            if self._closed:
                raise RuntimeError("fleet router is closed")
            live = len(self._replicas) - len(self._down | self._retired)
            if live <= 0:
                raise FleetDownError("every replica is down")
            if (
                self.max_backlog is not None
                and len(self._outstanding) >= self.max_backlog
            ):
                self._bump("sheds")
                raise OverloadedError(
                    f"fleet backlog full ({self.max_backlog} outstanding); "
                    "request shed at the router"
                )
            if rng is None:
                # router-owned keys: replica-independent, so a failover
                # or hedge resamples the exact same stream anywhere
                rng = jax.random.fold_in(self._base_rng, self._seq_no)
            self._seq_no += 1
            key = self._affinity_key_locked(prompt)
            freq = _FleetRequest(prompt, max_new_tokens, deadline_ms, rng,
                                 on_token, key)
            self._outstanding.append(freq)
            target = self._place_locked(key, healthy)
        self._bump("submitted")
        if target is None:
            self._fail(freq, OverloadedError(
                "no healthy replica available for admission"))
            self._bump("sheds")
            return freq.future
        try:
            self._dispatch(freq, target)
        except OverloadedError:
            # replica-side shed: the fleet request dies with it (clients
            # retry sheds; silently rerouting would hide saturation)
            with self._lock:
                freq.done = True
                self._discard_locked(freq)
                self._bump_locked("sheds")
            raise
        return freq.future

    def peek_placement(self, prompt) -> Optional[int]:
        """Where would :meth:`submit` route this prompt right now?

        The disagg coordinator (serving/disagg.py) asks BEFORE staging a
        KV transfer so the blocks land on the replica that will actually
        decode.  Runs the real placement (sticky registration included),
        so the follow-up ``submit`` of the same prompt lands on the
        returned replica unless it dies in between — and if it does, the
        transfer was wasted work, not a correctness event.  ``None``
        when no healthy replica is admissible.
        """
        prompt = np.asarray(prompt, np.int32)
        healthy = self._healthy()  # replica calls — before taking _lock
        with self._lock:
            if self._closed:
                return None
            key = self._affinity_key_locked(prompt)
            return self._place_locked(key, healthy)

    def depth(self) -> int:
        """Requests accepted by the router and not yet resolved."""
        with self._lock:
            return len(self._outstanding)

    def health(self) -> Dict[str, Any]:
        """Fleet health: per-replica snapshots + aggregate gates."""
        snaps = []
        for idx, rep in enumerate(self.replicas):  # locked snapshot
            with self._lock:
                down = idx in self._down
                out = idx in self._retired
            snap = {"replica": idx, "routed_down": down, "retired": out}
            try:
                snap.update(rep.health())
            except Exception as e:  # a dead replica must not hide the rest
                snap.update(ready=False, live=False, error=str(e))
            snap["heartbeat_stale"] = self._is_stale(rep)
            snaps.append(snap)
        usable = [
            s for s in snaps
            if s["ready"] and not s["routed_down"] and not s["retired"]
            and not s["heartbeat_stale"]
        ]
        with self._lock:
            outstanding = len(self._outstanding)
            closed = self._closed
        return {
            "ready": bool(usable) and not closed,
            "live": any(
                s["live"] and not s["routed_down"] for s in snaps
            ),
            "healthy_replicas": len(usable),
            "replicas": snaps,
            "outstanding": outstanding,
        }

    def stop_submissions(self) -> None:
        """Refuse new submits (drain step 1); in-flight work continues."""
        with self._lock:
            self._closed = True

    def shutdown(self) -> None:
        """Stop the monitor thread.  Does NOT touch the replicas — the
        fleet owns their lifecycle (drain wants them alive until their
        queues empty)."""
        with self._lock:
            self._closed = True
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join()
            self._monitor_thread = None

    # ------------------------------------------------------------------ #
    # placement

    def _affinity_key_locked(self, prompt: np.ndarray) -> Optional[Tuple[int, ...]]:
        """The prefix-cache identity of this prompt: its first full KV
        block (kv_pool caches ``(len(prompt)-1)//block_size`` blocks, so
        a prompt contributes/hits the cache iff that is >= 1)."""
        if not self.affinity:
            return None
        bs = self._block_size_locked()
        if bs is None or (int(prompt.size) - 1) // bs < 1:
            return None
        return tuple(int(t) for t in prompt[:bs])

    def _block_size_locked(self) -> Optional[int]:
        sched = self._sched_of_locked(0)
        return getattr(sched, "_block_size", None) if sched is not None else None

    def _sched_of(self, idx: int):
        """The replica's scheduler, for callers that do NOT hold
        ``self._lock`` (monitor sweeps, failover, injector consults)."""
        with self._lock:
            return self._sched_of_locked(idx)

    def _sched_of_locked(self, idx: int):
        """The replica's scheduler (engines wrap one; tests pass it bare).
        Attribute reads only — never calls into the replica."""
        rep = self._replicas[idx]
        sched = getattr(rep, "scheduler", None)
        if sched is not None:
            return sched
        return rep if hasattr(rep, "hard_kill") else None

    def _healthy(self) -> List[Tuple[int, Dict[str, Any]]]:
        """(idx, health snapshot) for every admissible replica.  Calls
        into replicas — never under ``self._lock``."""
        with self._lock:
            unusable = self._down | self._retired
            closed = self._closed
            reps = list(self._replicas)
        if closed:
            return []
        out = []
        for idx, rep in enumerate(reps):
            if idx in unusable:
                continue
            try:
                snap = rep.health()
            except Exception:
                continue
            if not snap.get("ready"):
                continue
            if self._is_stale(rep):
                continue
            out.append((idx, snap))
        return out

    def _load_score(self, snap: Dict[str, Any], sched) -> Tuple[float, float]:
        """Placement key.  The caller resolves ``sched`` with whichever
        ``_sched_of*`` variant matches its lock context — this helper is
        reached both under the lock (``_place_locked``) and without it
        (failover/hedge repair paths)."""
        depth = float(snap.get("queue_depth", 0) + snap.get("active_slots", 0))
        util = 0.0
        if sched is not None and hasattr(sched, "metrics"):
            util = get_registry().gauge(
                sched.metrics.global_name("block_util")).value
        return (depth, util)

    def _place_locked(
        self,
        key: Optional[Tuple[int, ...]],
        healthy: List[Tuple[int, Dict[str, Any]]],
    ) -> Optional[int]:
        """Pick a replica: sticky-by-prefix first, else least-loaded."""
        if not healthy:
            return None
        healthy_idx = {idx for idx, _ in healthy}
        if key is not None:
            cached = self._sticky.get(key)
            if cached is not None and cached in healthy_idx:
                self._sticky.move_to_end(key)
                self._bump_locked("affinity_hits")
                return cached
        target = min(
            healthy,
            key=lambda h: self._load_score(h[1], self._sched_of_locked(h[0])),
        )[0]
        if key is not None:
            self._sticky[key] = target
            self._sticky.move_to_end(key)
            while len(self._sticky) > self.affinity_capacity:
                self._sticky.popitem(last=False)
        return target

    # ------------------------------------------------------------------ #
    # dispatch + delivery

    def _dispatch(self, freq: _FleetRequest, idx: int,
                  replay: bool = False) -> None:
        """Submit ``freq`` to replica ``idx``.  Raises what the replica's
        ``submit`` raises; the caller decides whether that is fatal (a
        client submit) or retriable (a failover)."""
        with self._lock:
            a = _Assignment(idx, len(freq.delivered))
            freq.assignments.append(a)
            replay_tokens = list(freq.delivered) if replay else None
            rep = self._replicas[idx]
        try:
            fut = rep.submit(
                freq.prompt,
                deadline_ms=freq.deadline_ms,
                max_new_tokens=freq.max_new,
                on_token=lambda tok, f=freq, asn=a: self._deliver(f, asn, tok),
                rng=freq.rng,
                replay_tokens=replay_tokens,
            )
        except BaseException:
            with self._lock:
                a.removed = True
                if a in freq.assignments:
                    freq.assignments.remove(a)
            raise
        fut.add_done_callback(
            lambda f, fr=freq, asn=a: self._on_assignment_done(fr, asn, f))

    def _deliver(self, freq: _FleetRequest, a: _Assignment, tok: int) -> None:
        """Streaming token from one assignment: first-writer-wins dedupe
        against the fleet-level delivered stream.  Runs on the replica's
        scheduler thread (NOT under its condition)."""
        cb = None
        with self._lock:
            idx = a.next_idx
            a.next_idx += 1
            if idx < len(freq.delivered):
                # a slower twin (hedge, or a woken hung replica) re-emitting
                # a token the winner already delivered: drop, but verify
                if freq.delivered[idx] != int(tok):
                    self._bump_locked("parity_mismatch")
                    self.logger.error(
                        "fleet parity mismatch at token %d: replica %d says "
                        "%d, delivered %d", idx, a.replica_idx, int(tok),
                        freq.delivered[idx],
                    )
                return
            freq.delivered.append(int(tok))
            freq.last_progress = time.monotonic()
            cb = freq.on_token
            if cb is not None:
                # under _lock so a hedge twin cannot reorder the stream;
                # on_token contract: cheap, no fleet re-entry
                try:
                    cb(int(tok))
                except Exception:
                    self.logger.exception("fleet on_token callback failed")

    def _on_assignment_done(self, freq: _FleetRequest, a: _Assignment,
                            fut: Future) -> None:
        """Terminal state of one dispatch.  May run on the replica's
        scheduler thread while it holds ITS condition (the expiry path) —
        so this only classifies + enqueues; it never calls into a
        replica."""
        exc = fut.exception()
        if exc is None:
            self._complete(freq, a, fut.result())
        elif isinstance(exc, _REPLICA_ERRORS):
            self._replica_failed(freq, a, exc)
        else:
            self._request_failed(freq, a, exc)

    def _complete(self, freq: _FleetRequest, a: _Assignment, result) -> None:
        with self._lock:
            if freq.done:
                return
            freq.done = True
            self._discard_locked(freq)
            toks = [int(t) for t in np.asarray(result["tokens"]).ravel()]
            if toks[: len(freq.delivered)] != freq.delivered[: len(toks)]:
                self._bump_locked("parity_mismatch")
                self.logger.error(
                    "fleet parity mismatch: winner result %s != delivered %s",
                    toks[:8], freq.delivered[:8],
                )
            self._bump_locked("completed")
        freq.future.set_result(result)  # outside _lock: client callbacks

    def _fail(self, freq: _FleetRequest, exc: BaseException) -> None:
        with self._lock:
            if freq.done:
                return
            freq.done = True
            self._discard_locked(freq)
        freq.future.set_exception(exc)

    def _discard_locked(self, freq: _FleetRequest) -> None:
        try:
            self._outstanding.remove(freq)
        except ValueError:
            pass

    def _replica_failed(self, freq: _FleetRequest, a: _Assignment,
                        exc: BaseException) -> None:
        """The replica died under this request: mark it down and queue
        the request for failover (the monitor thread re-dispatches —
        this callback may hold the dead replica's condition)."""
        with self._lock:
            newly_down = a.replica_idx not in self._down
            if newly_down:
                self._down.add(a.replica_idx)
            a.removed = True
            if a in freq.assignments:
                freq.assignments.remove(a)
            queue_it = (
                not freq.done
                and not freq.assignments  # a hedge twin is still running
                and not freq.pending_failover
            )
            if queue_it:
                freq.pending_failover = True
                self._failover_q.append(freq)
        if newly_down:
            self._bump("replicas_down")
            self.logger.error(
                "replica %d marked down: %s", a.replica_idx, exc)

    def _request_failed(self, freq: _FleetRequest, a: _Assignment,
                        exc: BaseException) -> None:
        """Request-level error (poison, deadline, shed): the request is
        at fault, not the replica — propagate unless a twin is live."""
        with self._lock:
            a.removed = True
            if a in freq.assignments:
                freq.assignments.remove(a)
            if freq.done or freq.assignments:
                return
        self._fail(freq, exc)

    # ------------------------------------------------------------------ #
    # monitor thread: failover, staleness sweep, hedging, fault hooks

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self._poll_once()
            except Exception:
                # the monitor IS the fleet's recovery path; it must
                # survive its own bugs and keep sweeping
                self.logger.exception("fleet monitor poll failed")

    def _poll_once(self) -> None:
        self._poll_no += 1
        self._consult_injector()
        self._sweep_health()
        self._drain_failover_q()
        if self.hedge_ms is not None:
            self._sweep_hedges()

    def _consult_injector(self) -> None:
        """``replica_down@P[:R]`` / ``replica_hang@P[:SEC]``, keyed by
        this monitor's 1-based poll index."""
        inj = fault.get_injector()
        if not inj.active:
            return
        arg = inj.take("replica_down", self._poll_no)
        if arg is not None:
            idx = int(arg)
            with self._lock:
                known = 0 <= idx < len(self._replicas)
            if known:
                fault.bump("injected_replica_downs")
                self.logger.warning(
                    "fault injection: replica_down -> replica %d at poll %d",
                    idx, self._poll_no)
                sched = self._sched_of(idx)
                if sched is not None:
                    sched.hard_kill(ReplicaDownError(
                        f"injected replica_down at router poll {self._poll_no}"
                    ))
        sec = inj.take("replica_hang", self._poll_no)
        if sec is not None:
            fault.bump("injected_replica_hangs")
            self.logger.warning(
                "fault injection: replica_hang %.2fs -> replica 0 at poll %d",
                float(sec), self._poll_no)
            sched = self._sched_of(0)
            if sched is not None:
                sched.inject_hang(float(sec))

    def _is_stale(self, rep: Any) -> bool:
        """Heartbeat-staleness: the replica's scheduler thread has not
        touched its beat file within the timeout.  Works entirely from
        the filesystem — the wedged process cannot lie about it."""
        if self.heartbeat_timeout_s is None:
            return False
        path = getattr(rep, "heartbeat_path", None)
        if not path:
            return False
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            # not written yet: grace-period from router start, like the
            # elastic coordinator's startup grace
            mtime = self._start_wall
        return (time.time() - mtime) > self.heartbeat_timeout_s

    def _sweep_health(self) -> None:
        """Mark replicas down on stale heartbeat or dead liveness, and
        strand-rescue their in-flight requests."""
        for idx, rep in enumerate(self.replicas):  # locked snapshot
            with self._lock:
                # retired replicas drain on their own clock: sweeping
                # them down would hard-kill the drain mid-request
                if idx in self._down or idx in self._retired:
                    continue
            stale = self._is_stale(rep)
            dead = False
            if not stale:
                try:
                    dead = not rep.health()["live"]
                except Exception:
                    dead = True
            if stale or dead:
                self._mark_down(
                    idx,
                    "heartbeat stale" if stale else "liveness probe failed",
                )

    def _mark_down(self, idx: int, reason: str) -> None:
        with self._lock:
            if idx in self._down or idx in self._retired:
                return
            self._down.add(idx)
            victims = []
            for freq in self._outstanding:
                mine = [a for a in freq.assignments if a.replica_idx == idx]
                for a in mine:
                    a.removed = True
                    freq.assignments.remove(a)
                if (
                    mine and not freq.done and not freq.assignments
                    and not freq.pending_failover
                ):
                    freq.pending_failover = True
                    victims.append(freq)
            self._failover_q.extend(victims)
        self._bump("replicas_down")
        self.logger.error("replica %d marked down: %s", idx, reason)
        sched = self._sched_of(idx)
        if sched is not None:
            # fail whatever it still holds (processed at its next tick
            # boundary if it ever wakes); its done-callbacks will find
            # pending_failover already set and stay quiet
            sched.hard_kill(ReplicaDownError(f"router: {reason}"))

    def _drain_failover_q(self) -> None:
        while True:
            with self._lock:
                if not self._failover_q:
                    return
                freq = self._failover_q.popleft()
                if freq.done:
                    freq.pending_failover = False
                    continue
            self._failover(freq)

    def _failover(self, freq: _FleetRequest) -> None:
        """Re-dispatch onto a survivor with token-identical replay."""
        healthy = self._healthy()
        dispatched = False
        for idx, _snap in sorted(
            healthy, key=lambda h: self._load_score(h[1], self._sched_of(h[0]))
        ):
            try:
                self._dispatch(freq, idx, replay=True)
                dispatched = True
                break
            except Exception as e:
                self.logger.warning(
                    "failover dispatch to replica %d refused: %s", idx, e)
        with self._lock:
            freq.pending_failover = False
            if dispatched:
                freq.last_progress = time.monotonic()
        if dispatched:
            self._bump("failovers")
            self.logger.warning(
                "failed request over with %d delivered token(s) replayed",
                len(freq.delivered))
        else:
            self._fail(freq, FleetDownError(
                "no healthy replica left to fail over to"))

    def _sweep_hedges(self) -> None:
        now = time.monotonic()
        limit = self.hedge_ms / 1000.0
        with self._lock:
            stragglers = [
                freq for freq in self._outstanding
                if not freq.done and not freq.hedged
                and not freq.pending_failover
                and len(freq.assignments) == 1
                and (now - freq.last_progress) > limit
            ]
            for freq in stragglers:
                freq.hedged = True
        for freq in stragglers:
            self._hedge(freq)

    def _hedge(self, freq: _FleetRequest) -> None:
        """Duplicate a straggler onto another healthy replica; both keep
        running and ``_deliver`` picks the first writer per token."""
        with self._lock:
            busy = {a.replica_idx for a in freq.assignments}
        healthy = [(i, s) for i, s in self._healthy() if i not in busy]
        if not healthy:
            return
        idx = min(
            healthy,
            key=lambda h: self._load_score(h[1], self._sched_of(h[0])),
        )[0]
        try:
            self._dispatch(freq, idx, replay=True)
        except Exception as e:
            self.logger.warning("hedge dispatch to replica %d refused: %s",
                                idx, e)
            return
        self._bump("hedges")
        self.logger.warning(
            "hedged straggler onto replica %d (%d token(s) replayed)",
            idx, len(freq.delivered))

    # ------------------------------------------------------------------ #

    def _bump(self, name: str, n: int = 1) -> None:
        get_registry().counter(f"serving_fleet_{name}").inc(n)

    # identical, but callable where self._lock is already held (the
    # registry has its own lock and never calls back out)
    _bump_locked = _bump
