"""Prefill/decode disaggregation with a fleet-shared KV cache tier.

The DistServe/vLLM-style split the ROADMAP names (item 4): dedicated
PREFILL replicas absorb the compute-bound prompt phase so the DECODE
fleet's iteration loop stops stalling behind prefill bursts, and the
per-replica prefix caches federate into one fleet tier — a miss on the
replica that will decode but a hit anywhere else becomes a block
TRANSFER (serving/kv_transfer.py) instead of a recompute.

Topology: the decode fleet is an unmodified :class:`ServingFleet`
(router placement, failover replay, autoscaler verbs all intact); the
prefill replicas are extra engines built by the SAME ``replica_factory``
but never registered with the router — they serve no client traffic,
only ``max_new_tokens=1`` priming requests that populate their paged
pool for export.  A :class:`FleetCacheDirectory` maps content-addressed
first-block keys (the router's affinity-key construction, namespace-
seeded like kv_pool chain keys) to the DECODE replica currently holding
that prefix, and `ServingFleet.remove_replica` evicts a retiree's
entries before its drain starts, so a directory hit can never name a
replica that is no longer exportable.

Why parity is free: prefill under a fixed (config, params, bucket) is a
deterministic jit program, so a transferred block is bitwise identical
to the block the decode replica would have computed itself — a request
served through any arm of the recovery ladder emits the same tokens.

The recovery ladder — every transfer edge degrades, none fail the
request:

==========================  =========================================
transfer edge fault         recovery (counter)
==========================  =========================================
prefill replica dies        export future fails -> decode-side local
mid-transfer                recompute (``serving_disagg_transfer_
                            recomputes``)
corrupt/truncated payload   per-block CRC-32 reject at import, chain
                            dropped, suffix recomputed (``serving_
                            disagg_rejects`` + the importing engine's
                            ``kv_transfer_rejects``)
stalled transfer            bounded ``transfer_deadline_ms`` wait trips
                            -> colocated path (``serving_disagg_
                            deadline_degrades``)
decode replica dies         PR 12 failover replay re-routes the
mid-handoff                 request; the stranded directory entry is
                            evicted on its next failed export
==========================  =========================================

Staging is ASYNC (a small ``disagg-xfer`` worker pool): ``submit``
returns immediately and the worker stages blocks onto the replica
``FleetRouter.peek_placement`` names, then chains the real fleet submit
to the caller's future.  The staging path is wrapped whole in the
degrade-to-colocated net: any exception inside it is accounting, not an
error the client sees.

The HOST half of an export — device→host block copies plus the CRC
seal — runs on a separate bounded ``kv-staging`` executor
(``serving.disagg.staging_workers`` / ``staging_chunk_rows``), not on
the source scheduler's loop thread: the scheduler only dispatches lazy
device slices (``kv_transfer.extract_block_refs``) at a tick boundary,
so exporting a prefix no longer stalls the exporter's own decode
dispatch behind numpy copies.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..engine import fault
from ..telemetry.registry import get_registry
from . import kv_transfer
from .fleet import ServingFleet

__all__ = ["DisaggFleet", "FleetCacheDirectory"]


class FleetCacheDirectory:
    """Fleet-shared prefix-cache directory: content key -> holder replica.

    Keys are the router's affinity-key construction — the prompt's first
    full KV block, seeded with the tenant namespace exactly like
    kv_pool's chain keys, so cross-tenant (LoRA-namespaced) prompts can
    never alias an entry and therefore never transfer across
    namespaces.  Values are decode-replica router indices (the only
    exportable long-lived holders).  Bounded LRU; thread-safe (router
    worker threads, drain handlers, and the autoscaler all consult it).
    Counters mirror into the process registry as
    ``serving_fleet_cache_*`` so the serve bench and fleet snapshot read
    one ledger.
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"directory capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, int]" = OrderedDict()
        self._hits = 0
        self._misses = 0
        self._rejects = 0
        self._evictions = 0

    @staticmethod
    def key_of(prompt, block_size: int, namespace=-1) -> Optional[tuple]:
        """The prompt's directory identity: ``(namespace, first block)``.

        ``None`` when the prompt cannot contribute a cached block at all
        (kv_pool caches ``(len - 1) // block_size`` full blocks — same
        cutoff as the router's affinity key).
        """
        prompt = np.asarray(prompt)
        if block_size < 1 or (int(prompt.size) - 1) // block_size < 1:
            return None
        return (namespace, tuple(int(t) for t in prompt[:block_size]))

    def _bump(self, name: str, n: int = 1) -> None:
        get_registry().counter(f"serving_fleet_cache_{name}").inc(n)

    def publish(self, key: tuple, holder: int) -> None:
        """Record ``holder`` as the replica owning ``key``'s prefix
        blocks (last writer wins — the freshest holder is the least
        likely to have LRU-evicted the blocks locally)."""
        with self._lock:
            self._entries[key] = int(holder)
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self._evictions += 1

    def lookup(self, key: tuple) -> Optional[int]:
        """The holding replica, or ``None`` (counts the hit/miss)."""
        with self._lock:
            holder = self._entries.get(key)
            if holder is None:
                self._misses += 1
            else:
                self._entries.move_to_end(key)
                self._hits += 1
        self._bump("hits" if holder is not None else "misses")
        return holder

    def count_reject(self, n: int = 1) -> None:
        """A transferred payload failed its checksum at import."""
        with self._lock:
            self._rejects += n
        self._bump("rejects", n)

    def evict_replica(self, holder: int) -> int:
        """Drop every entry held by ``holder`` (retire/death coherence);
        returns how many were evicted."""
        with self._lock:
            doomed = [k for k, v in self._entries.items() if v == holder]
            for k in doomed:
                del self._entries[k]
            self._evictions += len(doomed)
        return len(doomed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": self._hits,
                "misses": self._misses,
                "rejects": self._rejects,
                "evictions": self._evictions,
            }


class DisaggFleet:
    """Disaggregated serving: decode :class:`ServingFleet` + prefill
    replicas + the transfer coordinator.  Mirrors the fleet's client
    verbs, so benches and tests drive either interchangeably."""

    def __init__(
        self,
        fleet: ServingFleet,
        disagg: Optional[Dict[str, Any]] = None,
        prefill_replicas: Optional[List[Any]] = None,
        logger: Optional[logging.Logger] = None,
    ):
        """``disagg`` is the raw ``serving.disagg`` config section;
        ``prefill_replicas`` overrides its ``prefill_replicas`` count
        with ready-built engines (tests inject hand-ticked ones)."""
        dcfg = dict(disagg or {})
        if not bool(dcfg.pop("enabled", True)):
            raise ValueError(
                "serving.disagg.enabled is false — build a ServingFleet "
                "instead of a DisaggFleet"
            )
        n_prefill = int(dcfg.pop("prefill_replicas", 1))
        deadline_ms = float(dcfg.pop("transfer_deadline_ms", 2000.0))
        capacity = int(dcfg.pop("directory_capacity", 4096))
        workers = int(dcfg.pop("transfer_workers", 2))
        staging_workers = int(dcfg.pop("staging_workers", 1))
        staging_chunk = dcfg.pop("staging_chunk_rows", None)
        if dcfg:
            raise ValueError(f"unknown serving.disagg keys: {sorted(dcfg)}")
        if deadline_ms <= 0:
            raise ValueError(
                f"transfer_deadline_ms must be > 0, got {deadline_ms}"
            )
        if workers < 1:
            raise ValueError(f"transfer_workers must be >= 1, got {workers}")
        if staging_workers < 1:
            raise ValueError(
                f"staging_workers must be >= 1, got {staging_workers}"
            )
        if staging_chunk is not None and int(staging_chunk) < 1:
            raise ValueError(
                f"staging_chunk_rows must be >= 1, got {staging_chunk}"
            )
        if n_prefill < 1:
            raise ValueError(
                f"serving.disagg.prefill_replicas must be >= 1, got {n_prefill}"
            )
        self.fleet = fleet
        self.router = fleet.router
        if prefill_replicas is None:
            # prefill identities start at 100: their serving_r<id>_*
            # telemetry namespace can never collide with decode replicas
            # the autoscaler adds later
            prefill_replicas = [
                fleet.replica_factory(100 + i) for i in range(n_prefill)
            ]
        self.prefill_replicas = list(prefill_replicas)
        self.directory = FleetCacheDirectory(capacity)
        # membership coherence: remove_replica evicts through this hook
        fleet.cache_directory = self.directory
        self.transfer_deadline_s = deadline_ms / 1000.0
        self.logger = logger or logging.getLogger("pdt.serving.disagg")
        self._exec = ThreadPoolExecutor(
            max_workers=workers,
            thread_name_prefix="disagg-xfer",
        )
        # host-staging executor: the device→host block copies + CRC seal
        # of an export run HERE, not on the source scheduler's loop
        # thread — the scheduler only dispatches lazy device slices
        # (kv_transfer.extract_block_refs) at a tick boundary, so a
        # transfer no longer steals decode-dispatch time from the
        # prefill replica it exports from.  Bounded separately from the
        # transfer coordinators so a burst of staging work queues rather
        # than fanning out across every core.
        self._staging_chunk = (
            int(staging_chunk) if staging_chunk is not None else None
        )
        self._stage_exec = ThreadPoolExecutor(
            max_workers=staging_workers,
            thread_name_prefix="kv-staging",
        )
        self._lock = threading.Lock()
        self._xfer_no = 0  # transfer ordinal (1-based) — the fault clock
        self._staging: set = set()  # keys with a transfer in flight
        self._dead_prefill: set = set()
        self._rr = 0  # prefill round-robin cursor
        self._closed = False

    # ------------------------------------------------------------------ #

    @classmethod
    def from_config(cls, cfg: Dict[str, Any], logger=None) -> "DisaggFleet":
        """Build the decode fleet from ``serving.fleet`` and the prefill
        side from ``serving.disagg`` — one checkpoint resolution total
        (prefill replicas come from the fleet's stored factory)."""
        logger = logger or logging.getLogger(__name__)
        fleet = ServingFleet.from_config(cfg, logger=logger)
        try:
            out = cls(fleet, disagg=cfg["serving"].get("disagg"),
                      logger=logger)
        except BaseException:
            fleet.close()
            raise
        logger.info(
            "disaggregated fleet up: %d decode replica(s), %d prefill "
            "replica(s), transfer deadline %.0f ms",
            len(fleet.replicas), len(out.prefill_replicas),
            out.transfer_deadline_s * 1000.0,
        )
        return out

    # ------------------------------------------------------------------ #
    # client verbs

    def submit(
        self,
        prompt,
        deadline_ms: Optional[float] = None,
        max_new_tokens: Optional[int] = None,
        on_token: Optional[Callable[[int], None]] = None,
        rng=None,
    ) -> Future:
        """Route one prompt; KV staging happens off-thread first.

        Prompts too short to own a cached block (or submitted after
        close began) skip staging entirely — the plain colocated path.
        The returned future resolves with the fleet result; staging
        failures are counters, never client errors.
        """
        prompt = np.asarray(prompt, np.int32)
        bs = self._block_size()
        key = (
            FleetCacheDirectory.key_of(prompt, bs) if bs is not None else None
        )
        if key is None:
            return self.fleet.submit(
                prompt, deadline_ms=deadline_ms,
                max_new_tokens=max_new_tokens, on_token=on_token, rng=rng,
            )
        outer: Future = Future()
        try:
            self._exec.submit(
                self._serve, prompt, key, deadline_ms, max_new_tokens,
                on_token, rng, outer,
            )
        except RuntimeError:  # executor shut down mid-close
            return self.fleet.submit(
                prompt, deadline_ms=deadline_ms,
                max_new_tokens=max_new_tokens, on_token=on_token, rng=rng,
            )
        return outer

    def depth(self) -> int:
        return self.fleet.depth()

    def health(self) -> Dict[str, Any]:
        return self.fleet.health()

    def live_replicas(self) -> int:
        return self.fleet.live_replicas()

    def snapshot(self) -> Dict[str, Any]:
        """Fleet snapshot + the disagg tier: directory state, transfer
        ordinal, and per-prefill-replica sub-snapshots."""
        snap = self.fleet.snapshot()
        with self._lock:
            transfers = self._xfer_no
        snap["disagg"] = {
            "directory": self.directory.snapshot(),
            "transfers": transfers,
            "prefill_replicas": len(self.prefill_replicas),
            "prefill": {
                f"p{i}": rep.metrics.snapshot()
                for i, rep in enumerate(self.prefill_replicas)
                if hasattr(rep, "metrics")
            },
        }
        return snap

    def drain(self, deadline_ms: Optional[float] = None) -> float:
        return self.fleet.drain(deadline_ms)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._exec.shutdown(wait=True)
        self._stage_exec.shutdown(wait=True)
        for i, rep in enumerate(self.prefill_replicas):
            try:
                rep.close()
            except Exception:
                self.logger.exception("prefill replica %d close failed", i)
        self.fleet.close()
        self._report_unfired_faults()

    def _report_unfired_faults(self) -> None:
        """Same contract as the scheduler's: an armed transfer fault the
        coordinator never reached must end the run accounted, not lost."""
        pending = fault.get_injector().pending()
        for kind, steps in pending.items():
            if not (
                kind.startswith("kv_transfer_") or kind == "prefill_replica_down"
            ):
                continue
            fault.bump(f"fault_unfired_{kind}", len(steps))
            self.logger.warning(
                "disagg coordinator closed with injected %s fault(s) still "
                "armed for transfer(s) %s — no transfer reached them",
                kind, steps,
            )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------ #
    # staging pipeline (disagg-xfer worker threads)

    def _serve(self, prompt, key, deadline_ms, max_new_tokens, on_token,
               rng, outer: Future) -> None:
        try:
            self._stage(prompt, key)
        except Exception:
            # the catch-all rung of the ladder: staging NEVER fails a
            # request — whatever happened, decode recomputes locally
            self._bump("transfer_recomputes")
            self.logger.exception(
                "disagg staging failed; degrading to colocated recompute"
            )
        try:
            inner = self.fleet.submit(
                prompt, deadline_ms=deadline_ms,
                max_new_tokens=max_new_tokens, on_token=on_token, rng=rng,
            )
        except Exception as exc:
            if not outer.done():
                outer.set_exception(exc)
            return

        def _chain(f: Future) -> None:
            if outer.done():
                return
            exc = f.exception()
            if exc is not None:
                outer.set_exception(exc)
            else:
                outer.set_result(f.result())

        inner.add_done_callback(_chain)

    def _stage(self, prompt, key) -> None:
        """Make ``key``'s prefix blocks local to the decode target."""
        with self._lock:
            if self._closed or key in self._staging:
                # single-flight per key: the second waiter follows the
                # sticky placement and hits whatever the first landed
                return
            self._staging.add(key)
        try:
            self._stage_inner(prompt, key)
        finally:
            with self._lock:
                self._staging.discard(key)

    def _stage_inner(self, prompt, key) -> None:
        target = self.router.peek_placement(prompt)
        if target is None:
            return  # nothing healthy: the fleet submit will shed/raise
        holder = self.directory.lookup(key)
        if holder == target:
            return  # fleet-cache hit, already local to the decode target
        source = None
        if holder is not None:
            source = self._sched_of_decode(holder)
            if source is None:
                # stranded entry (holder died outside the retire path)
                self.directory.evict_replica(holder)
                holder = None
        if holder is None:
            source = self._prefill_source(prompt)
            if source is None:
                return  # no prefill capacity left: plain colocated path
        self._transfer(prompt, key, source, holder, target)

    def _transfer(self, prompt, key, source, holder, target) -> None:
        """One ordinal on the transfer clock: export from ``source``,
        CRC-verify + import at ``target``, publish on success.  The
        injected ``kv_transfer_*``/``prefill_replica_down`` faults key
        on this ordinal."""
        with self._lock:
            self._xfer_no += 1
            ordinal = self._xfer_no
        stall_s = corrupt = None
        inj = fault.get_injector()
        if inj.active:
            down = inj.take("prefill_replica_down", ordinal)
            if down is not None:
                self._kill_prefill(int(down))
            stall_s = inj.take("kv_transfer_stall", ordinal)
            corrupt = inj.take("kv_transfer_corrupt", ordinal)
        tgt_sched = self._sched_of_decode(target)
        if tgt_sched is None:
            return
        self._bump("transfers")
        t0 = time.perf_counter()
        try:
            refs = source.export_kv_refs(
                prompt, namespace=-1, stall_s=stall_s,
            ).result(timeout=self.transfer_deadline_s)
            if not refs:
                # the source LRU-evicted the prefix between directory
                # lookup and export: recompute, and unpublish the holder
                if holder is not None:
                    self.directory.evict_replica(holder)
                self._bump("transfer_recomputes")
                return
            # host staging (device→host copies + CRC) on the bounded
            # kv-staging executor — the scheduler thread only paid the
            # device slice dispatch above
            payloads = self._stage_exec.submit(
                kv_transfer.materialize_payloads, refs, self._staging_chunk,
            ).result(timeout=self.transfer_deadline_s)
            if corrupt is not None:
                kv_transfer.corrupt_payload(payloads[0])
                self.logger.warning(
                    "fault injection: corrupted kv payload on transfer %d",
                    ordinal,
                )
            res = tgt_sched.import_kv_blocks(payloads).result(
                timeout=self.transfer_deadline_s
            )
        except (TimeoutError, FutureTimeoutError):
            self._bump("deadline_degrades")
            self.logger.warning(
                "kv transfer %d exceeded its %.0f ms deadline; degrading "
                "to the colocated path", ordinal,
                self.transfer_deadline_s * 1000.0,
            )
            return
        except Exception as exc:
            # source or target died mid-transfer (the headline fault):
            # the request recomputes/replays wherever it lands
            self._bump("transfer_recomputes")
            if holder is not None:
                self.directory.evict_replica(holder)
            self.logger.warning(
                "kv transfer %d failed (%s: %s); degrading to local "
                "recompute", ordinal, type(exc).__name__, exc,
            )
            return
        if res["rejected"]:
            self.directory.count_reject(res["rejected"])
            self._bump("rejects", res["rejected"])
        if res["accepted"] or not res["rejected"]:
            # the target now holds at least the verified prefix (an
            # all-skipped import means it already held everything)
            self.directory.publish(key, target)
        self.logger.debug(
            "kv transfer %d: %d block(s)/%d bytes to replica %d in %.1f ms",
            ordinal, res["accepted"], res["bytes"], target,
            (time.perf_counter() - t0) * 1000.0,
        )

    # ------------------------------------------------------------------ #
    # helpers

    def _bump(self, name: str, n: int = 1) -> None:
        get_registry().counter(f"serving_disagg_{name}").inc(n)

    @staticmethod
    def _sched_of(rep):
        # engines carry a .scheduler; tests hand in bare schedulers
        return getattr(rep, "scheduler", rep)

    def _block_size(self) -> Optional[int]:
        reps = self.fleet.replicas
        if not reps:
            return None
        return getattr(self._sched_of(reps[0]), "_block_size", None)

    def _sched_of_decode(self, idx: int):
        """The decode replica's scheduler iff it is still usable."""
        reps = self.fleet.replicas
        if not 0 <= idx < len(reps):
            return None
        sched = self._sched_of(reps[idx])
        if sched is None or sched._closed or sched._dead:
            return None
        return sched

    def _prefill_source(self, prompt):
        """Prime a prefill replica's pool with this prompt and return its
        scheduler as the export source (round-robin over survivors)."""
        n = len(self.prefill_replicas)
        for _ in range(n):
            with self._lock:
                idx = self._rr % n
                self._rr += 1
                if idx in self._dead_prefill:
                    continue
            rep = self.prefill_replicas[idx]
            try:
                # exactly one prefill program call: max_new_tokens=1
                # samples its token from the prefill logits and stops —
                # the token is discarded, the registered prefix is the
                # product
                rep.submit(prompt, max_new_tokens=1).result(timeout=600)
                return self._sched_of(rep)
            except Exception as exc:
                with self._lock:
                    self._dead_prefill.add(idx)
                self.logger.warning(
                    "prefill replica %d unusable (%s: %s); trying the next",
                    idx, type(exc).__name__, exc,
                )
        self._bump("prefill_unavailable")
        return None

    def _kill_prefill(self, idx: int) -> None:
        """The ``prefill_replica_down`` fault: hard-kill prefill replica
        ``idx`` so the in-flight export dies mid-transfer."""
        if not 0 <= idx < len(self.prefill_replicas):
            return
        self.logger.warning(
            "fault injection: prefill replica %d down mid-transfer", idx
        )
        self._bump("prefill_replicas_down")
        sched = self._sched_of(self.prefill_replicas[idx])
        if sched is not None:
            sched.hard_kill(
                fault.DeviceLostError(
                    f"injected prefill replica {idx} loss mid-transfer"
                )
            )
        with self._lock:
            self._dead_prefill.add(idx)
