"""lock-discipline: declared-shared attributes only move under their lock.

The convention (documented in RULES.md):

  - An attribute assignment carrying a trailing comment
    ``# guarded by: self._lock`` declares that attribute SHARED between
    threads and guarded by that lock/condition expression.  The natural
    place is the ``__init__`` that creates it.
  - Every read or write of a declared attribute in any OTHER method of
    the class must sit inside a ``with <guard>:`` block — or the method
    itself must be declared lock-held context, either by the naming
    convention ``*_locked`` or by carrying the same ``# guarded by:``
    comment on its ``def`` line (for helpers whose contract is "caller
    holds the lock").
  - ``__init__`` is exempt: object construction happens-before any
    thread that could observe the attribute (thread starts and object
    publication provide the barrier).

This is lockset analysis at its cheapest: no aliasing, no inter-
procedural reasoning — but it is exactly the discipline the codebase's
five host-side thread types (async checkpoint writer, heartbeat,
watchdog, worker pool, continuous-batching scheduler) already follow by
hand, and making it mechanical means a refactor that hoists a read out
of a ``with`` block fails analysis instead of corrupting a chaos run
once a month.  Benign races (single-writer counters read for telemetry)
are suppressed inline with a justification, which doubles as the
documentation that the race was SEEN and judged.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    SEVERITY_ERROR,
    AnalysisContext,
    AnalysisPass,
    Finding,
    SourceModule,
    dotted_name,
)

__all__ = ["LockDisciplinePass", "GUARDED_BY_RE"]

GUARDED_BY_RE = re.compile(r"#\s*guarded by:\s*(self\.[A-Za-z_]\w*)")


class _ClassAudit:
    """Guarded-attribute declarations + lock-held methods for one class."""

    def __init__(self, module: SourceModule, cls: ast.ClassDef):
        self.module = module
        self.cls = cls
        # attr name -> (guard expr, declaring line)
        self.guarded: Dict[str, Tuple[str, int]] = {}
        # method node -> set of guards assumed held on entry
        self.held_on_entry: Dict[ast.AST, Set[str]] = {}
        self.methods: List[ast.AST] = [
            n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self._collect_declarations()
        self._collect_locked_methods()

    def _line_guard(self, lineno: int) -> Optional[str]:
        if 1 <= lineno <= len(self.module.lines):
            m = GUARDED_BY_RE.search(self.module.lines[lineno - 1])
            if m:
                return m.group(1)
        return None

    def _collect_declarations(self) -> None:
        for method in self.methods:
            for node in ast.walk(method):
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        guard = self._line_guard(t.lineno)
                        if guard:
                            self.guarded.setdefault(t.attr, (guard, t.lineno))

    def _collect_locked_methods(self) -> None:
        all_guards = {g for g, _ in self.guarded.values()}
        for method in self.methods:
            held: Set[str] = set()
            guard = self._line_guard(method.lineno)
            if guard:
                held.add(guard)
            if method.name.endswith("_locked"):
                # naming convention: caller holds the class's guard(s);
                # with several distinct guards, prefer the explicit comment
                held.update(all_guards)
            self.held_on_entry[method] = held


class _MethodChecker(ast.NodeVisitor):
    """Track `with <guard>:` nesting and flag naked guarded accesses."""

    def __init__(self, audit: _ClassAudit, method: ast.AST, rule: str):
        self.audit = audit
        self.method = method
        self.rule = rule
        self.held: Set[str] = set(audit.held_on_entry.get(method, ()))
        self.findings: List[Finding] = []

    # -- lock acquisition ------------------------------------------------ #

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    def _visit_with(self, node) -> None:
        acquired = []
        for item in node.items:
            expr = dotted_name(item.context_expr)
            if expr and expr not in self.held:
                acquired.append(expr)
        self.held.update(acquired)
        for stmt in node.body:
            self.visit(stmt)
        self.held.difference_update(acquired)
        # context expressions themselves are evaluated unlocked
        for item in node.items:
            self.visit(item.context_expr)

    # -- scope boundaries ------------------------------------------------ #

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_nested(node)

    def _visit_nested(self, node) -> None:
        # A nested def runs at CALL time, not where it is defined: the
        # enclosing with-block's lock is not held when it eventually runs
        # (thread targets are the canonical case).  Check it with an empty
        # lockset unless its own def line declares otherwise.
        saved = self.held
        self.held = set()
        guard = self.audit._line_guard(node.lineno)
        if guard:
            self.held.add(guard)
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved = self.held
        self.held = set()
        self.visit(node.body)
        self.held = saved

    # -- the accesses ---------------------------------------------------- #

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            info = self.audit.guarded.get(node.attr)
            if info is not None:
                guard, decl_line = info
                if guard not in self.held:
                    verb = "written" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
                    self.findings.append(
                        Finding(
                            rule=self.rule,
                            severity=SEVERITY_ERROR,
                            path=self.audit.module.rel,
                            line=node.lineno,
                            # no declaration line number in the message:
                            # baseline keys must survive code motion
                            message=(
                                f"self.{node.attr} {verb} without holding {guard} in "
                                f"{self.audit.cls.name}.{self.method.name} "
                                "(attribute declared shared)"
                            ),
                        )
                    )
        self.generic_visit(node)


class LockDisciplinePass(AnalysisPass):
    rule = "lock-discipline"
    description = (
        "attributes declared '# guarded by: self._lock' must only be "
        "accessed under that lock outside __init__"
    )

    def run(self, modules: Sequence[SourceModule], ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for module in modules:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(self._check_class(module, node))
        return findings

    def _check_class(self, module: SourceModule, cls: ast.ClassDef) -> List[Finding]:
        audit = _ClassAudit(module, cls)
        if not audit.guarded:
            return []
        findings: List[Finding] = []
        for method in audit.methods:
            if method.name == "__init__":
                continue  # construction happens-before publication
            checker = _MethodChecker(audit, method, self.rule)
            for stmt in method.body:
                checker.visit(stmt)
            findings.extend(checker.findings)
        return findings
