"""marker-convention: the repo's test/telemetry structural conventions.

Migrated from the ad-hoc AST guard that used to live entirely inside
``tests/test_marker_convention.py`` (PRs 2-7 grew it one rule at a time);
the test file now just invokes this pass, so the rules run identically
from the CLI, ``bench.py lint``, and the tier-1 gate.  Three sub-rules:

  - **bench-slow**: a test function whose body drives ``bench.py`` (by
    subprocess or an in-process ``bench_*()`` entry point) pays model
    compiles + timed windows and must be ``@pytest.mark.slow`` — the
    tier-1 gate runs ``-m 'not slow'`` inside a fixed budget.
  - **fault-chaos**: a test touching the fault machinery
    (FaultInjector/watchdog/elastic/worker-pool kill paths) AND a heavy
    indicator (process spawns/kills, wall-clock sleeps) is a chaos test
    and must carry ``slow`` or ``chaos``.
  - **counter-store**: all observability counters flow through
    ``telemetry/registry.py``; assigning ``self._counters = {}`` (or a
    ``Counter()``/``defaultdict()``) anywhere else in the package
    reintroduces a private ledger the goodput snapshot cannot see.
  - **pass-registration**: every ``AnalysisPass`` subclass defined under
    ``analysis/`` must appear in the ``ALL_PASSES`` tuple in
    ``analysis/__init__.py``.  A pass that exists but is not registered
    silently runs nowhere — not in the CLI, not in ``bench.py lint``,
    not in the tier-1 gate — which is exactly the failure mode a lint
    framework must refuse to allow for itself.

The tests scan covers ``tests/test_*.py``; the counter scan covers the
package tree minus ``telemetry/`` (the one place ledgers may live) and
``analysis/`` (this package names the patterns it hunts).
"""
from __future__ import annotations

import ast
from typing import List, Sequence

from .core import (
    SEVERITY_ERROR,
    AnalysisContext,
    AnalysisPass,
    Finding,
    SourceModule,
)

__all__ = ["MarkerConventionPass", "is_counter_store"]

# Anything that runs a bench — shelling out to bench.py OR calling a bench
# entry point in-process — pays compiles and timed windows.
BENCH_DRIVERS = (
    "bench.py",
    "import bench",
    "bench_ckpt(",
    "bench_chaos(",
    "bench_serve(",
    "bench_chaos_serve(",
    "bench_chaos_integrity(",
    "bench_overlap(",
    "bench_chaos_fleet(",
    "bench_fleet_serve(",
    "bench_soak(",
    "bench_serve_modes(",
    "bench_autoscale(",
    "bench_disagg(",
    "bench_chaos_disagg(",
)

FAULT_MACHINERY = (
    "FaultInjector",
    "fault.install",
    "PDT_FAULT_SPEC",
    "StepWatchdog",
    "ProcessLoaderPool",
    "ElasticCoordinator",
    "IntegritySentinel",
    "kill_peer",
    "sdc_flip",
    "multihost_worker",
    "MH_ELASTIC",
    "ChaosSoakEngine",
    "ScenarioGenerator",
)
HEAVY_INDICATORS = ("time.sleep(", "os.kill(", "Process(", "subprocess")

# Files that NAME the machinery without driving it: the legacy guard file
# (kept as a wrapper) and the analyzer's own test battery (its fixtures
# quote the banned strings).
_EXEMPT_TEST_FILES = {"test_marker_convention.py", "test_static_analysis.py"}

_COUNTER_STORE_NAMES = ("_counters", "counters", "_counter_store")
_COUNTER_STORE_VALUES = ("dict", "Counter", "defaultdict", "OrderedDict")


def is_counter_store(node: ast.AST) -> bool:
    """An Assign/AnnAssign binding a counter-ish name to a fresh mapping."""
    if isinstance(node, ast.AnnAssign):
        targets, value = [node.target], node.value
    elif isinstance(node, ast.Assign):
        targets, value = node.targets, node.value
    else:
        return False
    named = False
    for t in targets:
        name = t.attr if isinstance(t, ast.Attribute) else (
            t.id if isinstance(t, ast.Name) else ""
        )
        if name in _COUNTER_STORE_NAMES or name.endswith("_counters"):
            named = True
    if not named or value is None:
        return False
    if isinstance(value, ast.Dict) and not value.keys:
        return True  # = {}
    if isinstance(value, ast.Call):
        fn = value.func
        fn_name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else ""
        )
        return fn_name in _COUNTER_STORE_VALUES
    return False


class MarkerConventionPass(AnalysisPass):
    rule = "marker-convention"
    description = (
        "bench-driving tests are slow-marked, fault-machinery tests are "
        "slow/chaos-marked, counters route through telemetry/registry"
    )

    def run(self, modules: Sequence[SourceModule], ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_tests(ctx))
        findings.extend(self._check_counter_stores(modules))
        findings.extend(self._check_pass_registration(modules))
        return findings

    # ------------------------------------------------------------------ #

    def _check_tests(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        tests_dir = ctx.resolved_tests_dir()
        if not tests_dir.is_dir():
            return findings
        for path in sorted(tests_dir.glob("test_*.py")):
            if path.name in _EXEMPT_TEST_FILES:
                continue
            rel = path.relative_to(ctx.repo_root).as_posix() if (
                ctx.repo_root in path.parents
            ) else path.name
            tree = ast.parse(path.read_text())
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if not node.name.startswith("test_"):
                    continue
                body_src = ast.unparse(node)
                decorators = [ast.unparse(d) for d in node.decorator_list]
                if any(b in body_src for b in BENCH_DRIVERS) and not any(
                    "slow" in d for d in decorators
                ):
                    findings.append(
                        Finding(
                            rule=self.rule,
                            severity=SEVERITY_ERROR,
                            path=rel,
                            line=node.lineno,
                            message=(
                                f"{node.name} drives bench.py (subprocess or "
                                "in-process bench_* entry point) without "
                                "@pytest.mark.slow — tier-1 runs -m 'not "
                                "slow' in a fixed budget"
                            ),
                        )
                    )
                if (
                    any(m in body_src for m in FAULT_MACHINERY)
                    and any(h in body_src for h in HEAVY_INDICATORS)
                    and not any("slow" in d or "chaos" in d for d in decorators)
                ):
                    findings.append(
                        Finding(
                            rule=self.rule,
                            severity=SEVERITY_ERROR,
                            path=rel,
                            line=node.lineno,
                            message=(
                                f"{node.name} exercises the fault machinery "
                                "with process spawns/kills or sleeps but "
                                "carries neither @pytest.mark.slow nor "
                                "@pytest.mark.chaos"
                            ),
                        )
                    )
        return findings

    def _check_pass_registration(self, modules: Sequence[SourceModule]) -> List[Finding]:
        """Every AnalysisPass subclass under analysis/ is in ALL_PASSES."""
        findings: List[Finding] = []
        defined = []  # (class name, module, lineno)
        registered = None  # names in the ALL_PASSES tuple, if found
        for module in modules:
            parts = module.rel.split("/")
            if "analysis" not in parts:
                continue
            for node in ast.walk(module.tree):
                if isinstance(node, ast.ClassDef) and any(
                    (isinstance(b, ast.Name) and b.id == "AnalysisPass")
                    or (isinstance(b, ast.Attribute) and b.attr == "AnalysisPass")
                    for b in node.bases
                ):
                    defined.append((node.name, module, node.lineno))
                if (
                    module.path.name == "__init__.py"
                    and isinstance(node, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id == "ALL_PASSES"
                        for t in node.targets
                    )
                    and isinstance(node.value, ast.Tuple)
                ):
                    registered = {
                        e.id if isinstance(e, ast.Name) else getattr(e, "attr", "")
                        for e in node.value.elts
                    }
        if registered is None:
            # No ALL_PASSES tuple in scope (e.g. a fixture subset) — the
            # pin only bites when the registry itself is being analyzed.
            return findings
        for name, module, lineno in defined:
            if name not in registered:
                findings.append(
                    Finding(
                        rule=self.rule,
                        severity=SEVERITY_ERROR,
                        path=module.rel,
                        line=lineno,
                        message=(
                            f"{name} subclasses AnalysisPass but is missing "
                            "from ALL_PASSES in analysis/__init__.py — an "
                            "unregistered pass runs nowhere (CLI, bench.py "
                            "lint, tier-1 gate all iterate ALL_PASSES)"
                        ),
                    )
                )
        return findings

    def _check_counter_stores(self, modules: Sequence[SourceModule]) -> List[Finding]:
        findings: List[Finding] = []
        for module in modules:
            parts = module.rel.split("/")
            if "telemetry" in parts or "analysis" in parts:
                continue
            for node in ast.walk(module.tree):
                if is_counter_store(node):
                    findings.append(
                        Finding(
                            rule=self.rule,
                            severity=SEVERITY_ERROR,
                            path=module.rel,
                            line=node.lineno,
                            message=(
                                "ad-hoc counter store outside telemetry/ — "
                                "use telemetry.registry "
                                "(get_registry().counter(name) or a private "
                                "MetricsRegistry for instance-local counts)"
                            ),
                        )
                    )
        return findings
