"""pdt-analyze: static analysis for trace purity, lock discipline,
collective order, donation safety, repo conventions, inferred-lockset
thread safety, resource lifecycles, and the generated config schema.

The analyzer itself is stdlib-only and never executes the code it
inspects (a purity checker that imported its targets would trigger the
side effects it polices).  See RULES.md (next to this file)
for the rule catalogue and suppression syntax, and
``python -m pytorch_distributed_training_tpu.analysis --help`` for the
CLI.

Programmatic entry point::

    from pytorch_distributed_training_tpu import analysis
    result = analysis.run()           # all passes over the package tree
    assert not result.unsuppressed
"""
from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

from .collectives import CollectiveOrderPass, extract_collective_sequences
from .configschema import ConfigSchemaPass, extract_schema, schema_as_json
from .conventions import MarkerConventionPass
from .core import (
    AnalysisContext,
    AnalysisPass,
    AnalysisResult,
    Finding,
    SourceModule,
    collect_modules,
    load_baseline,
    run_passes,
    write_baseline,
)
from .donation import DonationSafetyPass
from .lifecycle import ResourceLifecyclePass
from .locks import LockDisciplinePass
from .purity import TracePurityPass
from .report import json_payload, render_json, render_text
from .threads import ThreadSafetyPass

__all__ = [
    "ALL_PASSES",
    "AnalysisContext",
    "AnalysisPass",
    "AnalysisResult",
    "Finding",
    "SourceModule",
    "collect_modules",
    "extract_collective_sequences",
    "extract_schema",
    "json_payload",
    "load_baseline",
    "render_json",
    "render_text",
    "run",
    "schema_as_json",
    "write_baseline",
]

# Registration order == report order; rule name -> pass class.
ALL_PASSES = (
    TracePurityPass,
    LockDisciplinePass,
    CollectiveOrderPass,
    DonationSafetyPass,
    MarkerConventionPass,
    ThreadSafetyPass,
    ResourceLifecyclePass,
    ConfigSchemaPass,
)


def _default_context() -> AnalysisContext:
    package_root = Path(__file__).resolve().parent.parent
    return AnalysisContext(package_root=package_root, repo_root=package_root.parent)


def run(
    package_root: Optional[Path] = None,
    rules: Optional[Sequence[str]] = None,
    baseline: Optional[Path] = None,
    tests_dir: Optional[Path] = None,
    config_dir: Optional[Path] = None,
) -> AnalysisResult:
    """Run the selected passes (default: all) over ``package_root``."""
    if package_root is None:
        ctx = _default_context()
    else:
        package_root = Path(package_root).resolve()
        ctx = AnalysisContext(
            package_root=package_root, repo_root=package_root.parent
        )
    if tests_dir is not None:
        ctx.tests_dir = Path(tests_dir)
    if config_dir is not None:
        ctx.config_dir = Path(config_dir)
    passes = [cls() for cls in ALL_PASSES]
    if rules is not None:
        wanted = set(rules)
        known = {p.rule for p in passes}
        unknown = wanted - known
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; known: {sorted(known)}"
            )
        passes = [p for p in passes if p.rule in wanted]
    baseline_keys = load_baseline(baseline) if baseline else None
    return run_passes(passes, ctx, baseline_keys=baseline_keys)
