"""trace-purity: traced functions must be referentially transparent.

A function that reaches ``jax.jit`` / ``pjit`` / ``jax.shard_map`` /
``lax.while_loop`` / ``lax.scan`` / ``lax.cond`` is executed ONCE at trace
time and never again: a ``time.time()`` inside it bakes the compile-time
clock into the program, ``np.random`` draws a constant, ``os.environ``
reads silently fork the traced program across hosts (a cross-host deadlock
when a collective sits downstream), and ``print`` fires once per
(re)trace — the classic "why did my log stop" confusion that actually
signals a retrace storm.  Host-side impurity belongs OUTSIDE the traced
closure; inside it, use ``jax.random`` for randomness and
``jax.debug.print`` / ``io_callback`` for effects.

Mechanics: per module, trace roots are (a) defs decorated with
``jax.jit``/``jit``/``pjit`` (directly or through ``functools.partial``)
and (b) local defs passed by name into a jit-family call
(``jit``/``pjit``/``shard_map``/``while_loop``/``scan``/``cond``/
``fori_loop``/``checkpoint``/``remat``/``custom_vjp``...).  From the
roots, any *name reference* resolving to another def in an enclosing
scope joins the traced closure (this catches ``value_and_grad(loss_fn)``
and scan bodies without modeling higher-order flow).  Calls crossing
module boundaries are not followed — each module is analyzed against its
own closure, which keeps the pass O(tree) and the findings local.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    SEVERITY_ERROR,
    AnalysisContext,
    AnalysisPass,
    Finding,
    SourceModule,
    dotted_name,
    iter_child_statements,
)

__all__ = ["TracePurityPass", "TRACE_ENTRY_POINTS"]

# Call names (last attribute segment) whose function-valued arguments are
# traced.  ``jit`` et al. trace their first argument; control-flow
# primitives trace every callable operand — we conservatively treat every
# Name argument that resolves to a local def as entering the trace.
TRACE_ENTRY_POINTS = {
    "jit",
    "pjit",
    "shard_map",
    "while_loop",
    "scan",
    "cond",
    "switch",
    "fori_loop",
    "associative_scan",
    "checkpoint",
    "remat",
    "custom_vjp",
    "custom_jvp",
    "grad",
    "value_and_grad",
    "vmap",
    "pmap",
    "eval_shape",
}

# Dotted-prefix ban list.  An entry ending in '.' bans the whole module
# namespace; an exact entry bans that one callable/attribute.
_BANNED_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("time.", "wall-clock read baked in at trace time"),
    ("random.", "host RNG draws a trace-time constant; use jax.random"),
    ("np.random.", "host RNG draws a trace-time constant; use jax.random"),
    ("numpy.random.", "host RNG draws a trace-time constant; use jax.random"),
    ("os.environ", "env read can differ across hosts and fork the traced program"),
    ("os.getenv", "env read can differ across hosts and fork the traced program"),
    ("os.urandom", "host RNG draws a trace-time constant; use jax.random"),
    ("uuid.uuid4", "host RNG draws a trace-time constant"),
    ("datetime.now", "wall-clock read baked in at trace time"),
    ("datetime.datetime.now", "wall-clock read baked in at trace time"),
)

_BANNED_BARE_CALLS = {
    "print": "fires once per (re)trace, not per step; use jax.debug.print",
    "open": "file I/O inside a traced function runs at trace time only",
    "input": "blocking host I/O inside a traced function",
}


def _last_segment(dotted: Optional[str]) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


class _FunctionIndex:
    """All defs in a module + the scope chain needed to resolve names."""

    def __init__(self, module: SourceModule):
        self.module = module
        # def node -> (enclosing def nodes, outermost first)
        self.parents: Dict[ast.AST, Tuple[ast.AST, ...]] = {}
        # def node -> {local def name -> def node} for its immediate children
        self.children: Dict[ast.AST, Dict[str, ast.AST]] = {}
        self.module_defs: Dict[str, ast.AST] = {}
        self.qualnames: Dict[ast.AST, str] = {}
        self._index(module.tree, (), ())

    def _index(self, node: ast.AST, chain: Tuple[ast.AST, ...], names: Tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.parents[child] = chain
                self.qualnames[child] = ".".join(names + (child.name,))
                if chain:
                    self.children.setdefault(chain[-1], {})[child.name] = child
                else:
                    self.module_defs[child.name] = child
                self.children.setdefault(child, {})
                self._index(child, chain + (child,), names + (child.name,))
            elif isinstance(child, ast.ClassDef):
                # methods resolve like module-level defs scoped by class name;
                # they do not close over each other by bare name, so no chain
                self._index(child, chain, names + (child.name,))
            else:
                self._index(child, chain, names)

    def resolve(self, name: str, scope: ast.AST) -> Optional[ast.AST]:
        """Resolve a bare name reference from inside ``scope`` to a def."""
        local = self.children.get(scope, {})
        if name in local:
            return local[name]
        for parent in reversed(self.parents.get(scope, ())):
            sibling = self.children.get(parent, {})
            if name in sibling:
                return sibling[name]
        return self.module_defs.get(name)


class TracePurityPass(AnalysisPass):
    rule = "trace-purity"
    description = (
        "functions reaching jit/pjit/shard_map/while_loop/scan must not "
        "perform host I/O, host RNG, clock/env reads, or global mutation"
    )

    def run(self, modules: Sequence[SourceModule], ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for module in modules:
            findings.extend(self._run_module(module))
        return findings

    # ------------------------------------------------------------------ #

    def _run_module(self, module: SourceModule) -> List[Finding]:
        index = _FunctionIndex(module)
        roots = self._trace_roots(module, index)
        if not roots:
            return []
        closure = self._closure(roots, index)
        findings: List[Finding] = []
        for func, root in closure.items():
            findings.extend(self._check_function(module, index, func, root))
        return findings

    def _trace_roots(self, module: SourceModule, index: _FunctionIndex) -> Dict[ast.AST, ast.AST]:
        roots: Dict[ast.AST, ast.AST] = {}
        # (a) jit/pjit-decorated defs
        for func in index.qualnames:
            for deco in getattr(func, "decorator_list", []):
                if self._is_jit_expr(deco):
                    roots[func] = func
        # (b) local defs passed by name into a trace entry point
        for scope in list(index.qualnames) + [module.tree]:
            body_iter = (
                iter_child_statements(scope)
                if scope is not module.tree
                else self._module_level_nodes(module.tree)
            )
            for node in body_iter:
                if not isinstance(node, ast.Call):
                    continue
                callee = _last_segment(dotted_name(node.func))
                if callee not in TRACE_ENTRY_POINTS:
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name):
                        target = index.resolve(
                            arg.id, scope if scope is not module.tree else module.tree
                        )
                        if target is None and scope is module.tree:
                            target = index.module_defs.get(arg.id)
                        if target is not None:
                            roots.setdefault(target, target)
        return roots

    def _module_level_nodes(self, tree: ast.Module):
        stack = [n for n in tree.body]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _is_jit_expr(self, node: ast.AST) -> bool:
        """`@jax.jit`, `@jit`, `@pjit`, `@jax.jit(...)`, or
        `@functools.partial(jax.jit, ...)`."""
        name = dotted_name(node)
        if name and _last_segment(name) in ("jit", "pjit"):
            return True
        if isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            if fn and _last_segment(fn) in ("jit", "pjit"):
                return True
            if fn and _last_segment(fn) == "partial":
                return any(
                    _last_segment(dotted_name(a)) in ("jit", "pjit") for a in node.args
                )
        return False

    def _closure(
        self, roots: Dict[ast.AST, ast.AST], index: _FunctionIndex
    ) -> Dict[ast.AST, ast.AST]:
        """Transitive set of defs reachable by NAME from the roots."""
        seen: Dict[ast.AST, ast.AST] = {}
        stack = [(f, f) for f in roots]
        while stack:
            func, root = stack.pop()
            if func in seen:
                continue
            seen[func] = root
            for node in iter_child_statements(func):
                if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                    target = index.resolve(node.id, func)
                    if target is not None and target not in seen:
                        stack.append((target, root))
        return seen

    def _check_function(
        self,
        module: SourceModule,
        index: _FunctionIndex,
        func: ast.AST,
        root: ast.AST,
    ) -> List[Finding]:
        findings: List[Finding] = []
        qual = index.qualnames.get(func, getattr(func, "name", "<anon>"))
        root_qual = index.qualnames.get(root, getattr(root, "name", "<anon>"))
        where = (
            f"traced function `{qual}`"
            if func is root
            else f"`{qual}` (traced via `{root_qual}`)"
        )
        reported_prefixes: Set[Tuple[int, str]] = set()
        for node in iter_child_statements(func):
            if isinstance(node, ast.Global):
                findings.append(
                    Finding(
                        rule=self.rule,
                        severity=SEVERITY_ERROR,
                        path=module.rel,
                        line=node.lineno,
                        message=(
                            f"{where} declares `global {', '.join(node.names)}`:"
                            " module-global mutation under trace runs once at"
                            " trace time and never per step"
                        ),
                    )
                )
            if isinstance(node, ast.Call):
                bare = node.func.id if isinstance(node.func, ast.Name) else None
                if bare in _BANNED_BARE_CALLS:
                    findings.append(
                        Finding(
                            rule=self.rule,
                            severity=SEVERITY_ERROR,
                            path=module.rel,
                            line=node.lineno,
                            message=f"{where} calls `{bare}(...)`: {_BANNED_BARE_CALLS[bare]}",
                        )
                    )
                    continue
            name = dotted_name(node) if isinstance(node, (ast.Attribute, ast.Call)) else None
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
            if not name:
                continue
            for prefix, why in _BANNED_PREFIXES:
                if prefix.endswith("."):
                    hit = name.startswith(prefix)
                else:
                    hit = name == prefix or name.startswith(prefix + ".")
                if hit:
                    # a Call and the Attribute nested inside it both match
                    # the same prefix; report once (the Call comes first and
                    # carries the fuller dotted name)
                    pkey = (node.lineno, prefix)
                    if pkey in reported_prefixes:
                        break
                    reported_prefixes.add(pkey)
                    findings.append(
                        Finding(
                            rule=self.rule,
                            severity=SEVERITY_ERROR,
                            path=module.rel,
                            line=node.lineno,
                            message=f"{where} uses `{name}`: {why}",
                        )
                    )
                    break
        # de-dup: an Attribute nested in a Call reports twice otherwise
        unique = []
        seen_keys = set()
        for f in findings:
            k = (f.line, f.message)
            if k not in seen_keys:
                seen_keys.add(k)
                unique.append(f)
        return unique
