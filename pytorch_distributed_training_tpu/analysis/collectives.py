"""collective-order: extract per-family collective sequences, flag
host-divergent branching around collectives.

Multi-host SPMD correctness rests on one invariant: every process issues
the SAME sequence of collectives over the SAME axes.  XLA guarantees this
within one compiled program, so the residual risk is all at trace time —
a Python-level branch whose predicate differs across hosts
(``jax.process_index()``, ``os.environ``, wall clock, host RNG) traces a
DIFFERENT program on different hosts, and the first mismatched ``psum``
deadlocks the mesh with no diagnostic (the arXiv 2004.13336 failure mode:
sharded weight-update paths where one rank skips a collective).

Two jobs:

  1. **Extraction** — :func:`extract_collective_sequences` walks each
     step-family module (modules declaring ``PDT_COLLECTIVE_FAMILY``) and
     records, per top-level builder, the ordered sequence of
     ``psum``/``pmean``/``ppermute``/``all_gather``/``all_to_all``/...
     calls with their axis expressions.  This is the mechanical oracle
     for the ROADMAP item-3 step-family unification: the unified builder
     must reproduce these sequences (pinned in PERF.md and
     tests/test_static_analysis.py).
  2. **Divergence detection** — a finding for any collective call under a
     conditional (or loop) whose predicate reads host-identity or other
     host-divergent state.  That is the statically decidable core of
     "divergent orderings that would deadlock": config-driven branches
     (``if sync_bn:``) are host-uniform by construction and not flagged.
"""
from __future__ import annotations

import ast
from typing import Dict, List, NamedTuple, Optional, Sequence

from .core import (
    SEVERITY_ERROR,
    AnalysisContext,
    AnalysisPass,
    Finding,
    SourceModule,
    dotted_name,
)

__all__ = [
    "CollectiveOrderPass",
    "CollectiveCall",
    "extract_collective_sequences",
    "COLLECTIVE_OPS",
]

COLLECTIVE_OPS = {
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "ppermute",
    "pshuffle",
    "all_gather",
    "all_to_all",
    "psum_scatter",
}

# Dotted fragments that mark a predicate as host-divergent: two hosts can
# evaluate it differently at trace time, so a collective under it traces a
# divergent program.
_HOST_DIVERGENT_MARKERS = (
    "process_index",
    "process_count",
    "host_id",
    "local_device_count",  # differs on heterogeneous hosts
    "os.environ",
    "getenv",
    "gethostname",
    "getpid",
    "time.",
    "random.",
    "np.random",
    "numpy.random",
    "urandom",
)


class CollectiveCall(NamedTuple):
    op: str
    axis: str  # source expression of the axis argument ("?" if absent)
    function: str  # enclosing def name chain, e.g. "build_train_step.body"
    line: int


def _axis_expr(node: ast.Call) -> str:
    """The axis operand: 2nd positional arg or the axis_name/axis_index kw.

    ``ppermute(x, axis_name, perm)`` and ``psum(x, axis_name)`` both carry
    the axis as the second positional; keep the raw source expression so
    symbolic names (DATA_AXIS, axes) stay readable in the oracle.
    """
    for kw in node.keywords:
        if kw.arg in ("axis_name", "axis"):
            return ast.unparse(kw.value)
    if len(node.args) >= 2:
        return ast.unparse(node.args[1])
    return "?"


def _collective_op(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    if not name:
        return None
    last = name.rsplit(".", 1)[-1]
    if last not in COLLECTIVE_OPS:
        return None
    # require a lax-ish spelling (jax.lax.psum / lax.psum / bare from-import)
    # so methods like obj.all_gather() on unrelated classes don't register
    head = name.rsplit(".", 1)[0] if "." in name else ""
    if head and head.split(".")[-1] not in ("lax", "jax"):
        return None
    return last


def _family_of(module: SourceModule) -> Optional[str]:
    for node in module.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "PDT_COLLECTIVE_FAMILY":
                    if isinstance(node.value, ast.Constant) and isinstance(
                        node.value.value, str
                    ):
                        return node.value.value
    return None


def _extract_from_def(func: ast.AST, trail: str) -> List[CollectiveCall]:
    out: List[CollectiveCall] = []

    def visit(node: ast.AST, where: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, f"{where}.{child.name}")
                continue
            if isinstance(child, ast.Call):
                op = _collective_op(child)
                if op:
                    out.append(
                        CollectiveCall(op, _axis_expr(child), where, child.lineno)
                    )
            visit(child, where)
    visit(func, trail)
    out.sort(key=lambda c: c.line)
    return out


def extract_collective_sequences(
    package_root, repo_root=None
) -> Dict[str, Dict[str, List[CollectiveCall]]]:
    """{family: {builder_name: [CollectiveCall, ...]}} for every module
    declaring ``PDT_COLLECTIVE_FAMILY``.  Order is source order, which for
    these step files equals trace order (straight-line builders)."""
    from pathlib import Path

    from .core import collect_modules

    package_root = Path(package_root)
    repo_root = Path(repo_root) if repo_root is not None else package_root.parent
    out: Dict[str, Dict[str, List[CollectiveCall]]] = {}
    for module in collect_modules(package_root, repo_root):
        family = _family_of(module)
        if family is None:
            continue
        builders: Dict[str, List[CollectiveCall]] = {}
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                calls = _extract_from_def(node, node.name)
                if calls:
                    builders[node.name] = calls
        out[family] = builders
    return out


class CollectiveOrderPass(AnalysisPass):
    rule = "collective-order"
    description = (
        "collectives must not sit under host-divergent trace-time branches "
        "(process_index/env/clock/host-RNG predicates)"
    )

    def run(self, modules: Sequence[SourceModule], ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for module in modules:
            findings.extend(self._check_module(module))
        return findings

    def _check_module(self, module: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            test = None
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            if test is None:
                continue
            marker = self._divergent_marker(test)
            if marker is None:
                continue
            if isinstance(node, ast.IfExp):
                bodies = [node.body, node.orelse]
            else:
                bodies = list(node.body) + list(node.orelse)
            for body_node in bodies:
                for sub in ast.walk(body_node):
                    if isinstance(sub, ast.Call):
                        op = _collective_op(sub)
                        if op:
                            findings.append(
                                Finding(
                                    rule=self.rule,
                                    severity=SEVERITY_ERROR,
                                    path=module.rel,
                                    line=sub.lineno,
                                    # no line numbers in the message:
                                    # baseline keys must survive code motion
                                    message=(
                                        f"`{op}` under a branch on `{marker}`"
                                        ": hosts can trace different "
                                        "collective sequences and deadlock "
                                        "the mesh"
                                    ),
                                )
                            )
        return findings

    def _divergent_marker(self, test: ast.AST) -> Optional[str]:
        src = ast.unparse(test)
        for marker in _HOST_DIVERGENT_MARKERS:
            if marker in src:
                return marker.rstrip(".")
        return None
