"""Reporters: human text and machine JSON for analysis results.

The JSON schema is pinned by tests/test_static_analysis.py — CI consumers
(bench.py lint, the chaos harness) parse it, so additive evolution only.
"""
from __future__ import annotations

import json
from typing import Dict, List

from .core import AnalysisResult, Finding

__all__ = ["render_text", "render_json", "json_payload"]

JSON_SCHEMA_VERSION = 1


def render_text(result: AnalysisResult, verbose: bool = False) -> str:
    lines: List[str] = []
    for f in result.unsuppressed:
        lines.append(f.format())
    if verbose:
        for f in result.suppressed:
            lines.append(f"{f.format()}  [suppressed]")
        for f in result.baselined:
            lines.append(f"{f.format()}  [baselined]")
    totals = result.rule_totals("unsuppressed")
    summary = ", ".join(f"{k}={v}" for k, v in sorted(totals.items())) or "clean"
    lines.append(
        f"pdt-analyze: {len(result.unsuppressed)} finding(s) "
        f"({summary}); {len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined; "
        f"{result.files_scanned} files in {result.wall_s:.2f}s"
    )
    return "\n".join(lines)


def _finding_obj(f: Finding) -> Dict:
    return {
        "rule": f.rule,
        "severity": f.severity,
        "path": f.path,
        "line": f.line,
        "message": f.message,
    }


def json_payload(result: AnalysisResult) -> Dict:
    return {
        "version": JSON_SCHEMA_VERSION,
        "findings": [_finding_obj(f) for f in result.unsuppressed],
        "summary": {
            "unsuppressed": len(result.unsuppressed),
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
            "by_rule": result.rule_totals("unsuppressed"),
            "files_scanned": result.files_scanned,
            "wall_s": round(result.wall_s, 4),
        },
    }


def render_json(result: AnalysisResult) -> str:
    return json.dumps(json_payload(result), indent=2)
