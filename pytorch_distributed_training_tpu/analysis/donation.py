"""donation-safety: donated buffers must not be touched after the call.

``jax.jit(..., donate_argnums=(0,))`` hands the argument's device buffer
to XLA for reuse: after the call the caller's array is logically dead —
touching it raises on strict backends and silently reads reused memory on
others (the bench.py "donated-buffer fix (fresh_state per phase)" in PR 5
was exactly this bug).  The pass enforces the contract statically:

  - **registration**: a def decorated ``@jax.jit(donate_argnums=...)`` or
    ``@functools.partial(jax.jit, donate_argnums=...)``, or a module/local
    binding ``f = jax.jit(g, donate_argnums=...)``, registers a donating
    callable with its donated positions/names.
  - **call sites**: at every call of a registered callable inside the same
    module, each donated argument that is a plain variable is tracked
    forward through the enclosing function: a LOAD of that variable after
    the call, before any rebinding STORE, is a finding.  The idiomatic
    consume-and-rebind loop (``state, loss = step(state, ...)``) stores on
    the same statement and passes.
  - **arity**: ``donate_argnums`` out of range of the wrapped function's
    positional signature is reported directly (a latent TypeError that
    only fires on the first real call).

Resolution is intra-module and name-based — builders that RETURN jitted
closures (this codebase's dominant pattern) are checked at their
definition site (the decorated def), while their dynamic call sites in
runner.py are out of static reach.  That boundary is deliberate: the
pass stays exact (near-zero false positives) and the donation contract
is still pinned where the donation is declared.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    SEVERITY_ERROR,
    AnalysisContext,
    AnalysisPass,
    Finding,
    SourceModule,
    dotted_name,
    iter_child_statements,
)

__all__ = ["DonationSafetyPass"]


def _last(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


class _Donor:
    def __init__(self, argnums: Tuple[int, ...], argnames: Tuple[str, ...], line: int):
        self.argnums = argnums
        self.argnames = argnames
        self.line = line


def _literal_ints(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """(0, 1) / [0] / 0 -> tuple of ints; None when not statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _literal_strs(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _donation_kwargs(call: ast.Call) -> Optional[_Donor]:
    argnums: Tuple[int, ...] = ()
    argnames: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            argnums = _literal_ints(kw.value) or ()
        elif kw.arg == "donate_argnames":
            argnames = _literal_strs(kw.value) or ()
    if argnums or argnames:
        return _Donor(argnums, argnames, call.lineno)
    return None


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The jit(...) Call carrying donation kwargs, if this expression is
    one: `jax.jit(...)` or `functools.partial(jax.jit, ...)`."""
    if not isinstance(node, ast.Call):
        return None
    fn = _last(dotted_name(node.func))
    if fn in ("jit", "pjit"):
        return node
    if fn == "partial" and any(
        _last(dotted_name(a)) in ("jit", "pjit") for a in node.args
    ):
        return node
    return None


class DonationSafetyPass(AnalysisPass):
    rule = "donation-safety"
    description = (
        "arguments listed in donate_argnums/donate_argnames must not be "
        "referenced in the caller after the jitted call"
    )

    def run(self, modules: Sequence[SourceModule], ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for module in modules:
            findings.extend(self._check_module(module))
        return findings

    # ------------------------------------------------------------------ #

    def _check_module(self, module: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        donors: Dict[str, _Donor] = {}

        # registration: decorated defs (also checks arity on the spot)
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    jc = _jit_call(deco)
                    if jc is None:
                        continue
                    donor = _donation_kwargs(jc)
                    if donor is None:
                        continue
                    donors[node.name] = donor
                    findings.extend(self._check_arity(module, node, donor))
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                jc = _jit_call(node.value)
                if isinstance(t, ast.Name) and jc is not None:
                    donor = _donation_kwargs(jc)
                    if donor is not None:
                        donors[t.id] = donor

        if donors:
            for func in ast.walk(module.tree):
                if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    findings.extend(self._check_callsites(module, func, donors))
        return findings

    def _check_arity(
        self, module: SourceModule, func: ast.AST, donor: _Donor
    ) -> List[Finding]:
        n_pos = len(func.args.posonlyargs) + len(func.args.args)
        bad = [i for i in donor.argnums if i >= n_pos and func.args.vararg is None]
        if not bad:
            return []
        return [
            Finding(
                rule=self.rule,
                severity=SEVERITY_ERROR,
                path=module.rel,
                line=func.lineno,
                message=(
                    f"donate_argnums {tuple(sorted(bad))} out of range for "
                    f"`{func.name}` ({n_pos} positional parameter(s)): "
                    "donation will TypeError on the first call"
                ),
            )
        ]

    def _check_callsites(
        self, module: SourceModule, func: ast.AST, donors: Dict[str, _Donor]
    ) -> List[Finding]:
        findings: List[Finding] = []
        for node in iter_child_statements(func):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func.id if isinstance(node.func, ast.Name) else None
            if callee not in donors:
                continue
            donor = donors[callee]
            donated_vars: List[Tuple[str, int]] = []
            for idx in donor.argnums:
                if idx < len(node.args) and isinstance(node.args[idx], ast.Name):
                    donated_vars.append((node.args[idx].id, idx))
            for kw in node.keywords:
                if kw.arg in donor.argnames and isinstance(kw.value, ast.Name):
                    donated_vars.append((kw.value.id, kw.arg))
            for var, which in donated_vars:
                use = self._use_after(func, node, var)
                if use is not None:
                    findings.append(
                        Finding(
                            rule=self.rule,
                            severity=SEVERITY_ERROR,
                            path=module.rel,
                            line=use,
                            # no line numbers in the message: baseline
                            # keys (rule:path:message) must survive code
                            # motion
                            message=(
                                f"`{var}` used after being donated to "
                                f"`{callee}` (arg {which}): the buffer is "
                                "dead once donated — rebind or copy before "
                                "the call"
                            ),
                        )
                    )
        return findings

    def _use_after(self, func: ast.AST, call: ast.Call, var: str) -> Optional[int]:
        """First line > call where `var` is LOADed before any re-STORE.

        Line-ordered scan of the enclosing function: sound for the
        straight-line epilogue code donation bugs live in; loops where the
        next iteration rebinds are handled by the same-line/lower-line
        store rule (the canonical `state = step(state)` rebinding stores
        at the call line itself).
        """
        call_line = call.end_lineno or call.lineno
        events: List[Tuple[int, str]] = []
        for node in iter_child_statements(func):
            if isinstance(node, ast.Name) and node.id == var:
                if isinstance(node.ctx, ast.Load):
                    # the donated argument itself is a Load on the call line
                    if node.lineno > call_line:
                        events.append((node.lineno, "load"))
                elif isinstance(node.ctx, (ast.Store, ast.Del)):
                    if node.lineno >= call.lineno:
                        events.append((node.lineno, "store"))
        for line, kind in sorted(events):
            if kind == "store":
                return None
            return line
        return None
