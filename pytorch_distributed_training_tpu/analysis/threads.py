"""thread-safety: inferred-lockset race detection over concurrency roots.

Where lock-discipline (locks.py) enforces the ``# guarded by:``
declarations someone remembered to write, this pass INFERS the thread
structure of every class and flags the shared state nobody declared.
Since PR 8 the host-side thread surface has roughly tripled (scheduler
tick threads, router health monitors, per-replica heartbeat writers,
async checkpoint writers, supervisors); an annotation-only checker is a
sampled audit, this is the census.

Per class, the pass:

  1. discovers **concurrency roots** — every entry point from which a
     second thread of control can run a method of the class:

       - ``threading.Thread(target=self.m)`` / ``threading.Timer``
       - executor handoffs: ``.submit(self.m)`` / ``.submit(lambda: ...)``
       - completion callbacks: ``.add_done_callback(self.m | lambda)``
       - signal handlers: ``signal.signal(sig, self.m)`` (async interrupt)
       - registered callbacks: a bound method or lambda passed as a call
         argument (``on_retry=self._count_retry``,
         ``DynamicBatcher(self._run_batch, ...)``) — the callee stores it
         and may invoke it from any thread it owns
       - the **api root**: the class's public methods, standing in for
         "whatever thread the caller is on"

     ``atexit.register`` is exempt (runs on the main thread at interpreter
     exit, after every daemon thread stops being observable), and
     ``__init__`` is never a root — construction happens-before
     publication, so helpers reached only from ``__init__`` contribute
     nothing.

  2. computes each root's transitively-reached attribute read/write sets
     through the same scope-chain resolution purity.py uses, tracking the
     **lockset** held at every access (``with self._lock:`` nesting; a
     ``# guarded by:`` comment or ``*_locked`` suffix on a ``def`` line
     seeds the entry lockset, matching lock-discipline's contract).  The
     api root does not traverse into methods owned by a real root — a
     ``drain()`` that calls the tick loop's own helper in test mode is
     the loop's code, not a second mutator.

  3. flags any ``self.*`` attribute written outside ``__init__`` and
     accessed from >= 2 roots whose locksets share no common lock.  Both
     PR 8 race shapes (an unlocked ``fires += 1`` from a monitor thread, a
     lock-free list snapshot from the api while the loop thread mutates)
     fall out of this one rule, with zero annotations required.

Declarations become verified claims rather than the only signal:

  - ``# guarded by: self._lock`` attributes are skipped here —
    lock-discipline enforces every access site against the declaration.
  - ``# confined: <root>`` (new) declares single-writer thread
    confinement: only methods owned by the named root (a root entry
    method name, or ``api``) may WRITE the attribute; cross-root reads
    are the caller's stale-read bargain and stay legal.  The pass
    verifies the confinement instead of trusting it.

Out of scope, deliberately: synchronization that is not lock-shaped.
Attributes initialized to ``threading.Event``/``Lock``/``Condition``/
``Semaphore`` or ``queue.*Queue`` are internally synchronized and
exempt; cross-CLASS calls are not followed (each class is analyzed
against its own methods, keeping the pass O(tree) like purity.py); and
happens-before edges from ``Thread.start``/``join`` are not modeled —
state handed across such an edge wants a lock or a ``# confined:``
declaration that makes the ownership legible.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    SEVERITY_ERROR,
    AnalysisContext,
    AnalysisPass,
    Finding,
    SourceModule,
    dotted_name,
)
from .locks import GUARDED_BY_RE

__all__ = ["ThreadSafetyPass", "CONFINED_RE"]

CONFINED_RE = re.compile(r"#\s*confined:\s*([A-Za-z_]\w*)")

# `self.X = <ctor>()` in __init__ with one of these constructors marks X
# internally synchronized (or a lock object itself) — exempt from the
# shared-state analysis.  `deque` is NOT here: its append/popleft are
# individually atomic but compound read-modify-write sequences are not.
_SYNC_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "local",
}

# Mutating method calls on a container attribute count as writes to the
# attribute.  `.set()` is deliberately absent (threading.Event.set — and
# Events are exempt anyway).
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "remove", "clear", "add", "discard", "update", "setdefault",
    "sort", "reverse", "move_to_end",
}

# Callees whose callable arguments do NOT run concurrently with the class.
_NON_DEFERRED_CALLEES = {"atexit.register", "atexit.unregister"}

_PUBLIC_DUNDERS = {"__call__", "__enter__", "__exit__", "__iter__", "__next__"}

_API = "api"


def _last_segment(dotted: Optional[str]) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` -> "X", else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _Access:
    __slots__ = ("attr", "write", "root", "held", "line", "method")

    def __init__(self, attr, write, root, held, line, method):
        self.attr = attr
        self.write = write
        self.root = root  # root label
        self.held = held  # frozenset of lock names ("*" = all)
        self.line = line
        self.method = method


class _Root:
    """One concurrency root: an entry method (or lambda/local def body)."""

    def __init__(self, label: str, entry_name: Optional[str], bodies: List[ast.AST]):
        self.label = label
        self.entry_name = entry_name  # method name for named roots
        self.bodies = bodies  # method defs / lambda nodes to start from


class _ClassAudit:
    def __init__(self, module: SourceModule, cls: ast.ClassDef, rule: str):
        self.module = module
        self.cls = cls
        self.rule = rule
        self.findings: List[Finding] = []
        self.methods: Dict[str, ast.AST] = {}
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[node.name] = node
        # class-level aliases: `_bump_locked = _bump`
        for node in cls.body:
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Name)
                and node.value.id in self.methods
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.methods[t.id] = self.methods[node.value.id]
        self.exempt_attrs = self._collect_exempt()
        self.guarded_attrs = self._collect_marked(GUARDED_BY_RE)
        self.confined_attrs = self._collect_marked(CONFINED_RE)
        self.accesses: List[_Access] = []
        # methods visited by real (non-api) roots
        self._real_owned: Set[ast.AST] = set()
        # entry-method name -> names of methods that root owns
        self._owned_by: Dict[str, Set[str]] = {}
        self._visited: Set[Tuple[str, int, frozenset]] = set()

    # ---------------------------------------------------------- declarations

    def _line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.module.lines):
            return self.module.lines[lineno - 1]
        return ""

    def _collect_exempt(self) -> Set[str]:
        out: Set[str] = set()
        for method in self.methods.values():
            for node in ast.walk(method):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                if _last_segment(dotted_name(node.value.func)) not in _SYNC_CTORS:
                    continue
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        out.add(attr)
        return out

    def _collect_marked(self, regex) -> Dict[str, Tuple[str, int]]:
        """attr -> (marker payload, line) for assignments carrying `regex`."""
        out: Dict[str, Tuple[str, int]] = {}
        for method in self.methods.values():
            for node in ast.walk(method):
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        m = regex.search(self._line(t.lineno))
                        if m:
                            out.setdefault(attr, (m.group(1), t.lineno))
        return out

    # ----------------------------------------------------------------- roots

    def discover_roots(self) -> List[_Root]:
        roots: Dict[str, _Root] = {}

        def add_method_root(kind: str, name: str) -> None:
            # keyed by entry method: one method == one root even when it is
            # registered several ways (Thread target + generic kwarg scan)
            if name not in roots:
                roots[name] = _Root(f"{kind}:{name}", name, [self.methods[name]])

        def add_anon_root(kind: str, where: str, body: ast.AST) -> None:
            label = f"{kind}:<fn in {where}>"
            root = roots.setdefault(label, _Root(label, None, []))
            if body not in root.bodies:
                root.bodies.append(body)

        for mname, method in self.methods.items():
            local_defs = {
                n.name: n
                for n in ast.walk(method)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not method
            }
            for node in ast.walk(method):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func)
                if callee in _NON_DEFERRED_CALLEES:
                    continue
                seg = _last_segment(callee) if callee else (
                    node.func.attr if isinstance(node.func, ast.Attribute) else ""
                )
                deferred_args: List[Tuple[Optional[str], ast.AST]] = []
                if seg in ("Thread", "Timer"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            deferred_args.append(("thread", kw.value))
                    if seg == "Timer" and len(node.args) >= 2:
                        deferred_args.append(("thread", node.args[1]))
                elif seg == "submit" and node.args:
                    deferred_args.append(("executor", node.args[0]))
                elif seg == "add_done_callback" and node.args:
                    deferred_args.append(("callback", node.args[0]))
                elif seg == "signal" and len(node.args) >= 2:
                    deferred_args.append(("signal", node.args[1]))
                # generic callback registration: bound methods / lambdas
                # handed to an on_*/…callback kwarg, or positionally to a
                # constructor (which stores them and calls from its own
                # threads — DynamicBatcher(self._run_batch, ...)).  A plain
                # function taking a callable (device_prefetch, retry.call,
                # elastic.guard) runs it on the caller's own thread.
                for kw in node.keywords:
                    if kw.arg and (kw.arg.startswith("on_") or "callback" in kw.arg):
                        deferred_args.append((None, kw.value))
                if seg[:1].isupper():
                    for a in node.args:
                        deferred_args.append((None, a))
                for kind, arg in deferred_args:
                    attr = _self_attr(arg)
                    if attr and attr in self.methods:
                        add_method_root(kind or "callback", attr)
                    elif kind is not None and isinstance(arg, ast.Lambda):
                        add_anon_root(kind, mname, arg.body)
                    elif kind == "thread" and isinstance(arg, ast.Name):
                        target = local_defs.get(arg.id)
                        if target is not None:
                            add_anon_root("thread", mname, target)
                    elif (
                        kind is None
                        and isinstance(arg, ast.Lambda)
                        and self._lambda_is_callback(node, arg)
                    ):
                        add_anon_root("callback", mname, arg.body)
        return list(roots.values())

    def _lambda_is_callback(self, call: ast.Call, lam: ast.Lambda) -> bool:
        """A lambda kwarg named on_*/callback is a registered callback; a
        lambda in any other position (sort keys, tree_map fns) runs inline
        under the enclosing method's root."""
        for kw in call.keywords:
            if kw.value is lam and kw.arg and (
                kw.arg.startswith("on_") or "callback" in kw.arg
            ):
                return True
        return False

    def api_entries(self) -> List[ast.AST]:
        return [
            m
            for name, m in self.methods.items()
            if not name.startswith("_") or name in _PUBLIC_DUNDERS
        ]

    # ------------------------------------------------------------- traversal

    def _entry_seeds(self, method: ast.AST) -> frozenset:
        held: Set[str] = set()
        m = GUARDED_BY_RE.search(self._line(method.lineno))
        if m:
            held.add(m.group(1).split(".", 1)[-1])
        if getattr(method, "name", "").endswith("_locked"):
            held.add("*")
        return frozenset(held)

    def traverse_root(self, root: _Root, api_mode: bool = False) -> None:
        for body in root.bodies:
            if isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._visit_method(root, body, self._entry_seeds(body), api_mode)
            else:  # lambda body expression
                self._visit_nodes(root, [body], frozenset(), api_mode, "<lambda>")

    def _visit_method(
        self, root: _Root, method: ast.AST, held: frozenset, api_mode: bool
    ) -> None:
        key = (root.label, id(method), held)
        if key in self._visited:
            return
        self._visited.add(key)
        if not api_mode:
            self._real_owned.add(method)
            if root.entry_name:
                self._owned_by.setdefault(root.entry_name, set()).add(method.name)
        self._visit_nodes(root, method.body, held, api_mode, method.name)

    def _visit_nodes(
        self,
        root: _Root,
        nodes: Sequence[ast.AST],
        held: frozenset,
        api_mode: bool,
        where: str,
    ) -> None:
        for node in nodes:
            self._visit_node(root, node, held, api_mode, where)

    def _visit_node(
        self, root: _Root, node: ast.AST, held: frozenset, api_mode: bool, where: str
    ) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set()
            for item in node.items:
                self._visit_node(root, item.context_expr, held, api_mode, where)
                attr = _self_attr(item.context_expr)
                if attr:
                    acquired.add(attr)
            inner = frozenset(held | acquired)
            self._visit_nodes(root, node.body, inner, api_mode, where)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            # nested defs run at call time; traversed only when referenced
            # (thread targets become anonymous roots, local helpers are
            # traversed inline at their call sites below)
            return
        if isinstance(node, ast.Call):
            # method call on self: follow the edge under the current lockset
            attr = _self_attr(node.func)
            if attr and attr in self.methods:
                callee = self.methods[attr]
                if not (api_mode and callee in self._real_owned):
                    entry = frozenset(held | self._entry_seeds(callee))
                    self._visit_method(root, callee, entry, api_mode)
            # local helper called (or passed) by name runs inline
            # container mutator call == write to the attribute
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
                base = _self_attr(fn.value)
                if base:
                    self._record(base, True, root, held, fn.value.lineno, where)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                self._record_store_target(t, root, held, where)
        elif isinstance(node, ast.AugAssign):
            self._record_store_target(node.target, root, held, where)
        elif isinstance(node, (ast.Attribute,)):
            attr = _self_attr(node)
            if attr is not None:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                self._record(attr, write, root, held, node.lineno, where)
        for child in ast.iter_child_nodes(node):
            self._visit_node(root, child, held, api_mode, where)

    def _record_store_target(self, t: ast.AST, root, held, where) -> None:
        # self.x = v and self.x[i] = v mutate the binding/container x;
        # self.x.y = v mutates the OBJECT x points at — that store is the
        # inner class's concern (recorded as a read of x here)
        base = t
        while isinstance(base, ast.Subscript):
            base = base.value
        attr = _self_attr(base)
        if attr is not None:
            self._record(attr, True, root, held, base.lineno, where)

    def _record(self, attr, write, root, held, line, where) -> None:
        self.accesses.append(_Access(attr, write, root.label, held, line, where))

    # --------------------------------------------------------------- verdict

    def analyze(self) -> List[Finding]:
        roots = self.discover_roots()
        if not roots:
            return []  # no concurrency in this class
        for root in roots:
            self.traverse_root(root, api_mode=False)
        api = _Root(_API, _API, [])
        for entry in self.api_entries():
            self._visit_method(api, entry, self._entry_seeds(entry), api_mode=True)

        by_attr: Dict[str, List[_Access]] = {}
        for a in self.accesses:
            if a.attr in self.exempt_attrs or a.attr in self.methods:
                continue
            by_attr.setdefault(a.attr, []).append(a)

        root_names = {r.entry_name for r in roots if r.entry_name}
        for attr in sorted(by_attr):
            accesses = by_attr[attr]
            if attr in self.guarded_attrs:
                continue  # declared shared; lock-discipline enforces it
            if attr in self.confined_attrs:
                self._check_confined(attr, accesses, root_names)
                continue
            self._check_conflict(attr, accesses)
        # context-sensitive traversal can record one site several times
        seen: Set[Tuple[str, int, str]] = set()
        unique: List[Finding] = []
        for f in self.findings:
            k = (f.path, f.line, f.message)
            if k not in seen:
                seen.add(k)
                unique.append(f)
        return unique

    def _check_conflict(self, attr: str, accesses: List[_Access]) -> None:
        root_labels = sorted({a.root for a in accesses})
        writes = [a for a in accesses if a.write]
        if len(root_labels) < 2 or not writes:
            return
        common: Optional[Set[str]] = None
        for a in accesses:
            if "*" in a.held:
                continue
            common = set(a.held) if common is None else (common & set(a.held))
            if not common:
                break
        if common is None or common:
            return  # every access lock-compatible
        first = min(writes, key=lambda a: a.line)
        self.findings.append(
            Finding(
                rule=self.rule,
                severity=SEVERITY_ERROR,
                path=self.module.rel,
                line=first.line,
                message=(
                    f"self.{attr} in {self.cls.name} is mutated with no "
                    f"common lock across concurrency roots "
                    f"{', '.join(root_labels)} — guard it, or declare "
                    "single-writer ownership with '# confined: <root>'"
                ),
            )
        )

    def _check_confined(
        self, attr: str, accesses: List[_Access], root_names: Set[str]
    ) -> None:
        owner, decl_line = self.confined_attrs[attr]
        if owner != _API and owner not in root_names:
            self.findings.append(
                Finding(
                    rule=self.rule,
                    severity=SEVERITY_ERROR,
                    path=self.module.rel,
                    line=decl_line,
                    message=(
                        f"self.{attr} in {self.cls.name} declares "
                        f"'# confined: {owner}' but no concurrency root "
                        f"named {owner} exists (known: "
                        f"{', '.join(sorted(root_names | {_API}))})"
                    ),
                )
            )
            return
        owner_methods = self._owned_by.get(owner, set())
        for a in accesses:
            if not a.write:
                continue
            root_entry = a.root.split(":", 1)[-1] if a.root != _API else _API
            # a write in a method the owner root owns is the owner's code,
            # whichever root reached it (drain()/tick() run the loop body
            # inline in no-thread mode)
            if root_entry != owner and a.method not in owner_methods:
                self.findings.append(
                    Finding(
                        rule=self.rule,
                        severity=SEVERITY_ERROR,
                        path=self.module.rel,
                        line=a.line,
                        message=(
                            f"self.{attr} in {self.cls.name} is declared "
                            f"'# confined: {owner}' but is written from "
                            f"root {a.root} (in {a.method})"
                        ),
                    )
                )


class ThreadSafetyPass(AnalysisPass):
    rule = "thread-safety"
    description = (
        "attributes mutated from >= 2 inferred concurrency roots (threads, "
        "executors, signal handlers, callbacks, public api) must share a "
        "lock or declare '# confined: <root>' ownership"
    )

    def run(self, modules: Sequence[SourceModule], ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for module in modules:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(_ClassAudit(module, node, self.rule).analyze())
        return findings
