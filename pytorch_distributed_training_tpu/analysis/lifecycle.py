"""resource-lifecycle: acquire/release tracking on every outgoing path.

The serving stack leans on four resource patterns whose leaks the PR 9/12
audits could only catch dynamically (the KV-block leak audit inside the
scheduler tick, the in-flight-future sweep in the chaos harness).  This
pass makes the function-local cases static guarantees:

  ===============================  ==================================
  acquire                          release
  ===============================  ==================================
  ``f = Future()``                 ``f.set_result/set_exception/cancel``
  ``t = Thread(...)`` (non-daemon) ``t.join()``
  ``fh = open(...)``               ``fh.close()``
  ``blocks = pool.alloc/admit(..)``handed to a call (``free``/escape)
  ===============================  ==================================

Two findings per resource kind:

  - **definite leak** — the name never reaches a release call and never
    escapes the function (not returned/yielded, not passed to any call,
    not stored into an attribute/subscript/container, not aliased).  An
    escaping resource transfers ownership; tracking it further would need
    whole-program alias analysis and would drown the report in maybes.
  - **leak on exception edge** — a release exists, but statements between
    the acquire and the release contain calls that may raise, and the
    release is not protected by a ``finally`` (or reached via ``with``).
    This is exactly the shape of the in-flight-future bug class: admit a
    request, run model code that can throw, only then resolve the future.

Deliberate scope cuts, each matching a real idiom in the tree:

  - ``with open(...)`` is already safe and not tracked.
  - ``self._file = open(...)`` (telemetry sinks/spans) stores ownership
    in the object; object-lifetime pairing is the thread-safety /
    close-method contract, not a function-local property.
  - ``daemon=True`` threads are exempt from the join requirement:
    ``elastic.guard`` deliberately abandons its worker on timeout, and a
    daemon thread cannot block interpreter exit.  A *non-daemon* thread
    acquired and never joined is always a bug.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    SEVERITY_ERROR,
    AnalysisContext,
    AnalysisPass,
    Finding,
    SourceModule,
    dotted_name,
    func_qualname,
)

__all__ = ["ResourceLifecyclePass"]

# kind -> release method names on the acquired object
_RELEASES: Dict[str, Tuple[str, ...]] = {
    "future": ("set_result", "set_exception", "cancel"),
    "thread": ("join",),
    "file": ("close",),
    "blocks": ("free", "release"),  # via escape: passing to pool.free() absolves
}

_ACQ_CTORS = {"Future": "future", "Thread": "thread"}
_ACQ_METHODS = {"alloc": "blocks", "admit": "blocks"}


def _last_segment(dotted: Optional[str]) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _classify_acquire(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func)
    seg = _last_segment(name)
    if name == "open":
        return "file"
    if seg in _ACQ_CTORS:
        if seg == "Thread":
            for kw in call.keywords:
                if (
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                ):
                    return None  # daemon threads may be abandoned
        return _ACQ_CTORS[seg]
    if isinstance(call.func, ast.Attribute) and call.func.attr in _ACQ_METHODS:
        return _ACQ_METHODS[call.func.attr]
    return None


class _Resource:
    __slots__ = ("name", "kind", "line", "stmt")

    def __init__(self, name: str, kind: str, line: int, stmt: ast.stmt):
        self.name = name
        self.kind = kind
        self.line = line
        self.stmt = stmt


class _FunctionAudit:
    def __init__(self, module: SourceModule, fn: ast.AST, rule: str):
        self.module = module
        self.fn = fn
        self.rule = rule

    def _nodes(self):
        """All nodes of this function, excluding nested function bodies
        (a resource captured by a nested def has its lifetime extended in
        ways function-local analysis cannot pair)."""
        stack: List[ast.AST] = list(self.fn.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def acquires(self) -> List[_Resource]:
        out: List[_Resource] = []
        for node in self._nodes():
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not isinstance(t, ast.Name) or not isinstance(node.value, ast.Call):
                continue
            kind = _classify_acquire(node.value)
            if kind:
                out.append(_Resource(t.id, kind, node.lineno, node))
        return out

    # ------------------------------------------------------------ evidence

    def _uses(self, res: _Resource):
        """(releases, escapes, other_calls) — categorized uses after acquire."""
        releases: List[ast.Call] = []
        escapes: List[ast.AST] = []
        calls: List[ast.Call] = []
        name = res.name
        for node in self._nodes():
            if isinstance(node, ast.Call):
                calls.append(node)
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == name
                    and fn.attr in _RELEASES[res.kind]
                ):
                    releases.append(node)
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == name:
                        escapes.append(node)
                    elif isinstance(arg, ast.Starred) and (
                        isinstance(arg.value, ast.Name) and arg.value.id == name
                    ):
                        escapes.append(node)
            elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        escapes.append(node)
                        break
            elif isinstance(node, ast.Assign):
                if node is res.stmt:
                    continue
                # stored into attribute/subscript/container, or re-aliased
                value_names = {
                    n.id for n in ast.walk(node.value) if isinstance(n, ast.Name)
                }
                if name in value_names:
                    escapes.append(node)
        return releases, escapes, calls

    def _protected(self, releases: Sequence[ast.Call]) -> bool:
        """True if some release sits in a finally or except handler."""
        release_ids = {id(r) for r in releases}
        for node in self._nodes():
            if isinstance(node, ast.Try):
                regions = list(node.finalbody)
                for h in node.handlers:
                    regions.extend(h.body)
                for stmt in regions:
                    for sub in ast.walk(stmt):
                        if id(sub) in release_ids:
                            return True
        return False

    def findings(self) -> List[Finding]:
        out: List[Finding] = []
        qual = func_qualname(self.module, self.fn)
        for res in self.acquires():
            releases, escapes, calls = self._uses(res)
            if escapes:
                continue  # ownership transferred
            if not releases:
                out.append(
                    Finding(
                        rule=self.rule,
                        severity=SEVERITY_ERROR,
                        path=self.module.rel,
                        line=res.line,
                        message=(
                            f"{res.name} ({res.kind}) acquired in {qual} "
                            f"never reaches "
                            f"{'/'.join(_RELEASES[res.kind])} and does not "
                            "escape the function"
                        ),
                    )
                )
                continue
            if self._protected(releases):
                continue
            first_release = min(r.lineno for r in releases)
            risky = [
                c
                for c in calls
                if res.line < c.lineno < first_release
                and c not in releases
            ]
            if risky:
                out.append(
                    Finding(
                        rule=self.rule,
                        severity=SEVERITY_ERROR,
                        path=self.module.rel,
                        line=res.line,
                        message=(
                            f"{res.name} ({res.kind}) acquired in {qual} can "
                            f"leak on an exception edge: calls between the "
                            f"acquire and "
                            f"{'/'.join(_RELEASES[res.kind])} may raise "
                            "first — release in a finally block"
                        ),
                    )
                )
        return out


class ResourceLifecyclePass(AnalysisPass):
    rule = "resource-lifecycle"
    description = (
        "futures, threads, file handles and pool allocations must reach "
        "their release (set_result/join/close/free) or escape on every "
        "outgoing path, including exception edges"
    )

    def run(self, modules: Sequence[SourceModule], ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for module in modules:
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    findings.extend(_FunctionAudit(module, node, self.rule).findings())
        return findings
