"""CLI: ``python -m pytorch_distributed_training_tpu.analysis``.

Exit code 0 when no unsuppressed (and non-baselined) findings remain,
1 otherwise — the tier-1 gate and ``bench.py lint`` both key off it.

Examples::

    python -m pytorch_distributed_training_tpu.analysis
    python -m pytorch_distributed_training_tpu.analysis --format json
    python -m pytorch_distributed_training_tpu.analysis \
        --rules trace-purity,donation-safety --verbose
    python -m pytorch_distributed_training_tpu.analysis \
        --write-baseline .pdt-baseline.json
    python -m pytorch_distributed_training_tpu.analysis --collectives
    python -m pytorch_distributed_training_tpu.analysis --schema
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import (
    ALL_PASSES,
    extract_collective_sequences,
    extract_schema,
    render_json,
    render_text,
    run,
    schema_as_json,
    write_baseline,
)
from .core import collect_modules


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="pdt-analyze",
        description="static analysis: trace purity, lock discipline, "
        "collective order, donation safety, repo conventions, inferred-"
        "lockset thread safety, resource lifecycles, config schema",
    )
    parser.add_argument(
        "--root",
        type=Path,
        default=None,
        help="package root to analyze (default: the installed "
        "pytorch_distributed_training_tpu tree)",
    )
    parser.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule subset: "
        + ",".join(cls.rule for cls in ALL_PASSES),
    )
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument(
        "--baseline", type=Path, default=None, help="baseline JSON to subtract"
    )
    parser.add_argument(
        "--write-baseline",
        type=Path,
        default=None,
        metavar="PATH",
        help="write current unsuppressed findings as a baseline and exit 0",
    )
    parser.add_argument(
        "--verbose", action="store_true", help="also list suppressed/baselined"
    )
    parser.add_argument(
        "--collectives",
        action="store_true",
        help="print the per-family collective-order extraction and exit",
    )
    parser.add_argument(
        "--schema",
        action="store_true",
        help="print the generated config schema (accepted keys, types, "
        "defaults per section) as JSON and exit",
    )
    args = parser.parse_args(argv)

    if args.schema:
        root = args.root or Path(__file__).resolve().parent.parent
        modules = collect_modules(Path(root), Path(root).parent)
        print(json.dumps(schema_as_json(extract_schema(modules)), indent=2))
        return 0

    if args.collectives:
        root = args.root or Path(__file__).resolve().parent.parent
        seqs = extract_collective_sequences(root)
        for family in sorted(seqs):
            print(f"family {family}:")
            for builder, calls in seqs[family].items():
                print(f"  {builder}:")
                for c in calls:
                    print(f"    {c.op}({c.axis})  [{c.function}:{c.line}]")
        return 0

    rules = args.rules.split(",") if args.rules else None
    result = run(package_root=args.root, rules=rules, baseline=args.baseline)

    if args.write_baseline is not None:
        write_baseline(args.write_baseline, result.unsuppressed)
        print(
            f"wrote baseline with {len(result.unsuppressed)} finding(s) "
            f"to {args.write_baseline}"
        )
        return 0

    if args.format == "json":
        print(render_json(result))
    else:
        print(render_text(result, verbose=args.verbose))
    return 0 if not result.unsuppressed else 1


if __name__ == "__main__":
    sys.exit(main())
