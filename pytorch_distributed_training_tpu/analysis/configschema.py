"""config-schema: generated key/type schema + static YAML validation.

The config surface is parsed in one place per subsystem but DOCUMENTED
nowhere: ``topology.parse_*`` owns the ``training.*`` sections,
``*.from_config`` / ``resolve_config`` own checkpointing and serving, and
each uses one of two closed-set idioms to reject typos
(``unknown = set(sec) - {...}; if unknown: raise`` in topology,
dict-copy + ``pop`` + ``if sec: raise`` in serving).  This pass extracts
the accepted key/type/default surface from those sites into a generated
schema and then statically validates every shipped ``config/*.yml``
against it — so a misspelled ``bucket_mb`` fails lint instead of failing
a 30-minute run at parse time (or worse, being silently ignored in an
open section).

Extraction walks every function named ``parse_*`` / ``from_config`` /
``resolve_config`` (plus constructor bodies that copy the well-known
``scheduler`` / ``resilience`` kwargs), tracking dict aliases from the
root config down (``serve = cfg["serving"]``,
``fleet_cfg = dict(serve.get("fleet") or {})``) and recording every
``.get`` / ``.pop`` / ``[...]`` / ``in`` / ``.setdefault`` access:

  - key **types** come from literal defaults and enclosing casts
    (``int(sec.get("slots", 8))``).  A bare ``False`` default
    contributes no type — several keys (``training.zero``) accept bool
    OR int by contract; only an explicit ``bool(...)`` cast pins bool.
  - a section is **closed** when either rejection idiom is present;
    only closed sections produce unknown-key findings (open sections
    like ``model`` forward ``**kwargs`` by design).
  - a closed section's declared allow-set minus its actually-read keys
    is a **dead key** finding at the parser (accepted, never read).

YAML validation uses ``yaml.compose`` (node marks give real line
numbers; scalar tags give types without constructing) and degrades to
a no-op when PyYAML is absent — the analyzer must import anywhere the
package does.  Type checks are tag-based: bool is strict (YAML
``true`` is not an int), int satisfies float, ``null`` satisfies
anything (every key here is optional-with-default at parse level; the
hard required set lives in config_parsing and is enforced at load).
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    SEVERITY_ERROR,
    AnalysisContext,
    AnalysisPass,
    Finding,
    SourceModule,
    dotted_name,
)

__all__ = ["ConfigSchemaPass", "extract_schema", "schema_as_json"]

_PARSER_NAMES = ("from_config", "resolve_config")
_ROOT_PARAMS = {"cfg": (), "train_cfg": ("training",)}
# constructor kwargs that carry whole config sections past the parser
# boundary; recognized only via the dict-copy binding idiom
_SEED_PARAMS = {
    "scheduler": ("serving", "scheduler"),
    "resilience": ("serving", "resilience"),
    "quant": ("serving", "quant"),
    "lora": ("serving", "lora"),
    "speculative": ("serving", "speculative"),
    "autoscale": ("serving", "autoscale"),
    "workload": ("serving", "autoscale", "workload"),
    "disagg": ("serving", "disagg"),
}
_ACCESS_METHODS = {"get", "pop", "setdefault"}
_CASTS = {"int", "float", "bool", "str"}

_YAML_TAG_TYPES = {
    "tag:yaml.org,2002:int": "int",
    "tag:yaml.org,2002:float": "float",
    "tag:yaml.org,2002:bool": "bool",
    "tag:yaml.org,2002:str": "str",
    "tag:yaml.org,2002:null": "null",
}
# schema type -> acceptable YAML scalar types (bool-first: strict)
_COMPAT = {
    "int": {"int"},
    "float": {"int", "float"},
    "bool": {"bool"},
    "str": {"str"},
}


class _KeyInfo:
    __slots__ = ("types", "default", "required")

    def __init__(self):
        self.types: Set[str] = set()
        self.default: Optional[str] = None
        self.required = False

    @property
    def type(self) -> str:
        return next(iter(self.types)) if len(self.types) == 1 else "any"


class _Section:
    __slots__ = ("keys", "closed", "allowed", "source")

    def __init__(self):
        self.keys: Dict[str, _KeyInfo] = {}
        self.closed = False
        self.allowed: Optional[Set[str]] = None  # literal allow-set if any
        self.source: Optional[Tuple[str, int]] = None  # (rel, line)

    def effective_allowed(self) -> Set[str]:
        return set(self.allowed) if self.allowed is not None else set(self.keys)


Schema = Dict[Tuple[str, ...], _Section]


def _is_parser(fn: ast.AST) -> bool:
    name = getattr(fn, "name", "")
    return name.startswith("parse_") or name in _PARSER_NAMES


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _default_type(node: Optional[ast.AST]) -> Optional[str]:
    """Type evidence from a literal default (None = no evidence)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant):
        v = node.value
        if isinstance(v, bool) or v is None:
            return None  # bool-or-int keys exist; None pins nothing
        if isinstance(v, int):
            return "int"
        if isinstance(v, float):
            return "float"
        if isinstance(v, str):
            return "str"
        return None
    if isinstance(node, (ast.List, ast.Tuple)):
        return "list"
    if isinstance(node, ast.Dict):
        return "dict"
    return None


def _sectionish_default(node: Optional[ast.AST]) -> bool:
    """Could this .get default still yield a section? (absent/None/{})"""
    if node is None:
        return True
    if isinstance(node, ast.Constant) and node.value is None:
        return True
    if isinstance(node, ast.Dict) and not node.keys:
        return True
    return False


class _FunctionExtractor:
    """Extract section accesses from one parser function into `schema`."""

    def __init__(self, module: SourceModule, fn: ast.AST, schema: Schema):
        self.module = module
        self.fn = fn
        self.schema = schema
        self.env: Dict[str, Tuple[str, ...]] = {}
        self.copied: Set[str] = set()  # env names bound via dict(...) copy
        self.casts: Dict[int, str] = {}  # id(node) -> cast type

    def section(self, path: Tuple[str, ...]) -> _Section:
        sec = self.schema.setdefault(path, _Section())
        if sec.source is None:
            sec.source = (self.module.rel, self.fn.lineno)
        return sec

    # ------------------------------------------------------------- aliases

    def _resolve(self, node: ast.AST) -> Optional[Tuple[str, ...]]:
        """Resolve an expression to a config-section path, if it is one."""
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Subscript):
            key = _str_const(node.slice)
            base = self._resolve(node.value)
            if key is not None and base is not None:
                return base + (key,)
            return None
        if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or) and node.values:
            return self._resolve(node.values[0])
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee == "dict" and len(node.args) == 1:
                return self._resolve(node.args[0])
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "pop")
                and node.args
            ):
                key = _str_const(node.args[0])
                default = node.args[1] if len(node.args) > 1 else None
                if key is not None and _sectionish_default(default):
                    base = self._resolve(node.func.value)
                    if base is not None:
                        return base + (key,)
        return None

    def _bind_aliases(self) -> None:
        params = {
            a.arg
            for a in list(self.fn.args.args)
            + list(self.fn.args.kwonlyargs)
            + list(self.fn.args.posonlyargs)
        }
        for name, path in _ROOT_PARAMS.items():
            if name in params:
                self.env[name] = path
        assigns = sorted(
            (n for n in ast.walk(self.fn) if isinstance(n, ast.Assign)),
            key=lambda n: n.lineno,
        )
        for node in assigns:
            if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
                continue
            target = node.targets[0].id
            value = node.value
            # seed kwargs enter the env only via the dict-copy idiom
            seed = None
            if isinstance(value, ast.Call) and dotted_name(value.func) == "dict":
                if len(value.args) == 1:
                    inner = value.args[0]
                    if isinstance(inner, ast.BoolOp):
                        inner = inner.values[0]
                    if isinstance(inner, ast.Name) and inner.id in _SEED_PARAMS:
                        if inner.id in params:
                            seed = _SEED_PARAMS[inner.id]
            if seed is not None:
                self.env[target] = seed
                self.copied.add(target)
                continue
            path = self._resolve(value)
            if path is not None:
                self.env[target] = path
                if isinstance(value, ast.Call) and dotted_name(value.func) == "dict":
                    self.copied.add(target)

    # ------------------------------------------------------------ accesses

    def _collect_casts(self) -> None:
        for node in ast.walk(self.fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _CASTS
                and node.args
            ):
                for sub in ast.walk(node.args[0]):
                    self.casts[id(sub)] = node.func.id

    def _record(
        self,
        path: Tuple[str, ...],
        key: str,
        node: ast.AST,
        default: Optional[ast.AST],
        required: bool,
        is_section: bool,
    ) -> None:
        info = self.section(path).keys.setdefault(key, _KeyInfo())
        info.required = info.required or required
        if is_section:
            info.types.add("dict")
            return
        cast = self.casts.get(id(node))
        t = cast if cast else _default_type(default)
        if t:
            info.types.add(t)
        if default is not None and info.default is None:
            try:
                info.default = ast.unparse(default)
            except Exception:
                pass

    def _walk_accesses(self) -> None:
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr in _ACCESS_METHODS and node.args:
                    key = _str_const(node.args[0])
                    base = self._resolve(node.func.value)
                    if key is not None and base is not None:
                        default = node.args[1] if len(node.args) > 1 else None
                        is_section = self._resolve(node) is not None and (
                            node.func.attr != "setdefault"
                        )
                        self._record(base, key, node, default, False, is_section)
            elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
                key = _str_const(node.slice)
                base = self._resolve(node.value)
                if key is not None and base is not None:
                    is_section = self._resolve(node) is not None
                    self._record(base, key, node, None, True, is_section)
            elif isinstance(node, ast.Compare) and len(node.ops) == 1:
                if isinstance(node.ops[0], (ast.In, ast.NotIn)):
                    key = _str_const(node.left)
                    base = self._resolve(node.comparators[0])
                    if key is not None and base is not None:
                        self._record(base, key, node, None, False, False)

    # -------------------------------------------------------- closed sets

    def _detect_closed(self) -> None:
        # idiom 1: unknown = set(sec) - {"a", "b", ...}; if unknown: raise
        for node in ast.walk(self.fn):
            if (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)
                and isinstance(node.left, ast.Call)
                and dotted_name(node.left.func) == "set"
                and len(node.left.args) == 1
                and isinstance(node.right, ast.Set)
            ):
                path = self._resolve(node.left.args[0])
                allowed = {
                    s for s in (_str_const(e) for e in node.right.elts) if s
                }
                if path is not None and allowed:
                    sec = self.section(path)
                    sec.closed = True
                    sec.allowed = (sec.allowed or set()) | allowed
                    sec.source = (self.module.rel, node.lineno)
        # idiom 2: sec = dict(...); sec.pop(...)*; if sec: raise
        for node in ast.walk(self.fn):
            if (
                isinstance(node, ast.If)
                and isinstance(node.test, ast.Name)
                and node.test.id in self.copied
                and any(isinstance(s, ast.Raise) for s in node.body)
            ):
                path = self.env.get(node.test.id)
                if path is not None:
                    self.section(path).closed = True

    def extract(self) -> None:
        self._bind_aliases()
        if not self.env:
            return
        self._collect_casts()
        self._walk_accesses()
        self._detect_closed()


def _has_seed_binding(fn: ast.AST) -> bool:
    params = {a.arg for a in list(fn.args.args) + list(fn.args.kwonlyargs)}
    if not (params & set(_SEED_PARAMS)):
        return False
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and dotted_name(node.value.func) == "dict"
        ):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id in _SEED_PARAMS:
                    return True
    return False


def extract_schema(modules: Sequence[SourceModule]) -> Schema:
    """Build the accepted-config schema from every parser in `modules`."""
    schema: Schema = {}
    for module in modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if _is_parser(node) or _has_seed_binding(node):
                _FunctionExtractor(module, node, schema).extract()
    return schema


def schema_as_json(schema: Schema) -> Dict[str, Any]:
    """JSON-friendly dump (the documented config reference)."""
    out: Dict[str, Any] = {}
    for path in sorted(schema):
        sec = schema[path]
        out[".".join(path) or "<root>"] = {
            "closed": sec.closed,
            "keys": {
                k: {
                    "type": info.type,
                    "default": info.default,
                    "required": info.required,
                }
                for k, info in sorted(sec.keys.items())
            },
        }
    return out


# --------------------------------------------------------------------- YAML


def _compose_yaml(text: str):
    try:
        import yaml
    except ImportError:  # analyzer must run anywhere the package imports
        return None
    return yaml.compose(text)


def _scalar_type(node) -> Optional[str]:
    tag = getattr(node, "tag", "")
    return _YAML_TAG_TYPES.get(tag)


class ConfigSchemaPass(AnalysisPass):
    rule = "config-schema"
    description = (
        "config/*.yml must match the schema generated from topology.parse_* "
        "and *.from_config: no unknown keys in closed sections, no type "
        "mismatches, no accepted-but-never-read keys"
    )

    def run(self, modules: Sequence[SourceModule], ctx: AnalysisContext) -> List[Finding]:
        schema = extract_schema(modules)
        findings: List[Finding] = []
        findings.extend(self._dead_keys(schema))
        config_dir = ctx.resolved_config_dir()
        if config_dir.is_dir():
            for path in sorted(config_dir.glob("*.yml")):
                findings.extend(self._validate_yaml(path, schema, ctx))
        return findings

    def _dead_keys(self, schema: Schema) -> List[Finding]:
        findings: List[Finding] = []
        for path in sorted(schema):
            sec = schema[path]
            if sec.allowed is None:
                continue
            for key in sorted(sec.allowed - set(sec.keys)):
                rel, line = sec.source or ("<unknown>", 1)
                findings.append(
                    Finding(
                        rule=self.rule,
                        severity=SEVERITY_ERROR,
                        path=rel,
                        line=line,
                        message=(
                            f"config key {'.'.join(path)}.{key} is accepted "
                            "by the closed-set check but never read — dead "
                            "key (drop it from the allow-set or wire it)"
                        ),
                    )
                )
        return findings

    def _validate_yaml(
        self, path: Path, schema: Schema, ctx: AnalysisContext
    ) -> List[Finding]:
        findings: List[Finding] = []
        try:
            root = _compose_yaml(path.read_text())
        except Exception as exc:
            root = None
            findings.append(
                Finding(
                    rule=self.rule,
                    severity=SEVERITY_ERROR,
                    path=self._rel(path, ctx),
                    line=1,
                    message=f"unparseable YAML: {exc}".splitlines()[0],
                )
            )
        if root is None:
            return findings
        rel = self._rel(path, ctx)
        self._walk(root, (), schema, rel, findings)
        return findings

    def _rel(self, path: Path, ctx: AnalysisContext) -> str:
        try:
            return path.relative_to(ctx.repo_root).as_posix()
        except ValueError:
            return path.name

    def _walk(self, node, path, schema, rel, findings) -> None:
        if not hasattr(node, "value") or not isinstance(node.value, list):
            return
        pairs = [
            p for p in node.value if isinstance(p, tuple) and len(p) == 2
        ]
        if not pairs:
            return
        sec = schema.get(path)
        allowed = sec.effective_allowed() if (sec and sec.closed) else None
        for key_node, val_node in pairs:
            key = getattr(key_node, "value", None)
            if not isinstance(key, str):
                continue
            line = key_node.start_mark.line + 1
            if allowed is not None and key not in allowed:
                findings.append(
                    Finding(
                        rule=self.rule,
                        severity=SEVERITY_ERROR,
                        path=rel,
                        line=line,
                        message=(
                            f"unknown key {'.'.join(path + (key,))} — the "
                            f"{'.'.join(path)} section is closed (accepted: "
                            f"{', '.join(sorted(allowed))})"
                        ),
                    )
                )
            if sec is not None and key in sec.keys:
                expected = sec.keys[key].type
                got = _scalar_type(val_node)
                if (
                    expected in _COMPAT
                    and got is not None
                    and got != "null"
                    and got not in _COMPAT[expected]
                ):
                    findings.append(
                        Finding(
                            rule=self.rule,
                            severity=SEVERITY_ERROR,
                            path=rel,
                            line=line,
                            message=(
                                f"type mismatch for "
                                f"{'.'.join(path + (key,))}: schema says "
                                f"{expected}, YAML value is {got}"
                            ),
                        )
                    )
            self._walk(val_node, path + (key,), schema, rel, findings)
