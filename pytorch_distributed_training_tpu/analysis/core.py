"""pdt-analyze core: finding model, suppressions, baselines, pass protocol.

The analyzer is a collection of AST passes over the package tree (plus the
``tests/`` tree for the marker-convention pass).  Everything here is
stdlib-only and import-light by design: the CLI must run in CI containers
and pre-commit hooks without touching JAX, and the passes must never
*execute* the code they inspect — a purity analyzer that imports the
module under analysis would trigger the very side effects it polices.

Vocabulary:

  - A :class:`Finding` is one rule violation at ``file:line`` with a
    severity and a human message.
  - A suppression is an inline comment ``# pdt: ignore[rule]`` (or
    ``# pdt: ignore[rule1, rule2]``, or ``# pdt: ignore[*]``) on the
    flagged line — or alone on the line directly above it, for lines too
    long to carry a trailing comment.  Suppressions are expected to carry
    a one-line justification after a ``--``:
    ``# pdt: ignore[lock-discipline] -- single-writer counter, racy reads ok``
  - A baseline file (JSON) records the *identity keys* of known findings
    so a rule can be introduced without fixing the whole backlog at once;
    keys are line-number independent (rule + path + message) so pure code
    motion does not resurrect baselined findings.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import re
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "SourceModule",
    "AnalysisPass",
    "AnalysisContext",
    "AnalysisResult",
    "collect_modules",
    "run_passes",
    "load_baseline",
    "write_baseline",
]

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

# `# pdt: ignore[rule-a, rule-b]` with an optional `-- justification` tail
_SUPPRESS_RE = re.compile(r"#\s*pdt:\s*ignore\[([^\]]+)\]")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, pinned to a repo-relative ``path:line``."""

    rule: str
    severity: str
    path: str  # posix, relative to the analysis root's parent (repo root)
    line: int
    message: str

    @property
    def key(self) -> str:
        """Line-independent identity used by baseline files."""
        return f"{self.rule}:{self.path}:{self.message}"

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.severity}[{self.rule}] {self.message}"


class SourceModule:
    """A parsed source file: path, text, AST, and its suppression map."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel  # posix path relative to repo root
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self.suppressions = self._collect_suppressions()

    def _collect_suppressions(self) -> Dict[int, set]:
        """Map line number -> set of suppressed rule names ('*' = all).

        A comment on its own line suppresses the next line; a trailing
        comment suppresses its own line.  Both map through here so a
        finding only needs to check its own line number.
        """
        out: Dict[int, set] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            stripped = text.strip()
            target = i + 1 if stripped.startswith("#") else i
            out.setdefault(target, set()).update(rules)
        return out

    def is_suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line)
        if not rules:
            return False
        return "*" in rules or finding.rule in rules


@dataclasses.dataclass
class AnalysisContext:
    """Shared inputs handed to every pass."""

    package_root: Path  # the pytorch_distributed_training_tpu/ dir
    repo_root: Path  # its parent (where tests/ and bench.py live)
    tests_dir: Optional[Path] = None  # overridable for fixture tests
    config_dir: Optional[Path] = None  # overridable for fixture tests

    def resolved_tests_dir(self) -> Path:
        return self.tests_dir if self.tests_dir is not None else self.repo_root / "tests"

    def resolved_config_dir(self) -> Path:
        return self.config_dir if self.config_dir is not None else self.repo_root / "config"


class AnalysisPass:
    """Base class: subclasses set ``rule``/``description`` and run()."""

    rule: str = ""
    description: str = ""

    def run(self, modules: Sequence[SourceModule], ctx: AnalysisContext) -> List[Finding]:
        raise NotImplementedError


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]  # every finding, suppressed or not
    unsuppressed: List[Finding]  # what the gate sees
    suppressed: List[Finding]
    baselined: List[Finding]
    wall_s: float
    files_scanned: int

    def rule_totals(self, which: str = "unsuppressed") -> Dict[str, int]:
        pool = getattr(self, which)
        out: Dict[str, int] = {}
        for f in pool:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def collect_modules(package_root: Path, repo_root: Path) -> List[SourceModule]:
    """Parse every .py file under the package tree (skipping caches)."""
    modules = []
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(repo_root).as_posix()
        modules.append(SourceModule(path, rel, path.read_text()))
    return modules


def run_passes(
    passes: Sequence[AnalysisPass],
    ctx: AnalysisContext,
    baseline_keys: Optional[set] = None,
) -> AnalysisResult:
    """Run passes over the package tree and fold in suppressions/baseline."""
    t0 = time.perf_counter()
    modules = collect_modules(ctx.package_root, ctx.repo_root)
    by_rel = {m.rel: m for m in modules}
    findings: List[Finding] = []
    for p in passes:
        findings.extend(p.run(modules, ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    suppressed, baselined, live = [], [], []
    baseline_keys = baseline_keys or set()
    for f in findings:
        mod = by_rel.get(f.path)
        if mod is not None and mod.is_suppressed(f):
            suppressed.append(f)
        elif f.key in baseline_keys:
            baselined.append(f)
        else:
            live.append(f)
    return AnalysisResult(
        findings=findings,
        unsuppressed=live,
        suppressed=suppressed,
        baselined=baselined,
        wall_s=time.perf_counter() - t0,
        files_scanned=len(modules),
    )


def load_baseline(path: Path) -> set:
    data = json.loads(Path(path).read_text())
    if data.get("version") != 1:
        raise ValueError(f"unsupported baseline version in {path}: {data.get('version')!r}")
    return set(data.get("findings", []))


def write_baseline(path: Path, findings: Iterable[Finding]) -> None:
    keys = sorted({f.key for f in findings})
    Path(path).write_text(
        json.dumps({"version": 1, "findings": keys}, indent=2) + "\n"
    )


# --------------------------------------------------------------------------- #
# Shared AST helpers used by several passes.


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_child_statements(func: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's own nodes WITHOUT descending into nested defs.

    Lambdas are treated as part of the enclosing function (they execute
    inline under the same tracing/locking context as often as not, and
    they cannot contain statements of their own).
    """
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def func_qualname(module: SourceModule, target: ast.AST) -> str:
    """Best-effort dotted qualname of a def/class node within its module."""
    path: List[str] = []

    def visit(node: ast.AST, trail: Tuple[str, ...]) -> bool:
        for child in ast.iter_child_nodes(node):
            if child is target:
                name = getattr(child, "name", "<anon>")
                path.extend(trail + (name,))
                return True
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                if visit(child, trail + (child.name,)):
                    return True
            else:
                if visit(child, trail):
                    return True
        return False

    visit(module.tree, ())
    return ".".join(path) if path else getattr(target, "name", "<anon>")
