"""Unified telemetry: metrics registry, trace spans, goodput, capture.

The single observability surface for the whole system (README
"Observability").  Five subsystems' scattered counters and timers flow
through one process-global :class:`MetricsRegistry`; host-phase
:func:`span` context managers attribute each step's wall-clock; a
:class:`GoodputTracker` splits it into productive vs recovery time; the
jit-cache probe (:func:`register_compiled`) counts XLA compilations per
step function and flags retrace storms; and :class:`OnDemandProfiler`
opens a bounded ``jax.profiler`` window on SIGUSR2 or at a configured
iteration.  Everything exports through three sinks (TensorBoard / JSONL
snapshot / human summary table) behind the :class:`Telemetry` facade the
Runner drives.

Core modules (registry, spans, goodput, retrace) are stdlib-only so the
data pipeline and serving stack can import them without pulling JAX in.
"""
from .capture import OnDemandProfiler, parse_signal
from .goodput import GoodputTracker
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    reset_registry,
)
from .retrace import JitCacheProbe, get_probe, register_compiled
from .runtime import Telemetry
from .sinks import JsonlSink, LogSink, Sink, TensorBoardSink, summary_table
from .slo import mttr_events, summarize_recoveries
from .spans import SpanRecorder, get_recorder, set_recorder, span

__all__ = [
    "Counter",
    "Gauge",
    "GoodputTracker",
    "Histogram",
    "JitCacheProbe",
    "JsonlSink",
    "LogSink",
    "MetricsRegistry",
    "OnDemandProfiler",
    "Sink",
    "SpanRecorder",
    "Telemetry",
    "TensorBoardSink",
    "get_probe",
    "get_recorder",
    "get_registry",
    "mttr_events",
    "parse_signal",
    "register_compiled",
    "reset_registry",
    "set_recorder",
    "span",
    "summarize_recoveries",
    "summary_table",
]
