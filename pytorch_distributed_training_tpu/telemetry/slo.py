"""Recovery-SLO accounting: MTTR and recovery budgets from trace spans.

The chaos soak engine (engine/chaos.py) holds every recovery ladder to a
measured service-level objective, not just "it didn't crash".  The raw
material is the span stream (spans.py): each recovery path brackets its
work in a *recovery span* —

    training : ``rollback`` (anomaly guard), ``integrity_restore``
               (sentinel snapshot restore)
    serving  : ``serving_restart`` (hot-restart + replay),
               ``poison_bisect`` (culprit isolation)

— and productive progress is marked by *productive spans*
(``step_dispatch`` for training steps, ``decode_step`` for serving ticks).

**MTTR** for one recovery event = wall time from the moment the fault was
acted on (the recovery span's start — detection latency inside the step
that tripped the guard is already part of that step, not the recovery) to
the END of the first productive span that STARTS after the recovery span
finished: the system is "recovered" when it has completed new useful work,
not when the restore call returned.  A recovery with no later productive
span (the run ended first) reports ``mttr_ms = None`` — callers treat
that as a violation or as run-truncation depending on the scenario.

Stdlib-only (telemetry core contract): works on the in-memory ring from
``get_recorder().recent()`` and on parsed ``spans_rank<k>.jsonl`` lines
alike, since both carry the same record dicts.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

__all__ = [
    "PRODUCTIVE_SPAN_KINDS",
    "RECOVERY_SPAN_KINDS",
    "mttr_events",
    "summarize_recoveries",
]

RECOVERY_SPAN_KINDS = (
    "rollback",
    "integrity_restore",
    "serving_restart",
    "poison_bisect",
)

PRODUCTIVE_SPAN_KINDS = (
    "step_dispatch",
    "decode_step",
)


def _end(rec: Dict) -> float:
    return float(rec["t"]) + float(rec.get("ms", 0.0)) / 1e3


def mttr_events(
    records: Sequence[Dict],
    recovery_kinds: Sequence[str] = RECOVERY_SPAN_KINDS,
    productive_kinds: Sequence[str] = PRODUCTIVE_SPAN_KINDS,
) -> List[Dict]:
    """One event dict per recovery span found in ``records``.

    Keys: ``kind``, ``step`` (the step/tick the recovery anchored to),
    ``recovery_ms`` (the recovery span's own duration), ``mttr_ms``
    (recovery start → end of first productive span starting after the
    recovery finished; None when the run produced nothing afterwards).
    Records need not be sorted; they are ordered by start time here.
    """
    recs = sorted(records, key=lambda r: float(r["t"]))
    productive = [r for r in recs if r.get("kind") in set(productive_kinds)]
    events: List[Dict] = []
    for rec in recs:
        if rec.get("kind") not in set(recovery_kinds):
            continue
        t_start, t_done = float(rec["t"]), _end(rec)
        first_prod: Optional[Dict] = None
        for p in productive:
            if float(p["t"]) >= t_done:
                first_prod = p
                break
        events.append({
            "kind": rec["kind"],
            "step": rec.get("step"),
            "recovery_ms": round(float(rec.get("ms", 0.0)), 3),
            "mttr_ms": (
                round((_end(first_prod) - t_start) * 1e3, 3)
                if first_prod is not None else None
            ),
        })
    return events


def summarize_recoveries(records: Sequence[Dict]) -> Dict:
    """Aggregate SLO view over a run's spans (one scenario's worth).

    ``events`` is the per-recovery list from :func:`mttr_events`;
    ``mttr_ms_max``/``mttr_ms_mean`` aggregate the measured ones (None
    when no recovery completed); ``unrecovered`` counts recovery spans
    with no productive work after them.
    """
    events = mttr_events(records)
    measured = [e["mttr_ms"] for e in events if e["mttr_ms"] is not None]
    return {
        "events": events,
        "recoveries": len(events),
        "unrecovered": sum(1 for e in events if e["mttr_ms"] is None),
        "mttr_ms_max": max(measured) if measured else None,
        "mttr_ms_mean": (
            round(sum(measured) / len(measured), 3) if measured else None
        ),
    }
