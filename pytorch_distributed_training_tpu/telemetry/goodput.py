"""Goodput accounting: how much wall-clock actually advanced training.

The fault/elastic layers recover from anomalies, hangs, dead workers and
dead peers — but recovery costs steps.  This tracker splits the training
loop's wall time into:

- **productive**: steps that advanced the optimizer to a NEW iteration
  (applied, never seen before);
- **replay**: steps re-run after a rollback (``iter`` at or below the
  furthest iteration previously reached — the same batches again);
- **wasted**: steps the anomaly guard skipped (state bitwise untouched);
- **lost buckets** by kind: rollback restores, restart/re-init, and
  whatever else a caller bills via :meth:`note_lost`.

``goodput_ratio = productive / (productive + replay + wasted + lost)`` is
the single number a long chaotic run is judged by — the per-step
scaling-efficiency accounting the minutes-scale ImageNet recipes
(PAPERS.md 1811.05233, 1903.12650) are built on.  Recompile time is
tracked separately by the jit-cache probe (retrace.py): XLA compiles
inside a step are invisible to host timers except as a slow step, so the
probe counts them rather than pretending to time them.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["GoodputTracker"]


class GoodputTracker:
    """Thread-safe productive-vs-lost wall-time ledger."""

    def __init__(self):
        self._lock = threading.Lock()
        self._productive_s = 0.0
        self._replay_s = 0.0
        self._wasted_s = 0.0
        self._lost: Dict[str, float] = {}
        self._steps = 0
        self._replayed_steps = 0
        self._wasted_steps = 0

    def note_step(
        self, seconds: float, applied: bool = True, replayed: bool = False
    ) -> None:
        """Bill one loop iteration's wall time.

        ``applied=False`` marks an anomaly-guard skip (the step ran but
        changed nothing); ``replayed=True`` marks a post-rollback re-run.
        A replayed skip bills as replay (the rollback already owns the
        waste).
        """
        s = float(seconds)
        with self._lock:
            self._steps += 1
            if replayed:
                self._replayed_steps += 1
                self._replay_s += s
            elif not applied:
                self._wasted_steps += 1
                self._wasted_s += s
            else:
                self._productive_s += s

    def note_lost(self, kind: str, seconds: float) -> None:
        """Bill non-step recovery time (``rollback``, ``restart``, ...)."""
        with self._lock:
            self._lost[kind] = self._lost.get(kind, 0.0) + float(seconds)

    def ratio(self) -> Optional[float]:
        with self._lock:
            total = (
                self._productive_s + self._replay_s + self._wasted_s
                + sum(self._lost.values())
            )
            if total <= 0.0:
                return None
            return self._productive_s / total

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            lost_total = sum(self._lost.values())
            total = self._productive_s + self._replay_s + self._wasted_s + lost_total
            out = {
                "steps": self._steps,
                "replayed_steps": self._replayed_steps,
                "skipped_steps": self._wasted_steps,
                "productive_s": round(self._productive_s, 6),
                "replay_s": round(self._replay_s, 6),
                "skipped_s": round(self._wasted_s, 6),
                "lost_s": round(lost_total, 6),
            }
            for kind, s in sorted(self._lost.items()):
                out[f"lost_{kind}_s"] = round(s, 6)
            if total > 0.0:
                out["goodput_ratio"] = round(self._productive_s / total, 6)
            return out

    def reset(self) -> None:
        with self._lock:
            self._productive_s = self._replay_s = self._wasted_s = 0.0
            self._lost.clear()
            self._steps = self._replayed_steps = self._wasted_steps = 0
