"""Telemetry export sinks: one snapshot schema, three surfaces.

Every ``snapshot_interval`` steps (and once at end of run) the Telemetry
facade assembles one structured snapshot — counters, gauges, histogram
percentiles, goodput split, compile counts — and hands it to each
configured sink:

- :class:`TensorBoardSink`: scalars onto the Runner's existing rank-0
  writer under ``telemetry/…`` (counters, gauges, histogram p50/p95/p99,
  goodput ratio) so the dashboards people already watch gain the new
  numbers for free.
- :class:`JsonlSink`: the full snapshot, one JSON object per line, into
  ``snapshots.jsonl`` under the telemetry dir — the machine-readable
  record a regression hunt greps.  Written by rank 0 only (the registry is
  per-process; cross-host aggregation follows the ``logger/`` design:
  per-host span files + the rank-0 funnelled summary, not a distributed
  collector).
- :class:`LogSink`: the human ``summary()`` table through the process
  logger — which in the Runner carries a ``QueueHandler`` into the
  multiprocess log funnel (``logger/``), so the table lands in the same
  rank-0 aggregated log file as everything else.

``summary_table`` is also called directly by the watchdog-hang and
peer-loss diagnostics.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional

__all__ = ["Sink", "TensorBoardSink", "JsonlSink", "LogSink", "summary_table"]


class Sink:
    """Export interface: receives each periodic snapshot."""

    def emit(self, snapshot: Dict, step: Optional[int]) -> None:
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - default no-op
        pass


class TensorBoardSink(Sink):
    """Scalars onto an existing SummaryWriter-compatible object."""

    def __init__(self, writer, prefix: str = "telemetry"):
        self._writer = writer
        self._prefix = prefix

    def emit(self, snapshot: Dict, step: Optional[int]) -> None:
        if self._writer is None or step is None:
            return
        p = self._prefix
        for name, v in snapshot.get("counters", {}).items():
            self._writer.add_scalar(f"{p}/counters/{name}", v, step)
        for name, g in snapshot.get("gauges", {}).items():
            self._writer.add_scalar(f"{p}/gauges/{name}", g["value"], step)
        for name, h in snapshot.get("histograms", {}).items():
            if h.get("count"):
                for q in ("p50", "p95", "p99"):
                    self._writer.add_scalar(f"{p}/{name}/{q}", h[q], step)
        ratio = snapshot.get("goodput", {}).get("goodput_ratio")
        if ratio is not None:
            self._writer.add_scalar(f"{p}/goodput_ratio", ratio, step)
        # the writer flushes on its own schedule; no flush here


class JsonlSink(Sink):
    """Append each snapshot as one JSON line (machine-readable record)."""

    def __init__(self, path: str):
        self.path = path
        self._file = open(path, "a")

    def emit(self, snapshot: Dict, step: Optional[int]) -> None:
        rec = {"step": step, "wall": round(time.time(), 3)}
        rec.update(snapshot)
        self._file.write(json.dumps(rec) + "\n")
        self._file.flush()

    def close(self) -> None:
        self._file.close()


class LogSink(Sink):
    """The human summary table through the (funnelled) process logger."""

    def __init__(self, logger: Optional[logging.Logger] = None):
        self._logger = logger or logging.getLogger(__name__)

    def emit(self, snapshot: Dict, step: Optional[int]) -> None:
        self._logger.info(
            "telemetry summary (step %s):\n%s", step, summary_table(snapshot)
        )


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


def summary_table(snapshot: Dict) -> str:
    """Render a snapshot as an aligned two-column table (the ``summary()``
    surface printed at end of run and on watchdog/peer-loss dumps)."""
    rows: List[tuple] = []
    gp = snapshot.get("goodput", {})
    if gp:
        ratio = gp.get("goodput_ratio")
        rows.append((
            "goodput.ratio", f"{ratio:.4f}" if ratio is not None else "n/a"
        ))
        for k in ("steps", "replayed_steps", "skipped_steps"):
            if gp.get(k):
                rows.append((f"goodput.{k}", _fmt(gp[k])))
        for k, v in gp.items():
            if k.endswith("_s") and v:
                rows.append((f"goodput.{k}", _fmt(v)))
    for name, v in sorted(snapshot.get("counters", {}).items()):
        if v:
            rows.append((f"counter.{name}", _fmt(v)))
    for name, g in sorted(snapshot.get("gauges", {}).items()):
        rows.append((f"gauge.{name}", f"{g['value']:.3f} (max {g['max']:.3f})"))
    for name, h in sorted(snapshot.get("histograms", {}).items()):
        if h.get("count"):
            rows.append((
                f"hist.{name}",
                f"n={h['count']} mean={h['mean']:.3f} p50={h['p50']:.3f} "
                f"p95={h['p95']:.3f} p99={h['p99']:.3f}",
            ))
    if not rows:
        return "  (no telemetry recorded)"
    width = max(len(k) for k, _ in rows)
    return "\n".join(f"  {k.ljust(width)}  {v}" for k, v in rows)
