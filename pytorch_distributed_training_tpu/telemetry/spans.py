"""Host-phase trace spans: where each step's wall-clock actually went.

A span brackets one host-visible phase of the training loop — data wait,
step dispatch, device block, eval, checkpoint snapshot vs async write,
elastic guard window — with a context manager:

    with spans.span("data_wait", step=it):
        batch = next(stream)

Every span records monotonic start, wall-clock start, duration, kind,
step, and thread, and lands in two places:

- a bounded in-memory ring (always on, O(1) per span) that the watchdog
  and peer-loss diagnostics dump — a hang report says what the process was
  DOING, not just that it stopped;
- optionally a per-host JSONL file (``spans_rank<k>.jsonl`` under the
  telemetry dir), append-buffered and flushed every ``flush_every`` spans
  so the file cost stays off the per-span path.

Per-host files rather than one shared file: hosts only share a filesystem
by accident, and interleaved writers corrupt JSONL.  Rank 0's periodic
registry snapshot (sinks.py) is the aggregated view.

The module keeps one *current* recorder that the free function
:func:`span` uses, so deep call sites (``engine/checkpoint.py``'s writer
thread, ``engine/elastic.py``'s guard) emit spans without threading a
handle through every constructor — the same pattern as the fault-counter
ledger.  The default recorder is ring-only; the Runner swaps in its
configured recorder for the duration of the run.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["SpanRecorder", "get_recorder", "set_recorder", "span"]


class SpanRecorder:
    """Thread-safe span sink: bounded ring + optional buffered JSONL file."""

    def __init__(
        self,
        path: Optional[str] = None,
        ring: int = 256,
        host: int = 0,
        flush_every: int = 64,
    ):
        self.path = path
        self.host = int(host)
        self._ring: deque = deque(maxlen=max(int(ring), 1))
        self._buf: List[str] = []
        self._flush_every = max(int(flush_every), 1)
        self._lock = threading.Lock()
        self._file = open(path, "a") if path else None
        self.enabled = True

    @contextlib.contextmanager
    def span(self, kind: str, step: Optional[int] = None, **extra):
        if not self.enabled:
            yield
            return
        t0 = time.monotonic()
        wall = time.time()
        try:
            yield
        finally:
            self._record(kind, step, t0, wall, time.monotonic() - t0, extra)

    def _record(self, kind, step, t0, wall, dur_s, extra) -> None:
        rec: Dict = {
            "kind": kind,
            "step": step,
            "host": self.host,
            "t": round(t0, 6),
            "wall": round(wall, 3),
            "ms": round(dur_s * 1e3, 3),
            "thread": threading.current_thread().name,
        }
        if extra:
            rec.update(extra)
        with self._lock:
            self._ring.append(rec)
            if self._file is not None:
                self._buf.append(json.dumps(rec))
                if len(self._buf) >= self._flush_every:
                    self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buf and self._file is not None:
            self._file.write("\n".join(self._buf) + "\n")
            self._file.flush()
        self._buf.clear()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def recent(self, n: Optional[int] = None) -> List[Dict]:
        """Last ``n`` spans, oldest first (diagnostics payload)."""
        with self._lock:
            items = list(self._ring)
        return items if n is None else items[-int(n):]

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._file is not None:
                self._file.close()
                self._file = None


# ----------------------------------------------------------- current recorder
_LOCK = threading.Lock()
_RECORDER: Optional[SpanRecorder] = None


def get_recorder() -> SpanRecorder:
    """The current recorder (a ring-only default until a run installs one)."""
    global _RECORDER
    if _RECORDER is None:
        with _LOCK:
            if _RECORDER is None:
                _RECORDER = SpanRecorder()
    return _RECORDER


def set_recorder(recorder: Optional[SpanRecorder]) -> SpanRecorder:
    """Install ``recorder`` as the process's current one (None restores a
    fresh ring-only default); returns the recorder now in effect."""
    global _RECORDER
    with _LOCK:
        _RECORDER = recorder if recorder is not None else SpanRecorder()
        return _RECORDER


def span(kind: str, step: Optional[int] = None, **extra):
    """Record a phase span on the current recorder (context manager)."""
    return get_recorder().span(kind, step=step, **extra)
