"""On-demand profiler capture: a bounded jax.profiler window, on request.

The existing :class:`..engine.profiling.TraceProfiler` captures a window
configured BEFORE launch (``training.profile``).  Production regressions
don't schedule themselves: this module arms a capture while the run is
already going — either

- **signal-triggered**: ``kill -USR2 <pid>`` latches a flag (the handler
  does nothing else — signal-safe), and the NEXT step boundary opens a
  ``jax.profiler`` trace for ``n_iters`` steps into the telemetry dir; or
- **config-triggered**: ``training.telemetry.capture.at_iter`` arms the
  same window at a fixed step, for reproducing a known-bad region.

The window is bounded and closes itself (step-granular, synced on the
state so the trace ends at a step boundary, mirroring TraceProfiler's
hygiene).  One capture at a time; re-signalling during a capture is
ignored.  Signal installation only happens on the main thread (Python
refuses elsewhere) and restores the previous handler on ``close``.
"""
from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Optional

__all__ = ["OnDemandProfiler", "parse_signal"]


def parse_signal(spec) -> Optional[int]:
    """``"SIGUSR2"`` / ``"USR2"`` / ``12`` / None -> signal number."""
    if spec is None:
        return None
    if isinstance(spec, int):
        return signal.Signals(spec).value
    name = str(spec).upper()
    if not name.startswith("SIG"):
        name = "SIG" + name
    try:
        return signal.Signals[name].value
    except KeyError:
        raise ValueError(
            f"unknown capture signal {spec!r} (want e.g. SIGUSR2 or a number)"
        ) from None


class OnDemandProfiler:
    """Armable bounded jax.profiler window (signal- or config-triggered)."""

    def __init__(
        self,
        trace_dir: str,
        n_iters: int = 5,
        signum: Optional[int] = None,
        at_iter: Optional[int] = None,
        logger: Optional[logging.Logger] = None,
    ):
        if int(n_iters) < 1:
            raise ValueError(f"capture n_iters must be >= 1, got {n_iters}")
        self.trace_dir = trace_dir
        self.n_iters = int(n_iters)
        self.at_iter = None if at_iter is None else int(at_iter)
        self.signum = signum
        self._logger = logger or logging.getLogger(__name__)
        self._armed = threading.Event()
        self._tracing_from: Optional[int] = None
        self._captures = 0
        self._prev_handler = None
        self._installed = False
        if signum is not None and threading.current_thread() is threading.main_thread():
            self._prev_handler = signal.signal(signum, self._on_signal)
            self._installed = True

    # signal context: just latch the flag — everything else happens at the
    # next step boundary on the training thread
    def _on_signal(self, signum, frame) -> None:  # pragma: no cover - handler
        self._armed.set()

    def arm(self) -> None:
        """Programmatic trigger (the config path and tests)."""
        self._armed.set()

    @property
    def tracing(self) -> bool:
        return self._tracing_from is not None

    def after_step(self, it: int, sync=None) -> None:
        """Step-boundary hook: open an armed window / close a full one."""
        if self._tracing_from is not None:
            if it + 1 - self._tracing_from >= self.n_iters:
                self._stop(sync)
            return
        if self.at_iter is not None and it + 1 == self.at_iter:
            self._armed.set()
        if self._armed.is_set():
            self._armed.clear()
            self._start(it + 1)

    def _start(self, from_iter: int) -> None:
        import jax

        out = os.path.join(
            self.trace_dir, f"capture_{self._captures}_iter{from_iter}"
        )
        os.makedirs(out, exist_ok=True)
        try:
            jax.profiler.start_trace(out)
        except Exception as e:
            # a second live trace in the process (e.g. TraceProfiler's
            # window) raises — skip this capture rather than kill the run
            self._logger.warning("on-demand capture could not start: %s", e)
            return
        self._tracing_from = from_iter
        self._t0 = time.monotonic()
        self._logger.warning(
            "on-demand profiler capture ON: steps %d..%d -> %s",
            from_iter, from_iter + self.n_iters - 1, out,
        )

    def _stop(self, sync=None) -> None:
        import jax

        if sync is not None:
            jax.block_until_ready(sync)
        jax.profiler.stop_trace()
        self._logger.warning(
            "on-demand profiler capture done: %d step(s) in %.2fs",
            self.n_iters, time.monotonic() - self._t0,
        )
        self._tracing_from = None
        self._captures += 1

    def close(self, sync=None) -> None:
        if self._tracing_from is not None:
            try:
                self._stop(sync)
            except Exception:  # pragma: no cover - teardown best-effort
                pass
        if self._installed:
            try:
                signal.signal(self.signum, self._prev_handler)
            except (ValueError, TypeError):  # pragma: no cover - non-main thread
                pass
            self._installed = False
