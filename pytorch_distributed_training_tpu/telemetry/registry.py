"""Process-global metrics registry: counters, gauges, bounded histograms.

One ledger for every subsystem's observability numbers.  Before this
module each layer grew its own store — ``engine/fault.py`` had a module
``Counter``, ``serving/metrics.py`` kept unbounded per-request lists,
``engine/checkpoint.py`` and ``data/worker_pool.py`` carried loose ints —
so "where did the wall-clock go" required reading five snapshots with five
schemas.  Now every counter flows through a :class:`MetricsRegistry`
(``tests/test_marker_convention.py`` statically rejects new ad-hoc counter
dicts outside ``telemetry/``).

Design constraints, in order:

- **Import-light** (stdlib only): the data pipeline, serving stack, and
  fault layer consult the registry without pulling JAX in — the same rule
  ``engine/fault.py`` already follows.
- **Low overhead**: an ``inc`` is one lock + one int add; a histogram
  ``observe`` is one lock + O(1) reservoir bookkeeping.  Nothing allocates
  per call on the steady path.
- **Bounded memory**: histograms keep an Algorithm-R reservoir (uniform
  sample of everything observed) plus EXACT count/sum/min/max, so
  percentiles stay statistically stable and means stay exact no matter how
  long the process runs — the fix for the serving metrics lists that grew
  forever under sustained traffic.

The process-global registry lives behind :func:`get_registry`; subsystems
that need *instance-local* semantics (one :class:`ServingMetrics` per
engine) instantiate their own :class:`MetricsRegistry`.
"""
from __future__ import annotations

import math
import random
import threading
from typing import Dict, List, Optional

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "reset_registry",
]


class Counter:
    """Monotonic integer counter (thread-safe)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += int(n)

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-write-wins float (thread-safe); tracks the running max too."""

    __slots__ = ("name", "_value", "_max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            if self._value > self._max:
                self._max = self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def max(self) -> float:
        with self._lock:
            return self._max

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0
            self._max = 0.0


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated percentile of an ascending list (numpy's default
    method, so snapshots keep byte-stable values across the serving-metrics
    migration off ``np.percentile``)."""
    n = len(sorted_vals)
    if n == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (n - 1)
    lo = int(math.floor(pos))
    frac = pos - lo
    hi = min(lo + 1, n - 1)
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class Histogram:
    """Bounded-reservoir histogram: exact count/sum/min/max, sampled tails.

    Algorithm R keeps a uniform sample of the full observation stream in
    ``reservoir_size`` slots, so p50/p95/p99 estimate the TRUE stream
    percentiles (not a sliding window's) under any volume, while the moments
    the snapshot reports as exact (count, sum, mean, min, max) ARE exact.
    The RNG is seeded per-histogram so snapshots are reproducible.
    """

    __slots__ = (
        "name", "reservoir_size", "_sample", "_count", "_sum", "_min",
        "_max", "_rng", "_lock",
    )

    def __init__(self, name: str, reservoir_size: int = 1024):
        if int(reservoir_size) < 1:
            raise ValueError(
                f"histogram reservoir_size must be >= 1, got {reservoir_size}"
            )
        self.name = name
        self.reservoir_size = int(reservoir_size)
        self._sample: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._rng = random.Random(0x5EED ^ (hash(name) & 0xFFFFFFFF))
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if len(self._sample) < self.reservoir_size:
                self._sample.append(v)
            else:
                # Algorithm R: slot i < k with probability k/count — every
                # observation ever made has equal odds of being in the sample
                i = self._rng.randrange(self._count)
                if i < self.reservoir_size:
                    self._sample[i] = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._sample:
                return None
            return _percentile(sorted(self._sample), q)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if self._count == 0:
                return {"count": 0}
            s = sorted(self._sample)
            return {
                "count": self._count,
                "sum": self._sum,
                "mean": self._sum / self._count,
                "min": self._min,
                "max": self._max,
                "p50": _percentile(s, 50),
                "p95": _percentile(s, 95),
                "p99": _percentile(s, 99),
            }

    def _reset(self) -> None:
        with self._lock:
            self._sample.clear()
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None


class MetricsRegistry:
    """Named instrument store; instruments are created on first use."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}

    def _get(self, name: str, cls, *args):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, *args)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested {cls.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, reservoir_size: int = 1024) -> Histogram:
        return self._get(name, Histogram, reservoir_size)

    # ------------------------------------------------------------- snapshots
    def counters(self) -> Dict[str, int]:
        """All counter values (the ``fault.counters()`` compatibility view)."""
        with self._lock:
            insts = list(self._instruments.values())
        return {i.name: i.value for i in insts if isinstance(i, Counter)}

    def snapshot(self) -> Dict[str, dict]:
        """Full structured view: one sub-dict per instrument family."""
        with self._lock:
            insts = list(self._instruments.values())
        out: Dict[str, dict] = {"counters": {}, "gauges": {}, "histograms": {}}
        for i in insts:
            if isinstance(i, Counter):
                out["counters"][i.name] = i.value
            elif isinstance(i, Gauge):
                out["gauges"][i.name] = {"value": i.value, "max": i.max}
            elif isinstance(i, Histogram):
                out["histograms"][i.name] = i.snapshot()
        return out

    def reset(self) -> None:
        """Zero every instrument (kept registered — object identity is part
        of the API: call sites cache ``registry.counter(name)``)."""
        with self._lock:
            insts = list(self._instruments.values())
        for i in insts:
            i._reset()


# ---------------------------------------------------------- process-global
_REGISTRY_LOCK = threading.Lock()
_REGISTRY: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-wide registry (every subsystem's shared ledger)."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY


def reset_registry() -> None:
    """Zero the process registry (test/bench isolation hook)."""
    get_registry().reset()
