"""Jit-cache retrace probe: count XLA compilations per compiled function.

A retrace storm — a jitted step recompiling every call because a Python
scalar, a changing shape, or a fresh closure rides into it as a new
signature — looks exactly like "training got 100x slower" from the host
timers.  The probe makes it attributable: every step builder registers its
compiled function under a stable name, and a per-step poll reads the
function's executable-cache size (``_cache_size()`` on jax's jit wrapper
— the count of distinct traced signatures, i.e. compilations).  Deltas
flow into the global registry as ``compiles/<name>`` counters, and a
function whose RE-compile count (compiles beyond the first) crosses
``warn_threshold`` logs one loud storm warning per new compile.

Registered functions are held by weakref so the probe never extends the
life of a step (and the closures over model/optimizer inside it) past its
builder's caller.  Functions without ``_cache_size`` (older jax, wrapped
callables) register as inert entries — the probe degrades to "no data",
never to an error.  Import-light: nothing here imports jax.
"""
from __future__ import annotations

import logging
import threading
import weakref
from typing import Dict, Optional

from .registry import MetricsRegistry, get_registry

__all__ = ["JitCacheProbe", "get_probe", "register_compiled"]


class JitCacheProbe:
    """Registry of compiled fns, polled for executable-cache growth."""

    def __init__(self, warn_threshold: int = 3, logger=None):
        self.warn_threshold = int(warn_threshold)
        self._logger = logger or logging.getLogger(__name__)
        self._lock = threading.Lock()
        # name -> (weakref to fn, compiles already accounted, compiles warned)
        self._entries: Dict[str, list] = {}

    def register(self, name: str, fn):
        """Track ``fn``'s compile cache under ``name``; returns ``fn`` so
        builders can register in the return statement.  A name whose prior
        registrant is still alive gets a ``#k`` suffix (bench loops build
        the same family repeatedly)."""
        with self._lock:
            key = name
            k = 1
            while key in self._entries and self._entries[key][0]() is not None:
                k += 1
                key = f"{name}#{k}"
            self._entries[key] = [weakref.ref(fn), 0, 0]
        return fn

    @staticmethod
    def _cache_size(fn) -> Optional[int]:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:
            return None

    def poll(self, registry: Optional[MetricsRegistry] = None) -> Dict[str, int]:
        """Account new compilations since the last poll; returns the current
        total compile count per live registered fn."""
        reg = registry if registry is not None else get_registry()
        totals: Dict[str, int] = {}
        with self._lock:
            items = list(self._entries.items())
        for name, entry in items:
            fn = entry[0]()
            if fn is None:
                continue
            size = self._cache_size(fn)
            if size is None:
                continue
            totals[name] = size
            delta = size - entry[1]
            if delta <= 0:
                continue
            entry[1] = size
            reg.counter(f"compiles/{name}").inc(delta)
            recompiles = size - 1
            if recompiles >= self.warn_threshold and size > entry[2]:
                entry[2] = size
                self._logger.warning(
                    "RETRACE STORM: %s has compiled %d times (%d retraces, "
                    "threshold %d) — a step input is changing "
                    "signature/shape every call; see compiles/%s in the "
                    "telemetry snapshot",
                    name, size, recompiles, self.warn_threshold, name,
                )
        return totals

    def snapshot(self) -> Dict[str, int]:
        """Current compile counts without mutating warning state."""
        out: Dict[str, int] = {}
        with self._lock:
            items = list(self._entries.items())
        for name, entry in items:
            fn = entry[0]()
            if fn is None:
                continue
            size = self._cache_size(fn)
            if size is not None:
                out[name] = size
        return out


# ---------------------------------------------------------- process-global
_LOCK = threading.Lock()
_PROBE: Optional[JitCacheProbe] = None


def get_probe() -> JitCacheProbe:
    global _PROBE
    if _PROBE is None:
        with _LOCK:
            if _PROBE is None:
                _PROBE = JitCacheProbe()
    return _PROBE


def register_compiled(name: str, fn):
    """Register a compiled fn with the process probe (builders call this
    in their return path); returns ``fn``."""
    return get_probe().register(name, fn)
