"""The Telemetry facade: one object the Runner (and bench) drives.

Bundles the process registry, a configured span recorder, the goodput
tracker, the jit-cache probe, the on-demand profiler, and the export
sinks behind the handful of calls the training loop makes:

    tel = Telemetry(dir=..., host=rank, is_rank0=..., tb_writer=...)
    with tel.span("data_wait", step=it): ...
    tel.note_step(dt, applied=..., replayed=...)
    tel.after_step(it, sync=state)      # probe poll + capture + export
    tel.diagnostics()                   # watchdog / peer-loss dump payload
    tel.close(step=final)               # final snapshot + summary + flush

``enabled=False`` keeps the full surface but turns every call into a
cheap no-op (spans become ``nullcontext``), so call sites never branch.
The registry itself stays live either way — recovery counters predate
this layer and must keep flowing (``engine/fault.py`` tests).
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
from typing import Dict, List, Optional

from .capture import OnDemandProfiler
from .goodput import GoodputTracker
from .registry import get_registry
from .retrace import get_probe
from .sinks import JsonlSink, LogSink, Sink, TensorBoardSink, summary_table
from .spans import SpanRecorder, set_recorder

__all__ = ["Telemetry"]


class Telemetry:
    """Per-run telemetry driver over the process-global instruments."""

    def __init__(
        self,
        enabled: bool = True,
        dir: Optional[str] = None,
        host: int = 0,
        is_rank0: bool = True,
        snapshot_interval: int = 100,
        span_ring: int = 256,
        retrace_warn: int = 3,
        tb_writer=None,
        use_tensorboard: bool = True,
        capture_signal: Optional[int] = None,
        capture_iters: int = 5,
        capture_at_iter: Optional[int] = None,
        capture_dir: Optional[str] = None,
        logger: Optional[logging.Logger] = None,
    ):
        self.enabled = bool(enabled)
        self.dir = dir
        self._logger = logger or logging.getLogger(__name__)
        self._interval = max(int(snapshot_interval), 1)
        self.registry = get_registry()
        self.goodput = GoodputTracker()
        self.probe = get_probe()
        self.probe.warn_threshold = int(retrace_warn)
        self.probe._logger = self._logger
        self.capture: Optional[OnDemandProfiler] = None
        self._sinks: List[Sink] = []
        self._recorder: Optional[SpanRecorder] = None
        self._closed = False
        if not self.enabled:
            return

        if dir is not None:
            os.makedirs(dir, exist_ok=True)
            span_path = os.path.join(dir, f"spans_rank{host}.jsonl")
        else:
            span_path = None
        # the configured recorder becomes the process-current one so deep
        # call sites (checkpoint writer thread, elastic guard) land in the
        # same ring/file (spans.span free function)
        self._recorder = set_recorder(
            SpanRecorder(path=span_path, ring=span_ring, host=host)
        )

        if is_rank0:
            if use_tensorboard and tb_writer is not None:
                self._sinks.append(TensorBoardSink(tb_writer))
            if dir is not None:
                self._sinks.append(
                    JsonlSink(os.path.join(dir, "snapshots.jsonl"))
                )
            self._sinks.append(LogSink(self._logger))

        cap_dir = capture_dir or (
            None if dir is None else os.path.join(dir, "profile")
        )
        if cap_dir is not None and (
            capture_signal is not None or capture_at_iter is not None
        ):
            self.capture = OnDemandProfiler(
                cap_dir,
                n_iters=capture_iters,
                signum=capture_signal,
                at_iter=capture_at_iter,
                logger=self._logger,
            )

    # --------------------------------------------------------------- loop API
    def span(self, kind: str, step: Optional[int] = None, **extra):
        if not self.enabled or self._recorder is None:
            return contextlib.nullcontext()
        return self._recorder.span(kind, step=step, **extra)

    def note_step(self, seconds: float, applied: bool = True,
                  replayed: bool = False) -> None:
        if self.enabled:
            self.goodput.note_step(seconds, applied=applied, replayed=replayed)

    def note_lost(self, kind: str, seconds: float) -> None:
        if self.enabled:
            self.goodput.note_lost(kind, seconds)

    def after_step(self, it: int, sync=None) -> None:
        """Once per loop iteration: poll the retrace probe, advance any
        profiler capture window, and export on the snapshot interval."""
        if not self.enabled:
            return
        self.probe.poll(self.registry)
        if self.capture is not None:
            self.capture.after_step(it, sync=sync)
        if (it + 1) % self._interval == 0:
            self.export(it)

    # -------------------------------------------------------------- snapshots
    def snapshot(self) -> Dict:
        snap = self.registry.snapshot()
        snap["goodput"] = self.goodput.snapshot()
        snap["compiles"] = self.probe.snapshot()
        return snap

    def export(self, step: Optional[int]) -> Dict:
        snap = self.snapshot()
        for sink in self._sinks:
            try:
                sink.emit(snap, step)
            except Exception:  # one broken sink must not stop the others
                self._logger.exception(
                    "telemetry sink %s failed", type(sink).__name__
                )
        return snap

    def summary(self) -> str:
        """The human table (printed at end of run and on diagnostics)."""
        return summary_table(self.snapshot())

    def diagnostics(self, n_spans: int = 20) -> str:
        """Watchdog/peer-loss payload: last spans + the counter snapshot —
        what the process was doing, not just that it stopped."""
        spans = self._recorder.recent(n_spans) if self._recorder else []
        lines = ["last %d span(s):" % len(spans)]
        for rec in spans:
            lines.append("  " + json.dumps(rec))
        lines.append("registry summary:")
        lines.append(self.summary())
        return "\n".join(lines)

    # --------------------------------------------------------------- teardown
    def flush(self) -> None:
        """Crash-path flush: spans buffered to disk, nothing closed."""
        if self._recorder is not None:
            self._recorder.flush()

    def close(self, step: Optional[int] = None) -> None:
        """Final export + summary, then release files and the recorder."""
        if self._closed or not self.enabled:
            return
        self._closed = True
        self.probe.poll(self.registry)
        self.export(step)
        for sink in self._sinks:
            try:
                sink.close()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
        if self.capture is not None:
            self.capture.close()
        if self._recorder is not None:
            self._recorder.close()
            set_recorder(None)  # restore the ring-only default
