"""Determinism + iteration helpers.

Re-provides the ``dl_lib.utils`` surface pinned by the reference at
train_distributed.py:27 (``make_deterministic``, ``make_iter_dataloader``),
re-designed for a JAX runtime: JAX PRNG keys are explicit, so
``make_deterministic`` seeds the *host* RNGs (python/numpy/torch-if-present)
and records a global base seed from which the framework derives
``jax.random.PRNGKey`` streams.
"""
from __future__ import annotations

import random
from typing import Generator, Iterable, Optional, Tuple

import numpy as np

__all__ = [
    "make_deterministic",
    "get_base_seed",
    "make_iter_dataloader",
    "enable_compile_cache",
]

_BASE_SEED: Optional[int] = None


def make_deterministic(seed: int) -> None:
    """Seed all host-side RNGs and record the framework base seed.

    Reference contract (train_distributed.py:51-53, :141-142): called once in
    the parent and once per worker with the *same* seed on all ranks, so model
    init is identical everywhere (which is what makes DDP's initial param
    broadcast redundant — we rely on the same property: replicated same-seed
    init instead of a broadcast collective).

    On TPU/XLA, kernel determinism is the default; there is no
    ``cudnn.deterministic`` analog to set.
    """
    global _BASE_SEED
    _BASE_SEED = int(seed)
    random.seed(seed)
    np.random.seed(seed % (2**32))
    try:  # torch is an optional host-side dependency (parity tests only)
        import torch

        torch.manual_seed(seed)
    except ImportError:  # pragma: no cover
        pass


def get_base_seed(default: int = 0) -> int:
    """Base seed recorded by :func:`make_deterministic` (``default`` if unset)."""
    return _BASE_SEED if _BASE_SEED is not None else default


def enable_compile_cache(directory: str) -> str:
    """Point JAX's persistent compilation cache at ``directory``.

    The TPU-native analog of the reference's ``cudnn.benchmark = True``
    (train_distributed.py:54, SURVEY.md §2.3 autotune row): cuDNN autotune
    amortizes kernel selection across runs; XLA's persistent cache amortizes
    whole-program compilation across *launches* — the second launch of the
    same program skips the ~40s ResNet-50 step compile entirely.

    Thresholds are zeroed so every executable is cached regardless of compile
    time or size (the default 1s/64KB floors would skip small eval steps whose
    recompilation still costs seconds through a remote-device transport).
    """
    import os

    import jax

    directory = os.path.expanduser(directory)
    os.makedirs(directory, exist_ok=True)
    if jax.config.jax_compilation_cache_dir not in (None, directory):
        # the cache object is initialized lazily ONCE per process; a dir
        # change after first use is silently ignored without a reset
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # pragma: no cover - private-API drift tolerance
            pass
    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # -1 disables the size floor; 0 would mean "filesystem-dependent default",
    # which can silently reinstate a 64KB floor on some backends
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return directory


def make_iter_dataloader(
    loader: Iterable,
    start_iter: int = 0,
    start_epoch: Optional[int] = None,
    skip_batches: Optional[int] = None,
) -> Generator[Tuple, None, None]:
    """Convert an epoch-based loader into an infinite per-iteration generator.

    Reference contract (train_distributed.py:27, :249-252): the training loop
    is iteration-based (``train_iters`` total) and draws ``(img, label)``
    batches forever.  Between epochs we advance the loader's epoch so the
    distributed shuffle re-randomizes (the analog of
    ``DistributedSampler.set_epoch``).

    ``start_iter`` fast-forwards the stream to a checkpointed position
    (epoch = start_iter // batches_per_epoch, then skip the remainder at the
    index level) so a resumed run sees exactly the batch *indices* a straight
    run would.  For index-seeded datasets (synthetic) this makes resume
    bit-exact; for datasets with stochastic augmentation driven by the global
    host RNG (ImageFolder crop/flip) the skipped decodes don't consume RNG
    draws, so augmented pixels after resume differ from a hypothetical
    uninterrupted run — sample identity and visit order are still exact.

    ``start_epoch``/``skip_batches`` (both or neither) OVERRIDE that
    derivation with an explicitly persisted pipeline position (the elastic
    checkpoint sidecar, engine/checkpoint.py): after a mesh reshape the
    batch count per epoch may differ from the saving topology's, so
    dividing the step counter by the *current* epoch length would land on
    the wrong sample — the recorded (epoch, batches-consumed) pair is
    topology-independent under ``batch_division: world``.

    Validation runs eagerly at the CALL (this is a wrapper around the
    actual generator), so a bad resume position fails where it was
    computed, not at the loop's first ``next()``.
    """
    if hasattr(loader, "__len__") and len(loader) == 0:
        # drop_last can leave zero full batches (dataset shard < batch size);
        # the infinite loop below would busy-spin forever on an empty loader
        raise ValueError(
            "loader yields no batches (dataset shard smaller than batch size "
            "with drop_last?) — the iteration-based loop would spin forever"
        )
    if (start_epoch is None) != (skip_batches is None):
        raise ValueError(
            "start_epoch and skip_batches must be given together "
            f"(got start_epoch={start_epoch}, skip_batches={skip_batches})"
        )
    epoch = 0
    if start_epoch is not None:
        epoch = int(start_epoch)
        skip = int(skip_batches)
        if epoch < 0 or skip < 0:
            raise ValueError(
                f"start_epoch/skip_batches must be >= 0, got "
                f"{start_epoch}/{skip_batches}"
            )
        if skip and hasattr(loader, "skip_next"):
            loader.skip_next(skip)
    elif start_iter:
        batches_per_epoch = len(loader)
        epoch = start_iter // batches_per_epoch
        skip = start_iter % batches_per_epoch
        if skip and hasattr(loader, "skip_next"):
            loader.skip_next(skip)

    def _stream(epoch):
        while True:
            if hasattr(loader, "set_epoch"):
                loader.set_epoch(epoch)
            for batch in loader:
                yield batch
            epoch += 1

    return _stream(epoch)
