"""Bounded retry with exponential backoff + jitter (shared I/O policy).

Checkpoint storage on large jobs is the classic transient-failure surface
(SURVEY.md §5.3: the reference restarts from iter 0 on any failure; the
at-scale runs ROADMAP targets cannot).  One policy object serves every
retrying call site — today orbax save/restore in ``engine/checkpoint.py`` —
so backoff behavior is configured once and tested once.

Design points:
  - bounded ``attempts`` (never an infinite loop around a broken disk),
  - exponential backoff ``backoff * 2**attempt`` capped at ``max_backoff``,
    with multiplicative jitter so a fleet of hosts retrying a shared
    filesystem doesn't stampede in lockstep,
  - an exception *allowlist* (``retry_on``) plus a *denylist*
    (``non_retryable``): only failures that can plausibly be transient are
    retried, and programming errors (``ValueError``/``TypeError`` by
    default) re-raise on the first attempt even when an allowlisted base
    class would otherwise catch them — retrying a deterministic bug only
    burns the attempt budget and delays the traceback,
  - an optional *total deadline* (``total_timeout_s``) across all attempts:
    stacked backoff must not outlive an external grace window (the spot
    preemption SIGTERM→SIGKILL gap, an elastic peer-loss emergency save),
    so when the NEXT backoff sleep would cross the deadline the policy
    stops retrying and re-raises the last failure — classified like the
    non-retryable path, plus a ``retry_deadline_exceeded`` counter,
  - injectable ``sleep``/``rng``/``clock`` so tests assert the exact delay
    sequence and deadline arithmetic without waiting on a wall clock.
"""
from __future__ import annotations

import functools
import logging
import random
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["Retry"]


class Retry:
    """Callable retry policy: use as ``policy.call(fn, ...)`` or ``@policy``."""

    def __init__(
        self,
        attempts: int = 3,
        backoff: float = 0.25,
        max_backoff: float = 8.0,
        jitter: float = 0.25,
        retry_on: Tuple[Type[BaseException], ...] = (OSError,),
        non_retryable: Tuple[Type[BaseException], ...] = (ValueError, TypeError),
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        logger: Optional[logging.Logger] = None,
        total_timeout_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        if backoff < 0 or max_backoff < 0:
            raise ValueError(
                f"backoff/max_backoff must be >= 0, got {backoff}/{max_backoff}"
            )
        if not (0.0 <= jitter <= 1.0):
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        if total_timeout_s is not None and total_timeout_s <= 0:
            raise ValueError(
                f"total_timeout_s must be > 0, got {total_timeout_s}"
            )
        self.attempts = int(attempts)
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)
        self.retry_on = tuple(retry_on)
        self.non_retryable = tuple(non_retryable)
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self._logger = logger
        self.total_timeout_s = (
            float(total_timeout_s) if total_timeout_s is not None else None
        )
        self._clock = clock

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (0-based failed attempt)."""
        base = min(self.backoff * (2.0 ** attempt), self.max_backoff)
        return base * (1.0 + self.jitter * self._rng.random())

    def call(self, fn: Callable, *args, on_retry: Optional[Callable] = None, **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying allowlisted failures.

        ``on_retry(attempt, exc, delay)`` fires before each backoff sleep
        (counter hooks); the final failure always re-raises the original
        exception.  With ``total_timeout_s`` set, a retry whose backoff
        sleep would land past the deadline is abandoned instead: the last
        failure re-raises immediately (``retry_deadline_exceeded``), never
        sleeping beyond the budget.
        """
        deadline = (
            self._clock() + self.total_timeout_s
            if self.total_timeout_s is not None else None
        )
        for attempt in range(self.attempts):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as exc:
                if isinstance(exc, self.non_retryable):
                    # deterministic failure (bad argument, wrong type):
                    # retrying cannot help, surface it immediately
                    raise
                if attempt == self.attempts - 1:
                    self._count("retry_exhausted")
                    raise
                d = self.delay(attempt)
                if deadline is not None and self._clock() + d > deadline:
                    self._count("retry_deadline_exceeded")
                    if self._logger is not None:
                        self._logger.warning(
                            "%s failed (attempt %d/%d): %s — next backoff "
                            "%.2fs would exceed the %.2fs total budget, "
                            "abandoning retries",
                            getattr(fn, "__name__", "call"),
                            attempt + 1, self.attempts, exc, d,
                            self.total_timeout_s,
                        )
                    raise
                self._count("retry_attempts")
                if on_retry is not None:
                    on_retry(attempt, exc, d)
                if self._logger is not None:
                    self._logger.warning(
                        "%s failed (attempt %d/%d): %s — retrying in %.2fs",
                        getattr(fn, "__name__", "call"),
                        attempt + 1, self.attempts, exc, d,
                    )
                self._sleep(d)

    @staticmethod
    def _count(name: str) -> None:
        """Mirror retry traffic into the process telemetry registry.

        ``retry_attempts`` counts retried failures (not first tries);
        ``retry_exhausted`` counts budget exhaustions.  Lazy import keeps
        this module importable standalone, matching engine/fault.py.
        """
        from ..telemetry.registry import get_registry

        get_registry().counter(name).inc()

    def __call__(self, fn: Callable) -> Callable:
        """Decorator form: ``@Retry(...)`` wraps ``fn`` with ``call``."""

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        return wrapped
