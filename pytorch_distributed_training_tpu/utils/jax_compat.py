"""Opt-in graft of newer-JAX surface onto an older install, in-repo only.

The codebase targets the toolchain's grafted JAX API: ``jax.shard_map``
as a top-level export with a ``check_vma=`` kwarg.  On a vanilla
jax<=0.4.x install (the CPU dev image, which lacks the toolchain graft)
that name never left ``jax.experimental.shard_map`` and the kwarg is
spelled ``check_rep`` — every shard_map-based step dies with
``AttributeError: module 'jax' has no attribute 'shard_map'`` before a
single op runs.

``install()`` bridges the gap WITHOUT touching site-packages: when
``jax.shard_map`` already exists it is a strict no-op; otherwise, IF the
environment sets ``PDT_JAX_COMPAT=1``, it publishes a thin wrapper
around the experimental entry point.  Two deliberate design points:

- **Opt-in, not automatic.**  Pre-vma shard_map has DIFFERENT autodiff
  semantics for in-body collectives: ``grad`` through a body-internal
  ``pmean/psum`` yields the per-device LOCAL cotangent (old transpose
  rules), where the vma-typed shard_map yields the replicated mean this
  codebase's DP/SP steps are written against.  On a multi-device mesh a
  compat-mode training step therefore computes WRONG gradients — a
  silently-diverging run is far worse than the loud AttributeError, so
  the graft never turns itself on.  Single-device meshes are exempt from
  the caveat (collectives over an axis of size 1 are identity, and the
  identity's transpose is exact), which is what makes compat mode useful
  at all: single-device CPU smoke runs of the real step/bench code are
  numerically trustworthy end to end.
- **An alias, not a vendored implementation.**  On the real toolchain
  the grafted ``jax.shard_map`` wins untouched, so chip behavior can
  never diverge from what the driver benches.  ``check_vma`` is dropped
  and ``check_rep`` forced off because the old static replication
  checker rejects valid programs the vma type system accepts (e.g. the
  DP train step's pmean'd gradients).
"""
from __future__ import annotations

import functools
import os

import jax

__all__ = ["install"]


def install() -> None:
    """Publish ``jax.shard_map`` when missing and ``PDT_JAX_COMPAT=1``."""
    if hasattr(jax, "shard_map"):  # grafted/new JAX: nothing to do
        return
    if os.environ.get("PDT_JAX_COMPAT") != "1":
        return
    try:
        from jax.experimental.shard_map import shard_map as _exp_shard_map
    except ImportError:  # pragma: no cover - no known JAX hits this
        return

    @functools.wraps(_exp_shard_map)
    def shard_map(*args, **kwargs):
        kwargs.pop("check_vma", None)
        kwargs["check_rep"] = False
        # new API spells "manual over these axes, auto over the rest" as
        # axis_names={...}; the experimental entry point spells the same
        # thing as the complement, auto={rest of the mesh axes}
        axis_names = kwargs.pop("axis_names", None)
        if axis_names is not None:
            mesh = kwargs.get("mesh", args[1] if len(args) > 1 else None)
            kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _exp_shard_map(*args, **kwargs)

    jax.shard_map = shard_map
