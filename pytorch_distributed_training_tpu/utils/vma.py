"""shard_map varying-manual-axes (vma) compatibility shim.

JAX's API for promoting a mesh-invariant value to "varying over axis"
moved between versions (``jax.lax.pvary`` -> ``jax.lax.pcast(...,
to='varying')``).  Both the ring-attention collective and the Pallas fused
CE need it; this is the single shared implementation so the two can't drift
onto different code paths.
"""
from __future__ import annotations

from typing import Sequence

import jax

__all__ = ["mark_varying", "varying_axes_of"]


def mark_varying(tree, axis_names: Sequence[str]):
    """Mark every array in ``tree`` as varying over ``axis_names``.

    Idempotent: axes a leaf is ALREADY varying over are skipped (``pcast``
    rejects re-marking).  No-op when ``axis_names`` is empty or the running
    JAX predates vma typing (neither API exists).
    """
    axes = tuple(axis_names)
    if not axes:
        return tree

    def missing(x):
        return tuple(a for a in axes if a not in varying_axes_of(x))

    if hasattr(jax.lax, "pcast"):
        return jax.tree.map(
            lambda x: jax.lax.pcast(x, m, to="varying")
            if (m := missing(x))
            else x,
            tree,
        )
    if hasattr(jax.lax, "pvary"):  # pre-pcast JAX
        return jax.tree.map(
            lambda x: jax.lax.pvary(x, m) if (m := missing(x)) else x, tree
        )
    return tree


def varying_axes_of(x, default=()):
    """The mesh axes ``x`` is varying over (empty outside shard_map)."""
    try:
        return tuple(sorted(jax.typeof(x).vma))
    except (AttributeError, TypeError):
        return tuple(default)
