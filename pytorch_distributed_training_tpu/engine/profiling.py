"""Config-gated ``jax.profiler`` trace hooks.

The reference has no profiling subsystem at all (SURVEY.md §5.1: only tqdm
progress bars + an ineffective ``cudnn.benchmark`` toggle); the rebuild adds
the TPU-native one: an XLA trace window captured with ``jax.profiler`` that
can be opened in TensorBoard / Perfetto (HLO timelines, per-op HBM + MXU
utilization).  Config-gated so default behavior matches the reference:

.. code-block:: yaml

    training:
      profile:
        dir: run/profile     # trace output directory (required)
        start_iter: 10       # window opens after this iteration completes,
                             # so iterations start_iter+1 .. start_iter+n_iters
                             # are traced (default 10: skips the XLA-compile
                             # iterations, which would dwarf the timeline)
        n_iters: 5           # number of traced iterations (default 5)

The Runner calls :meth:`after_step` once per iteration on the rank-0 host
only.  Validation and checkpoint I/O force-close the window so only
steady-state train steps land in the trace; if that close happens before any
traced iteration completed, the window re-arms and retries after the
interruption (a partial window logs a warning instead).
"""
from __future__ import annotations

import logging
from collections.abc import Mapping
from typing import Any, Dict, Optional

__all__ = ["TraceProfiler"]


class TraceProfiler:
    """One bounded ``jax.profiler`` trace window over the training loop."""

    def __init__(self, directory: str, start_iter: int = 10, n_iters: int = 5,
                 logger: Optional[logging.Logger] = None):
        if n_iters <= 0:
            raise ValueError(f"profile.n_iters must be positive, got {n_iters}")
        self.directory = directory
        self.start_iter = int(start_iter)
        self.n_iters = int(n_iters)
        self._active = False
        self._done = False
        self._log = logger or logging.getLogger(__name__)

    @classmethod
    def from_config(
        cls, train_cfg: Dict[str, Any], logger: Optional[logging.Logger] = None
    ) -> Optional["TraceProfiler"]:
        """Build from the ``training.profile`` config section (None if absent)."""
        prof_cfg = train_cfg.get("profile")
        if prof_cfg is None or prof_cfg is False:
            return None
        # an empty mapping is a *misconfiguration* (user enabled the section
        # but gave no keys) — fall through so the 'dir' check raises
        if not isinstance(prof_cfg, Mapping):
            raise ValueError(
                f"training.profile must be a mapping with a 'dir' key, got {prof_cfg!r}"
            )
        if "dir" not in prof_cfg:
            raise ValueError("training.profile.dir is required when profiling is enabled")
        return cls(
            directory=prof_cfg["dir"],
            start_iter=prof_cfg.get("start_iter", 10),
            n_iters=prof_cfg.get("n_iters", 5),
            logger=logger,
        )

    def after_step(self, iteration: int, sync=None) -> None:
        """Open/close the trace window; called once AFTER each iteration, so
        opening when ``iteration == start_iter`` traces iterations
        ``start_iter+1 .. start_iter+n_iters`` inclusive.

        ``sync``: optional pytree of device arrays (e.g. the train state) to
        ``block_until_ready`` on at the window boundaries — required for the
        trace to actually contain the device timeline, since the steady-state
        loop never otherwise syncs (JAX dispatch is async; without the barrier
        ``stop_trace`` could fire while the traced steps are still enqueued).
        Blocking happens only at the two boundary crossings, not per step.
        """
        import jax

        if self._done:
            return
        if not self._active and iteration >= self.start_iter:
            if sync is not None:
                jax.block_until_ready(sync)  # keep prior async work out of the window
            jax.profiler.start_trace(self.directory)
            self._active = True
            self._opened_at = iteration
            self._last_step = iteration
            self._log.info("profiler: trace started after iter %d -> %s",
                           iteration, self.directory)
        elif self._active:
            self._last_step = iteration
            if iteration >= self._opened_at + self.n_iters:
                self.stop(sync=sync)

    def stop(self, sync=None) -> None:
        """Close the window if open — also called before validation/checkpoint
        work so only steady-state train iterations land in the trace.  An early
        close that captured ZERO iterations discards the window and re-arms it
        (retry after the interruption); a partial capture logs a warning."""
        import jax

        if not self._active:
            return
        if sync is not None:
            jax.block_until_ready(sync)
        jax.profiler.stop_trace()
        self._active = False
        captured = self._last_step - self._opened_at
        if captured <= 0:
            # e.g. validation fired at the very iteration the window opened:
            # nothing traced yet — wait for the next quiet iteration instead
            self._log.warning(
                "profiler: window closed before any iteration was traced; "
                "re-arming (will retry after the interruption)"
            )
            return
        self._done = True
        if captured < self.n_iters:
            self._log.warning(
                "profiler: window closed early: %d of %d iterations captured -> %s",
                captured, self.n_iters, self.directory,
            )
        else:
            self._log.info("profiler: trace stopped -> %s", self.directory)

    def finalize(self) -> None:
        """Loop-exit hook: close any open window and warn if the configured
        window never produced a trace (e.g. ``start_iter >= train_iters``)."""
        self.stop()
        if not self._done:
            self._log.warning(
                "profiler: no trace captured (start_iter=%d never reached or "
                "every window was interrupted) -> %s",
                self.start_iter, self.directory,
            )
