"""Config-gated ``jax.profiler`` trace hooks.

The reference has no profiling subsystem at all (SURVEY.md §5.1: only tqdm
progress bars + an ineffective ``cudnn.benchmark`` toggle); the rebuild adds
the TPU-native one: an XLA trace window captured with ``jax.profiler`` that
can be opened in TensorBoard / Perfetto (HLO timelines, per-op HBM + MXU
utilization).  Config-gated so default behavior matches the reference:

.. code-block:: yaml

    training:
      profile:
        dir: run/profile     # trace output directory (required)
        start_iter: 10       # window opens after this iteration completes,
                             # so iterations start_iter+1 .. start_iter+n_iters
                             # are traced (default 10: skips the XLA-compile
                             # iterations, which would dwarf the timeline)
        n_iters: 5           # number of traced iterations (default 5)

The Runner calls :meth:`after_step` once per iteration on the rank-0 host
only.  Validation and checkpoint I/O force-close the window so only
steady-state train steps land in the trace; if that close happens before any
traced iteration completed, the window re-arms and retries after the
interruption (a partial window logs a warning instead).
"""
from __future__ import annotations

import logging
from collections.abc import Mapping
from typing import Any, Dict, Optional

__all__ = ["TraceProfiler", "decompose_lm_step"]


class TraceProfiler:
    """One bounded ``jax.profiler`` trace window over the training loop."""

    def __init__(self, directory: str, start_iter: int = 10, n_iters: int = 5,
                 logger: Optional[logging.Logger] = None):
        if n_iters <= 0:
            raise ValueError(f"profile.n_iters must be positive, got {n_iters}")
        self.directory = directory
        self.start_iter = int(start_iter)
        self.n_iters = int(n_iters)
        self._active = False
        self._done = False
        self._log = logger or logging.getLogger(__name__)

    @classmethod
    def from_config(
        cls, train_cfg: Dict[str, Any], logger: Optional[logging.Logger] = None
    ) -> Optional["TraceProfiler"]:
        """Build from the ``training.profile`` config section (None if absent)."""
        prof_cfg = train_cfg.get("profile")
        if prof_cfg is None or prof_cfg is False:
            return None
        # an empty mapping is a *misconfiguration* (user enabled the section
        # but gave no keys) — fall through so the 'dir' check raises
        if not isinstance(prof_cfg, Mapping):
            raise ValueError(
                f"training.profile must be a mapping with a 'dir' key, got {prof_cfg!r}"
            )
        if "dir" not in prof_cfg:
            raise ValueError("training.profile.dir is required when profiling is enabled")
        return cls(
            directory=prof_cfg["dir"],
            start_iter=prof_cfg.get("start_iter", 10),
            n_iters=prof_cfg.get("n_iters", 5),
            logger=logger,
        )

    def after_step(self, iteration: int, sync=None) -> None:
        """Open/close the trace window; called once AFTER each iteration, so
        opening when ``iteration == start_iter`` traces iterations
        ``start_iter+1 .. start_iter+n_iters`` inclusive.

        ``sync``: optional pytree of device arrays (e.g. the train state) to
        ``block_until_ready`` on at the window boundaries — required for the
        trace to actually contain the device timeline, since the steady-state
        loop never otherwise syncs (JAX dispatch is async; without the barrier
        ``stop_trace`` could fire while the traced steps are still enqueued).
        Blocking happens only at the two boundary crossings, not per step.
        """
        import jax

        if self._done:
            return
        if not self._active and iteration >= self.start_iter:
            if sync is not None:
                jax.block_until_ready(sync)  # keep prior async work out of the window
            jax.profiler.start_trace(self.directory)
            self._active = True
            self._opened_at = iteration
            self._last_step = iteration
            self._log.info("profiler: trace started after iter %d -> %s",
                           iteration, self.directory)
        elif self._active:
            self._last_step = iteration
            if iteration >= self._opened_at + self.n_iters:
                self.stop(sync=sync)

    def stop(self, sync=None) -> None:
        """Close the window if open — also called before validation/checkpoint
        work so only steady-state train iterations land in the trace.  An early
        close that captured ZERO iterations discards the window and re-arms it
        (retry after the interruption); a partial capture logs a warning."""
        import jax

        if not self._active:
            return
        if sync is not None:
            jax.block_until_ready(sync)
        jax.profiler.stop_trace()
        self._active = False
        captured = self._last_step - self._opened_at
        if captured <= 0:
            # e.g. validation fired at the very iteration the window opened:
            # nothing traced yet — wait for the next quiet iteration instead
            self._log.warning(
                "profiler: window closed before any iteration was traced; "
                "re-arming (will retry after the interruption)"
            )
            return
        self._done = True
        if captured < self.n_iters:
            self._log.warning(
                "profiler: window closed early: %d of %d iterations captured -> %s",
                captured, self.n_iters, self.directory,
            )
        else:
            self._log.info("profiler: trace stopped -> %s", self.directory)

    def finalize(self) -> None:
        """Loop-exit hook: close any open window and warn if the configured
        window never produced a trace (e.g. ``start_iter >= train_iters``)."""
        self.stop()
        if not self._done:
            self._log.warning(
                "profiler: no trace captured (start_iter=%d never reached or "
                "every window was interrupted) -> %s",
                self.start_iter, self.directory,
            )


# ---------------------------------------------------------------------------
# Programmatic step-time decomposition (``bench.py decompose``)
# ---------------------------------------------------------------------------
#
# The TensorBoard trace above answers "what does iteration N look like" for a
# human; it cannot drive an optimization loop.  ``decompose_lm_step`` answers
# the machine-readable version: it re-times each component family of the LM
# training step as an ISOLATED compiled probe at the step's exact shapes —
# the same modules (same flash-attention dispatch, same Pallas CE kernel,
# same optimizer tree-map) with the surrounding step stripped away — and
# buckets the full step time against those probe times.  Each probe chains
# ``iters`` fwd+bwd executions inside one compiled ``fori_loop`` (gradients
# folded into the carry so DCE cannot drop the backward) and syncs once via
# scalar materialization, the same anti-async discipline as bench.py.
#
# The bucket sums are NORMALIZED to the measured step time: isolated probes
# both undercount (no overlap constraints, better fusion in isolation) and
# overcount (no inter-component fusion), so the raw sum lands near — not at —
# step_ms.  ``raw_ms`` keeps the unscaled measurements honest; ``buckets``
# rescales proportionally when the raw sum overflows step_ms and otherwise
# assigns the shortfall to ``host_infeed`` (dispatch gaps + infeed stall —
# everything the device probes cannot see).  By construction the published
# buckets sum to step_ms exactly.


def _scalar_sync(tree) -> float:
    """Force execution of everything ``tree`` depends on.

    Host materialization of one element, not ``block_until_ready`` — the
    latter has been observed returning early through the remote-device
    transport (bench.py's ~250x under-report pathology)."""
    import jax

    leaf = jax.tree_util.tree_leaves(tree)[0]
    return float(leaf.ravel()[0])


def _timed_ms(many, carry, iters: int, windows: int) -> float:
    """Best-of-``windows`` device ms per fori iteration of ``many(carry)``."""
    import time

    _scalar_sync(many(carry))  # compile + warm outside the timed windows
    best = None
    for _ in range(max(1, windows)):
        t0 = time.perf_counter()
        _scalar_sync(many(carry))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best / iters * 1e3


def _grad_chain(loss_fn, params, x, iters: int, n_rep: int = 1):
    """Compiled probe: ``iters`` fori iterations, each running ``n_rep``
    sequential fwd+bwd passes of ``loss_fn(params, x)`` with the gradients
    folded back into the carry (params AND activations — dropping either
    would let XLA dead-code the corresponding backward matmuls)."""
    import jax
    import jax.numpy as jnp

    grad = jax.grad(loss_fn, argnums=(0, 1))

    @jax.jit
    def many(carry):
        def body(_, c):
            p, a = c
            for _ in range(n_rep):
                dp, da = grad(p, a)
                p = jax.tree_util.tree_map(
                    lambda w, g: w - jnp.asarray(1e-12, w.dtype) * g.astype(w.dtype),
                    p, dp,
                )
                a = a + jnp.asarray(1e-12, a.dtype) * da.astype(a.dtype)
            return (p, a)

        return jax.lax.fori_loop(0, iters, body, carry)

    return many, (params, x)


def _sq_loss(y) -> "Any":
    """f32 sum-of-squares over a pytree — the probe objective (cheap, dense
    cotangents everywhere, dtype-safe for bf16 outputs)."""
    import jax
    import jax.numpy as jnp

    return sum(
        (leaf.astype(jnp.float32) ** 2).sum()
        for leaf in jax.tree_util.tree_leaves(y)
    )


def decompose_lm_step(
    lm,
    optimizer,
    params,
    opt_state,
    tokens,
    labels,
    step_ms: float,
    *,
    lr: float = 3e-4,
    iters: int = 10,
    windows: int = 3,
    ema=None,
    ema_decay: Optional[float] = None,
) -> Dict[str, Any]:
    """Decompose one LM training step into component-family buckets (ms).

    Args:
      lm: the :class:`~..models.transformer_lm.TransformerLM` the step runs
        (its fields pin the probe shapes and the fused/remat configuration).
      optimizer / params / opt_state: the live objects from the step — the
        optimizer probe times the REAL update (fused or per-leaf) on the
        real tree.
      tokens / labels: one step's ``[B, S]`` int32 batch (labels feed the
        CE probe so the Pallas fused-CE dispatch matches the step).
      step_ms: the measured full-step time to decompose against.
      ema / ema_decay: pass the step's EMA tree + decay so the optimizer
        bucket includes the smoothing update exactly as the step runs it
        (fused fold or post-hoc tree-map).

    Returns a JSON-ready dict: ``buckets`` (attention / mlp_matmul /
    elementwise / ce_softmax / optimizer / host_infeed — sums to ``step_ms``
    exactly), ``raw_ms`` (unscaled probe times), ``residual_ms`` (signed
    ``step_ms - sum(raw)``; negative = probes overlap-overcount),
    ``overlap_factor`` (``sum(raw) / step_ms``).
    """
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from ..models.vit import MLP
    from ..ops import cross_entropy_loss
    from ..ops.attention import MultiHeadAttention

    batch, seq = tokens.shape
    embed, depth = lm.embed_dim, lm.depth
    rng = jax.random.PRNGKey(0)
    x0 = jax.random.normal(rng, (batch, seq, embed), lm.dtype)

    # -- attention: depth x MHA (qkv/out projections + causal core) --------
    mha = MultiHeadAttention(
        num_heads=lm.num_heads, causal=True, dtype=lm.dtype,
        flash_mesh=lm.flash_mesh,
    )
    p_attn = mha.init(rng, x0)["params"]
    many, carry = _grad_chain(
        lambda p, x: _sq_loss(mha.apply({"params": p}, x)),
        p_attn, x0, iters, n_rep=depth,
    )
    attention_ms = _timed_ms(many, carry, iters, windows)

    # -- MLP matmuls: depth x (fc1 + gelu + fc2), fused-tails aware --------
    mlp = MLP(
        hidden=int(embed * lm.mlp_ratio), out=embed, dtype=lm.dtype,
        fused_tails=lm.fused_tails,
    )
    p_mlp = mlp.init(rng, x0)["params"]
    many, carry = _grad_chain(
        lambda p, x: _sq_loss(mlp.apply({"params": p}, x)),
        p_mlp, x0, iters, n_rep=depth,
    )
    mlp_ms = _timed_ms(many, carry, iters, windows)

    # -- layernorm / residual / elementwise tails --------------------------
    # The block skeleton with attention and the MLP replaced by identity:
    # every op here exists in the real program (ln1, residual add, ln2,
    # residual add, per block; final ln) and vice versa — except the one
    # pos-embedding add, noise next to 5*depth [B,S,E] ops.
    class _ElemProbe(nn.Module):
        depth: int
        fused: bool
        dtype: Any

        @nn.compact
        def __call__(self, x):
            if self.fused:
                from ..ops.fused_elementwise import FusedResidualLayerNorm
            for i in range(self.depth):
                y = nn.LayerNorm(dtype=self.dtype, name=f"ln1_{i}")(x)
                if self.fused:
                    x, y2 = FusedResidualLayerNorm(
                        dtype=self.dtype, name=f"ln2_{i}")(x, y)
                else:
                    x = x + y
                    y2 = nn.LayerNorm(dtype=self.dtype, name=f"ln2_{i}")(x)
                x = x + y2
            return nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)

    elem = _ElemProbe(depth=depth, fused=lm.fused_tails, dtype=lm.dtype)
    p_elem = elem.init(rng, x0)["params"]
    many, carry = _grad_chain(
        lambda p, x: _sq_loss(elem.apply({"params": p}, x)),
        p_elem, x0, iters,
    )
    elementwise_ms = _timed_ms(many, carry, iters, windows)

    # -- CE + softmax (incl. the untied head projection [E, V]) ------------
    head = nn.Dense(lm.vocab_size, dtype=jnp.float32)
    p_head = head.init(rng, x0)["params"]
    flat_labels = labels.reshape(-1)

    def ce_loss(p, x):
        logits = head.apply({"params": p}, x)
        return cross_entropy_loss(
            logits.reshape(-1, lm.vocab_size), flat_labels
        )

    many, carry = _grad_chain(ce_loss, p_head, x0, iters)
    ce_ms = _timed_ms(many, carry, iters, windows)

    # -- optimizer (+ EMA) update: the real update on the real tree --------
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, 1e-6), params
    )
    fold_ema = ema_decay is not None and getattr(optimizer, "fused", False)

    @jax.jit
    def opt_many(carry):
        def body(_, c):
            p, s, e = c
            if fold_ema:
                p, s, e = optimizer.update_with_ema(
                    grads, s, p, lr, e, float(ema_decay)
                )
            else:
                p, s = optimizer.update(grads, s, p, lr)
                if ema_decay is not None:
                    d = float(ema_decay)
                    e = jax.tree_util.tree_map(
                        lambda a, b: d * a + (1.0 - d) * b, e, p
                    )
            return (p, s, e)

        return jax.lax.fori_loop(0, iters, body, carry)

    ema0 = ema if ema is not None else params
    optimizer_ms = _timed_ms(opt_many, (params, opt_state, ema0), iters, windows)

    raw = {
        "attention": attention_ms,
        "mlp_matmul": mlp_ms,
        "elementwise": elementwise_ms,
        "ce_softmax": ce_ms,
        "optimizer": optimizer_ms,
    }
    raw_sum = sum(raw.values())
    residual = step_ms - raw_sum
    if residual >= 0:
        buckets = dict(raw)
        buckets["host_infeed"] = residual
    else:
        # probes overcount (isolation lost overlap/fusion): rescale so the
        # published decomposition still partitions the step exactly
        scale = step_ms / raw_sum
        buckets = {k: v * scale for k, v in raw.items()}
        buckets["host_infeed"] = 0.0
    return {
        "step_ms": round(step_ms, 3),
        "buckets": {k: round(v, 3) for k, v in buckets.items()},
        "raw_ms": {k: round(v, 3) for k, v in raw.items()},
        "residual_ms": round(residual, 3),
        "overlap_factor": round(raw_sum / step_ms, 3) if step_ms else None,
        "iters": iters,
        "windows": windows,
    }
