"""Compiled tensor-parallel (DP x TP) LM training step via GSPMD.

Unlike the shard_map-based DP/SP steps (steps.py / sp_steps.py), this step
is written as straight-line single-device math and parallelized entirely by
sharding annotations: params carry the Megatron-style ``model``-axis specs
from :mod:`..parallel.tensor`, the batch is sharded over ``data``, and the
XLA SPMD partitioner inserts every collective (gradient all-reduce over
data, partial-sum all-reduce after the row-parallel matmuls, resharding at
boundaries).  This is the scaling-book recipe verbatim: pick a mesh,
annotate, let XLA do the communication scheduling.

The same :class:`TransformerLM` module (seq_axis=None) is used — TP here
composes with DP, and — on a 3-D ``(data, sequence, model)`` mesh
(``parallel.make_3d_mesh``) — with GSPMD sequence parallelism too: token
inputs shard over BOTH the data and sequence axes and the partitioner
inserts the sequence resharding around attention (DeepSpeed-Ulysses-style
all-to-alls fall out of the sharding propagation).  The shard_map-based
ring-attention path (``sp_steps``) remains the memory-optimal choice for
SP-only long-context runs; this GSPMD path is what composes all three
axes in one program.
"""
from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import cross_entropy_loss
from ..parallel.mesh import DATA_AXIS
from ..parallel.sequence import SEQUENCE_AXIS
from ..parallel.tensor import tp_state_shardings
from ..telemetry.retrace import register_compiled
from .steps import TrainState


def _token_spec(mesh: Mesh) -> P:
    """Tokens shard over data (+ sequence, when the mesh carries that axis)."""
    if SEQUENCE_AXIS in mesh.axis_names:
        return P(DATA_AXIS, SEQUENCE_AXIS)
    return P(DATA_AXIS, None)

__all__ = ["build_tp_lm_train_step", "build_tp_lm_eval_step"]

# Step-family label for the static collective-order oracle (see
# analysis/collectives.py and PERF.md).  The TP path is GSPMD-compiled:
# collectives are inserted by the partitioner, so the static extraction
# legitimately reports zero explicit collectives for this family.
PDT_COLLECTIVE_FAMILY = "tp"


def build_tp_lm_train_step(
    model,
    optimizer,
    lr_fn: Callable,
    mesh: Mesh,
    donate: bool = True,
    label_smoothing: float = 0.0,
    zero: int = 0,
    grad_accum: int = 1,
):
    """Compile one DP x TP LM iteration (GSPMD-partitioned).

    ``model`` must be a :class:`TransformerLM` with ``seq_axis=None`` (the
    partitioner, not the module, distributes the math).  Use
    :func:`..parallel.tensor.tp_state_shardings` to place the state before
    the first call; in/out shardings are pinned so XLA keeps params resident
    in their TP layout across steps.

    ``grad_accum``: process the batch as N sequential micro-batches under
    ``lax.scan`` (activation memory / N).  Equal micro sizes make the mean
    of per-micro mean losses the exact full-batch objective; for MoE the
    aux loss (and routing capacity) is likewise per-micro — the average of
    per-micro aux terms, the standard accumulation semantics.

    ``zero``: 0/False = mirrored optimizer state; 1/True = ZeRO-1 (moments
    sharded over ``data``; the partitioner reduce-scatters grads into the
    sharded update and all-gathers fresh params); 2 = ZeRO-2 — additionally
    pins GRADIENT buffers to the same sharded layout via
    ``with_sharding_constraint``, so each device holds only its 1/N grad
    slice (and, under ``grad_accum``, a 1/N accumulator carried across
    micro-batches) instead of a replicated full-gradient tree.  The update
    math is identical in all three modes.
    """
    import jax.numpy as jnp

    from ..parallel.tensor import zero_grad_shardings

    zero = int(zero)
    # Hand the model the mesh so attention runs the Pallas flash kernel in
    # a shard_map island (ops/attention.py) — a bare pallas_call has no
    # GSPMD partitioning rule, so without this every TP/ZeRO/FSDP/MoE step
    # paid O(S^2) einsum attention (VERDICT r4 weak #3).  clone() changes
    # static config only; param shapes are untouched.
    if hasattr(model, "flash_mesh") and model.flash_mesh is None:
        model = model.clone(flash_mesh=mesh)

    def shard_grads(grads):
        """ZeRO-2: reduce-scatter gradients into their 1/N home slices."""
        return jax.lax.with_sharding_constraint(
            grads, zero_grad_shardings(grads, mesh)
        )

    def loss_fn(p, tokens, labels):
        # mutable="intermediates" collects sown auxiliary objectives —
        # today the MoE load-balancing loss (ops/moe.py sows the
        # already-weighted value under ``moe_aux``); dense models sow
        # nothing.  Only ``moe_aux`` entries join the objective: other
        # sown intermediates (telemetry, debugging) must NOT leak into
        # the loss (r2 code-review finding).  Validation stays pure CE.
        logits, inter = model.apply(
            {"params": p}, tokens, mutable="intermediates"
        )
        vocab = logits.shape[-1]
        loss = cross_entropy_loss(
            logits.reshape(-1, vocab), labels.reshape(-1), label_smoothing
        )
        for path, leaf in jax.tree_util.tree_flatten_with_path(inter)[0]:
            if any(
                str(getattr(key, "key", key)) == "moe_aux" for key in path
            ):
                loss = loss + leaf
        return loss

    def step(state: TrainState, tokens, labels):
        if grad_accum > 1:
            b, seq = tokens.shape
            if b % grad_accum != 0:
                raise ValueError(
                    f"global batch {b} not divisible by grad_accumulation "
                    f"{grad_accum}"
                )
            micro = b // grad_accum
            # keep each micro-batch sharded exactly like the full batch
            # (data [+ sequence] on the row dim) — without the constraint
            # the partitioner may shard the scan axis instead, serializing
            # the data parallelism
            micro_spec = P(None, *_token_spec(mesh))
            tok = jax.lax.with_sharding_constraint(
                tokens.reshape(grad_accum, micro, seq),
                NamedSharding(mesh, micro_spec),
            )
            lab = jax.lax.with_sharding_constraint(
                labels.reshape(grad_accum, micro, seq),
                NamedSharding(mesh, micro_spec),
            )
            zero_g = jax.tree.map(jnp.zeros_like, state.params)
            if zero >= 2:
                zero_g = shard_grads(zero_g)

            def scan_step(carry, xy):
                acc, loss_acc = carry
                loss, grads = jax.value_and_grad(loss_fn)(state.params, *xy)
                if zero >= 2:
                    # each micro's grads land in their 1/N slices BEFORE the
                    # add, keeping the carried accumulator sharded
                    grads = shard_grads(grads)
                return (jax.tree.map(jnp.add, acc, grads), loss_acc + loss), None

            (grads, loss_sum), _ = jax.lax.scan(
                scan_step, (zero_g, jnp.float32(0.0)), (tok, lab)
            )
            grads = jax.tree.map(lambda g: g / grad_accum, grads)
            loss = loss_sum / grad_accum
        else:
            loss, grads = jax.value_and_grad(loss_fn)(
                state.params, tokens, labels
            )
            if zero >= 2:
                grads = shard_grads(grads)
        lr = lr_fn(state.opt_state.step)
        # A `fused=True` optimizer composes with ZeRO here unchanged: this is
        # GSPMD (not shard_map), so the concatenated flat update buffers are
        # ordinary ops on sharded arrays and XLA's sharding propagation
        # chooses the layout — the cross-replica sharded weight update of
        # arXiv:2004.13336 expressed declaratively.  Bitwise parity with the
        # per-leaf path is pinned in tests/test_profiling.py (incl. a ZeRO-1
        # GSPMD case); whether concat beats per-leaf under ZeRO is a chip
        # measurement (`bench.py decompose`), not an assumption.
        new_params, new_opt = optimizer.update(grads, state.opt_state, state.params, lr)
        return (
            TrainState(
                params=new_params, batch_stats=state.batch_stats,
                opt_state=new_opt, ema=state.ema,
            ),
            loss,
        )

    def compile_for(state: TrainState):
        """jit with shardings derived from this state's structure."""
        state_sh = tp_state_shardings(state, mesh, zero=zero)
        tok_sh = NamedSharding(mesh, _token_spec(mesh))
        rep = NamedSharding(mesh, P())
        return register_compiled(
            "lm_train_step/tp",
            jax.jit(
                step,
                in_shardings=(state_sh, tok_sh, tok_sh),
                out_shardings=(state_sh, rep),
                donate_argnums=(0,) if donate else (),
            ),
        )

    return compile_for


def build_tp_lm_eval_step(model, mesh: Mesh, zero: int = 0):
    """Compile the TP LM validation step (GSPMD-partitioned).

    Same contract as the other eval steps — replicated ``(loss, acc1,
    acc5)``: mean CE per token + next-token top-1/top-5 — so
    ``Runner.validate`` drives it unchanged.  Like the train step, returns a
    ``compile_for(state)`` closure that pins the TP state shardings.
    """
    from ..metrics import accuracy

    # same flash-island mesh hint as the train step
    if hasattr(model, "flash_mesh") and model.flash_mesh is None:
        model = model.clone(flash_mesh=mesh)

    def step(state: TrainState, tokens, labels):
        logits = model.apply({"params": state.params}, tokens)
        vocab = logits.shape[-1]
        flat_logits = logits.reshape(-1, vocab)
        flat_labels = labels.reshape(-1)
        loss = cross_entropy_loss(flat_logits, flat_labels)
        acc1, acc5 = accuracy(flat_logits, flat_labels, topk=(1, 5))
        return loss, acc1, acc5

    def compile_for(state: TrainState):
        state_sh = tp_state_shardings(state, mesh, zero=zero)
        tok_sh = NamedSharding(mesh, _token_spec(mesh))
        rep = NamedSharding(mesh, P())
        return jax.jit(
            step,
            in_shardings=(state_sh, tok_sh, tok_sh),
            out_shardings=(rep, rep, rep),
        )

    return compile_for
