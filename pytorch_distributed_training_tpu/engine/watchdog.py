"""Hung-step watchdog: detect a stuck training step from a monitor thread.

A hung collective (one host dropped out of an allreduce, a wedged DMA) is
silent: the loop simply never returns from the step and the job burns its
reservation until an external timeout.  The watchdog observes host-visible
step wall time — ``step_started``/``step_finished`` bracket the loop body,
data fetch included — and fires when the live step exceeds
``max(min_seconds, factor * trailing-median step time)``.

On fire it logs a diagnostic dump via the injected ``on_hang`` callback
(the Runner reports step index, per-host identity, loader queue depths and
a faulthandler stack dump) and, when configured, requests checkpoint-and-
exit by setting the :class:`.preemption.PreemptionGuard` flag — reusing the
eviction path, which already saves at the current iteration and exits
cleanly across hosts.

The monitor never touches JAX: it reads two timestamps under a lock, so it
cannot deadlock with the runtime it is watching.  Arming requires a few
completed steps (``warmup``) so the first compile — minutes of legitimate
wall time — cannot false-fire.
"""
from __future__ import annotations

import logging
import statistics
import threading
import time
from collections import deque
from typing import Callable, Optional

__all__ = ["StepWatchdog"]


class StepWatchdog:
    """Monitor thread flagging steps that exceed the trailing step time.

    ``on_hang(step, elapsed, limit)`` fires at most once per step index.
    Use as a context manager or call :meth:`close` to stop the thread.
    """

    def __init__(
        self,
        factor: float = 10.0,
        min_seconds: float = 60.0,
        window: int = 32,
        warmup: int = 3,
        poll_seconds: Optional[float] = None,
        on_hang: Optional[Callable[[int, float, float], None]] = None,
        logger: Optional[logging.Logger] = None,
    ):
        if factor <= 1.0:
            raise ValueError(f"watchdog factor must be > 1, got {factor}")
        if min_seconds <= 0:
            raise ValueError(f"watchdog min_seconds must be > 0, got {min_seconds}")
        if warmup < 1:
            raise ValueError(f"watchdog warmup must be >= 1, got {warmup}")
        self.factor = float(factor)
        self.min_seconds = float(min_seconds)
        self.warmup = int(warmup)
        self.fires = 0  # guarded by: self._lock
        self.resets = 0  # guarded by: self._lock
        self._times: deque = deque(maxlen=int(window))  # guarded by: self._lock
        self._on_hang = on_hang
        self._logger = logger
        self._lock = threading.Lock()
        self._cur_step: Optional[int] = None  # guarded by: self._lock
        self._cur_start: float = 0.0  # guarded by: self._lock
        self._fired_for: Optional[int] = None  # guarded by: self._lock
        self._stop = threading.Event()
        self._poll = (
            float(poll_seconds)
            if poll_seconds is not None
            else max(self.min_seconds / 4.0, 0.02)
        )
        if self._poll <= 0:
            raise ValueError(f"watchdog poll_seconds must be > 0, got {self._poll}")
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ loop hooks
    def step_started(self, step: int) -> None:
        with self._lock:
            self._cur_step = int(step)
            self._cur_start = time.monotonic()

    def step_finished(self) -> None:
        with self._lock:
            if self._cur_step is None:
                return
            self._times.append(time.monotonic() - self._cur_start)
            self._cur_step = None

    def reset(self) -> None:
        """Forget trailing history and re-enter warmup.

        For EVERY restart/recovery path that resumes stepping against a
        cold pipeline — the serving hot restart, the training anomaly
        rollback, and an integrity-snapshot restore: the first post-restore
        steps legitimately take compile/replay-scale wall time, and judging
        them against the pre-fault median would turn the recovery itself
        into another false hang.  ``resets`` counts invocations so tests
        pin that recovery paths actually call this.
        """
        with self._lock:
            self._times.clear()
            self._cur_step = None
            self._fired_for = None
            self.resets += 1

    def trailing_median(self) -> Optional[float]:
        with self._lock:
            return statistics.median(self._times) if self._times else None

    # --------------------------------------------------------------- monitor
    def _limit(self) -> Optional[float]:  # guarded by: self._lock
        """Current hang threshold; None while unarmed (warming up)."""
        if len(self._times) < self.warmup:
            return None
        return max(self.min_seconds, self.factor * statistics.median(self._times))

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            with self._lock:
                step, start = self._cur_step, self._cur_start
                if step is None or step == self._fired_for:
                    continue
                limit = self._limit()
            if limit is None:
                continue
            elapsed = time.monotonic() - start
            if elapsed <= limit:
                continue
            with self._lock:
                # re-check under the lock: the step may have finished (or a
                # new one started) while we computed
                if self._cur_step != step or step == self._fired_for:
                    continue
                self._fired_for = step
                self.fires += 1
            if self._logger is not None:
                self._logger.error(
                    "watchdog: step %d running for %.2fs (limit %.2fs)",
                    step, elapsed, limit,
                )
            if self._on_hang is not None:
                try:
                    self._on_hang(step, elapsed, limit)
                except Exception:  # the monitor must survive its own dump
                    if self._logger is not None:
                        self._logger.exception("watchdog on_hang callback failed")

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "StepWatchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
