"""Elastic multi-host coordination: heartbeats, peer liveness, hang guard.

The multi-host failure mode PR 3's single-process fault-tolerance layer
cannot touch: a peer process dies (spot eviction, OOM kill, hardware fault)
and every survivor blocks FOREVER inside the next collective — the XLA
all-reduce simply never completes, the watchdog can only dump stacks, and
the job burns its remaining allocation doing nothing.  This module turns
that indefinite hang into a *diagnosed, bounded* failure:

  - every process writes a per-rank heartbeat file (JSON: rank, pid,
    generation, seq) into a shared directory every ``heartbeat_interval``
    seconds from a daemon thread — alive means "recently mtime-touched",
    independent of where the main thread is blocked;
  - ``check_peers()`` stats the peer files and raises :class:`PeerLostError`
    naming every peer whose heartbeat is staler than ``timeout`` (and the
    age it was last seen at) — called at the top of each training step,
    before the step's first collective is dispatched;
  - ``guard(fn)`` runs a blocking call (the step's device sync — the point
    where a dead peer's unfinished collective would wedge the host) on a
    side thread while the caller polls peer liveness: peer death mid-
    collective surfaces as the same ``PeerLostError`` within one timeout,
    never an indefinite hang;
  - a *generation counter* persisted in the heartbeat file increments each
    time a rank restarts into the same directory, so survivors can tell a
    rejoined peer from a stale file of a dead one (``peer_restarts``
    counter).

File mtime is the liveness clock (portable stat; on a shared filesystem
this assumes loosely synchronized host clocks — the same assumption the
checkpoint step numbering already makes).  Import-light on purpose
(stdlib only, like :mod:`.fault`): the runner passes its rank/world size
in, so tests can drive coordinators without a JAX distributed runtime.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from . import fault
from ..telemetry.spans import span

__all__ = ["ElasticCoordinator", "PeerLostError"]


class PeerLostError(RuntimeError):
    """A peer process's heartbeat went stale: it is presumed dead.

    Attributes:
      dead_ranks: ranks whose heartbeat exceeded the timeout (or never
        appeared within the startup grace window).
      mid_step: True when the loss was detected while this process was
        blocked inside a step's collective — the in-flight step's results
        are unrecoverable, so the emergency checkpoint path must not touch
        the current state (the last periodic checkpoint is the resume
        point instead).

    Subclasses extend the same contract to losses that are POLICY rather
    than silence: :class:`~.integrity.DivergedReplicaError` quarantines a
    persistently corrupt replica by exiting with the diagnosis — peers
    then observe that exit through this heartbeat layer as an ordinary
    peer loss and the relaunch reshapes around it, so the reshaped-resume
    machinery needs no corruption-specific branch.
    """

    def __init__(self, message: str, dead_ranks=(), mid_step: bool = False):
        super().__init__(message)
        self.dead_ranks = tuple(dead_ranks)
        self.mid_step = bool(mid_step)


class ElasticCoordinator:
    """Per-process heartbeat writer + peer-liveness detector."""

    def __init__(
        self,
        directory: str,
        process_index: int,
        num_processes: int,
        heartbeat_interval: float = 0.5,
        timeout: float = 5.0,
        startup_grace: Optional[float] = None,
        logger: Optional[logging.Logger] = None,
    ):
        if heartbeat_interval <= 0:
            raise ValueError(
                f"heartbeat_interval must be > 0, got {heartbeat_interval}"
            )
        if timeout <= heartbeat_interval:
            # a timeout within one beat period would flag live peers on any
            # scheduling hiccup — reject the footgun at construction
            raise ValueError(
                f"timeout ({timeout}) must exceed heartbeat_interval "
                f"({heartbeat_interval})"
            )
        self.directory = os.path.abspath(os.path.expanduser(directory))
        self.process_index = int(process_index)
        self.num_processes = int(num_processes)
        self.heartbeat_interval = float(heartbeat_interval)
        self.timeout = float(timeout)
        # peers that have not yet written a first beat are only "lost" once
        # the startup allowance passes (coordinator/service bring-up skew);
        # compile time is NOT in this window — the beat thread runs through it
        self.startup_grace = (
            float(startup_grace) if startup_grace is not None else
            max(30.0, 4.0 * self.timeout)
        )
        # _beat_lock orders the beat writers: the beat thread, and
        # start()/close() writing the first/last beat from the caller's
        # thread.  close() joins with a TIMEOUT, so the final stopped-beat
        # can genuinely overlap a still-live loop iteration — the lock is
        # load-bearing there, not decoration.
        self._beat_lock = threading.Lock()
        self.generation = 0  # guarded by: self._beat_lock
        self._logger = logger
        self._seq = 0  # guarded by: self._beat_lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at: Optional[float] = None
        self._peer_generations: Dict[int, int] = {}

    # ------------------------------------------------------------ heartbeat
    def _path(self, rank: int) -> str:
        return os.path.join(self.directory, f"heartbeat_{rank}.json")

    def _write_beat(self, stopped: bool = False) -> None:
        with self._beat_lock:
            payload = {
                "rank": self.process_index,
                "pid": os.getpid(),
                "generation": self.generation,
                "seq": self._seq,
                "time": time.time(),
                "stopped": stopped,
            }
            self._seq += 1
            tmp = self._path(self.process_index) + f".tmp{os.getpid()}"
            with open(tmp, "w") as fp:
                json.dump(payload, fp)
            os.replace(tmp, self._path(self.process_index))  # atomic vs readers

    def start(self) -> "ElasticCoordinator":
        """Write the first beat (bumping the generation past any previous
        incarnation's) and start the daemon beat thread."""
        os.makedirs(self.directory, exist_ok=True)
        prior = self._read(self._path(self.process_index))
        if prior is not None:
            with self._beat_lock:
                self.generation = int(prior.get("generation", -1)) + 1
                generation = self.generation
            if self._logger:
                self._logger.info(
                    "elastic: rank %d rejoining as generation %d",
                    self.process_index, generation,
                )
        self._started_at = time.monotonic()
        self._write_beat()
        self._thread = threading.Thread(
            target=self._beat_loop, name="elastic-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _beat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._write_beat()
            except OSError:  # transient shared-fs error: next beat retries
                pass

    def close(self) -> None:
        """Stop the beat thread and mark this rank's file cleanly stopped."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0 * self.heartbeat_interval + 1.0)
            self._thread = None
        try:
            self._write_beat(stopped=True)
        except OSError:
            pass

    # --------------------------------------------------------- peer liveness
    @staticmethod
    def _read(path: str) -> Optional[dict]:
        try:
            with open(path) as fp:
                return json.load(fp)
        except (OSError, ValueError):
            # missing, or caught mid-replace on a non-atomic network fs
            return None

    def check_peers(self, mid_step: bool = False) -> None:
        """Raise :class:`PeerLostError` if any peer's heartbeat is stale.

        A peer file older than ``timeout`` (by mtime) means the writer
        thread died — with the process.  A file that never appeared is only
        fatal after ``startup_grace``.  A generation bump on a live peer
        (it restarted into the same directory) is logged and counted, not
        an error.
        """
        if self.num_processes <= 1:
            return
        now = time.time()
        since_start = (
            time.monotonic() - self._started_at
            if self._started_at is not None else 0.0
        )
        dead = []
        for rank in range(self.num_processes):
            if rank == self.process_index:
                continue
            path = self._path(rank)
            try:
                age = now - os.stat(path).st_mtime
            except OSError:
                if since_start > self.startup_grace:
                    dead.append((rank, None))
                continue
            payload = self._read(path) or {}
            gen = int(payload.get("generation", 0))
            prev_gen = self._peer_generations.get(rank)
            if prev_gen is not None and gen > prev_gen:
                fault.bump("peer_restarts")
                if self._logger:
                    self._logger.info(
                        "elastic: peer rank %d restarted (generation %d -> %d)",
                        rank, prev_gen, gen,
                    )
            self._peer_generations[rank] = gen
            if age > self.timeout:
                dead.append((rank, age))
        if not dead:
            return
        parts = []
        for rank, age in dead:
            if age is None:
                parts.append(
                    f"rank {rank}: no heartbeat within {self.startup_grace:.1f}s "
                    "startup grace"
                )
            else:
                parts.append(f"rank {rank}: last heartbeat {age:.1f}s ago")
        raise PeerLostError(
            f"peer(s) presumed dead (heartbeat timeout {self.timeout:.1f}s, "
            f"dir {self.directory}): " + "; ".join(parts),
            dead_ranks=[r for r, _ in dead],
            mid_step=mid_step,
        )

    # ---------------------------------------------------------- hang guard
    def guard(self, fn: Callable, *args, what: str = "step sync"):
        """Run blocking ``fn(*args)`` with bounded-hang peer detection.

        ``fn`` is the host-blocking point of a training step (the device
        sync on the step's outputs — the first place a dead peer's
        unfinished collective wedges the host).  It runs on a daemon side
        thread while this (main) thread polls ``check_peers``; if a peer
        dies mid-collective the poll raises :class:`PeerLostError` (with
        ``mid_step=True``) within roughly one timeout instead of blocking
        forever.  The abandoned daemon thread stays wedged in the runtime —
        the caller's contract is to checkpoint-and-exit, not to resume
        collectives on a broken world.
        """
        if self.num_processes <= 1:
            return fn(*args)
        box: dict = {}
        done = threading.Event()

        def _run():
            try:
                box["result"] = fn(*args)
            except BaseException as e:  # re-raised on the caller thread
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=_run, name="elastic-guarded", daemon=True)
        started = time.monotonic()
        with span("elastic_guard", what=what):
            t.start()
            poll = min(self.heartbeat_interval, self.timeout / 4.0)
            while not done.wait(poll):
                try:
                    self.check_peers(mid_step=True)
                except PeerLostError as e:
                    blocked = time.monotonic() - started
                    raise PeerLostError(
                        f"{e} — detected while blocked in {what} for "
                        f"{blocked:.1f}s; the in-flight step is unrecoverable",
                        dead_ranks=e.dead_ranks,
                        mid_step=True,
                    ) from None
        if "error" in box:
            raise box["error"]
        return box.get("result")
