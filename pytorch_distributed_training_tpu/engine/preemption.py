"""Preemption-safe training: checkpoint-and-exit on SIGTERM.

The reference has no failure handling beyond a log-and-delete crash hook
(SURVEY.md §5.3 — a worker loss restarts the 450k-iteration run from
zero).  TPU pods make this concrete: preemptible/spot capacity delivers
SIGTERM with a grace window before eviction.  This guard turns that signal
into a clean save-and-exit: the training loop polls ``triggered`` once per
iteration (a Python bool check — nothing enters the compiled step) and,
when set, writes a checkpoint at the CURRENT iteration and stops; the next
launch resumes from it via the normal ``checkpoint.resume`` path.

Enabled automatically whenever checkpointing is configured (set
``training.checkpoint.preemption: False`` to opt out).  The latched signal
set is configurable — ``training.checkpoint.preemption_signals: [SIGTERM,
SIGUSR1]`` (names or numbers; see :meth:`PreemptionGuard.parse_signals`) —
because eviction notices differ by platform: plain SIGTERM on most
spot/preemptible VMs, but e.g. SIGUSR1-style custom notice hooks on some
GKE/TPU-VM setups.  Default stays SIGTERM-only.

Non-main-thread degradation: signal handlers are process-wide and
installable ONLY from the main thread (CPython restriction).  When the
guard is entered from any other thread — e.g. a Runner driven inside a
test harness thread or an embedding server — ``__enter__`` logs a warning
and installs nothing: ``triggered`` stays a plain inert flag (it can still
be set programmatically, which is exactly what the hung-step watchdog's
``checkpoint_and_exit`` path does), and ``__exit__`` restores nothing.
The training run is then simply not preemption-safe rather than crashing
(unit-tested in tests/test_fault_tolerance.py).
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Optional, Sequence

__all__ = ["PreemptionGuard"]


class PreemptionGuard:
    """Latches termination signals into a pollable flag.

    Use as a context manager around the training loop; previous handlers
    are restored on exit so nested/sequential Runners (tests) don't leak
    process state.
    """

    def __init__(
        self,
        signals: Sequence[int] = (signal.SIGTERM,),
        logger: Optional[logging.Logger] = None,
    ):
        self.signals = tuple(signals)
        self.logger = logger
        self.triggered = False
        self._prev: dict = {}
        self._installed = False

    @staticmethod
    def parse_signals(spec) -> tuple:
        """Resolve ``training.checkpoint.preemption_signals`` to signal numbers.

        Accepts a single name/number or a list of them.  Names are
        case-insensitive and the ``SIG`` prefix is optional (``sigterm``,
        ``TERM``, ``SIGUSR1`` all work); numbers must be valid signals on
        this platform.  Returns a non-empty tuple of ``signal.Signals``.
        """
        if isinstance(spec, (str, int)):
            spec = [spec]
        out = []
        for s in spec:
            if isinstance(s, str):
                name = s.upper()
                if not name.startswith("SIG"):
                    name = "SIG" + name
                sig = getattr(signal.Signals, name, None)
                if sig is None:
                    raise ValueError(
                        f"training.checkpoint.preemption_signals: unknown "
                        f"signal name {s!r}"
                    )
            else:
                try:
                    sig = signal.Signals(int(s))
                except ValueError:
                    raise ValueError(
                        f"training.checkpoint.preemption_signals: invalid "
                        f"signal number {s!r}"
                    ) from None
            out.append(sig)
        if not out:
            raise ValueError(
                "training.checkpoint.preemption_signals must name at least "
                "one signal"
            )
        return tuple(out)

    def _handler(self, signum, frame):
        # async-signal-safe: ONLY set the flag.  Logging here can self-
        # deadlock — the runner's QueueHandler takes a non-reentrant lock,
        # and the handler may interrupt the main thread mid-logging-call
        # (r2 code-review finding); the poll site in Runner._train_loop
        # logs the event instead.
        del signum, frame
        self.triggered = True

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            if self.logger:
                self.logger.warning(
                    "PreemptionGuard: not on the main thread, signal "
                    "handlers unavailable — preemption checkpointing disabled"
                )
            return self
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._handler)
        self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)
            self._installed = False
        return None
