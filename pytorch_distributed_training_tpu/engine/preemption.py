"""Preemption-safe training: checkpoint-and-exit on SIGTERM.

The reference has no failure handling beyond a log-and-delete crash hook
(SURVEY.md §5.3 — a worker loss restarts the 450k-iteration run from
zero).  TPU pods make this concrete: preemptible/spot capacity delivers
SIGTERM with a grace window before eviction.  This guard turns that signal
into a clean save-and-exit: the training loop polls ``triggered`` once per
iteration (a Python bool check — nothing enters the compiled step) and,
when set, writes a checkpoint at the CURRENT iteration and stops; the next
launch resumes from it via the normal ``checkpoint.resume`` path.

Enabled automatically whenever checkpointing is configured (set
``training.checkpoint.preemption: False`` to opt out).  Signal handlers
are process-wide and only installable from the main thread; elsewhere the
guard degrades to an inert flag (documented, logged).
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Optional, Sequence

__all__ = ["PreemptionGuard"]


class PreemptionGuard:
    """Latches termination signals into a pollable flag.

    Use as a context manager around the training loop; previous handlers
    are restored on exit so nested/sequential Runners (tests) don't leak
    process state.
    """

    def __init__(
        self,
        signals: Sequence[int] = (signal.SIGTERM,),
        logger: Optional[logging.Logger] = None,
    ):
        self.signals = tuple(signals)
        self.logger = logger
        self.triggered = False
        self._prev: dict = {}
        self._installed = False

    def _handler(self, signum, frame):
        # async-signal-safe: ONLY set the flag.  Logging here can self-
        # deadlock — the runner's QueueHandler takes a non-reentrant lock,
        # and the handler may interrupt the main thread mid-logging-call
        # (r2 code-review finding); the poll site in Runner._train_loop
        # logs the event instead.
        del signum, frame
        self.triggered = True

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            if self.logger:
                self.logger.warning(
                    "PreemptionGuard: not on the main thread, signal "
                    "handlers unavailable — preemption checkpointing disabled"
                )
            return self
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._handler)
        self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)
            self._installed = False
        return None
