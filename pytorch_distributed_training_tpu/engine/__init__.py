"""Training engine: compiled SPMD steps + the Runner orchestration.

Replaces the reference's L4/L6 layers (``Runner`` process orchestration and
the hot loops, train_distributed.py:89-331) — see runner.py / steps.py.
"""
from .chaos import (
    FAULT_MENU,
    ChaosSoakEngine,
    Scenario,
    ScenarioGenerator,
    coverage_matrix,
)
from .elastic import ElasticCoordinator, PeerLostError
from .integrity import DivergedReplicaError, IntegritySentinel
from .profiling import TraceProfiler
from .runner import Runner
from .sp_steps import build_lm_eval_step, build_lm_train_step
from .steps import (
    TrainState,
    build_eval_step,
    build_eval_step_exact,
    build_train_step,
    init_train_state,
)
from .tp_steps import build_tp_lm_train_step

__all__ = [
    "FAULT_MENU",
    "ChaosSoakEngine",
    "DivergedReplicaError",
    "ElasticCoordinator",
    "IntegritySentinel",
    "PeerLostError",
    "Runner",
    "Scenario",
    "ScenarioGenerator",
    "coverage_matrix",
    "TraceProfiler",
    "TrainState",
    "build_train_step",
    "build_eval_step",
    "build_eval_step_exact",
    "build_lm_train_step",
    "build_lm_eval_step",
    "build_tp_lm_train_step",
    "init_train_state",
]
