"""Compiled SPMD train / eval steps.

The TPU-native re-design of the reference's hot loop (SURVEY.md §3.2-3.3):
``train_iter``'s zero_grad -> H2D -> forward -> CE -> backward (DDP bucketed
allreduce) -> SGD step sequence (train_distributed.py:267-299) becomes ONE
XLA program: forward, loss, backward, gradient ``pmean`` over the ICI data
axis, BN-stats ``pmean`` (SyncBN), LR-schedule evaluation, and the SGD update
are all traced together under ``jit`` + ``shard_map``, so XLA fuses the
elementwise work into the matmuls and overlaps the gradient all-reduce with
remaining backward compute — the scheduling DDP's C++ reducer does by hand.

The per-step loss is ``pmean``-reduced in-graph (the reference's explicit
``dist.all_reduce(loss)/world_size``, :281-284) and returned as a device
scalar; the host only syncs on it at ``print_interval`` (:280), so steady-state
iterations never block on device->host transfers.

Eval mirrors :301-321: loss + top-1/top-5 computed on-device and
``pmean``-reduced (the reference's three per-batch ``all_reduce`` calls
collapse into the compiled step).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..metrics import accuracy
from ..ops import cross_entropy_loss
from ..parallel.mesh import DATA_AXIS
from ..telemetry.retrace import register_compiled
from .comm import reduce_gradients

__all__ = [
    "TrainState",
    "build_train_step",
    "build_eval_step",
    "build_eval_step_exact",
    "init_train_state",
]

# Step-family label for the static collective-order oracle (see
# analysis/collectives.py and PERF.md): all collectives emitted by the
# builders in this module belong to the data-parallel family.
PDT_COLLECTIVE_FAMILY = "dp"


class TrainState(struct.PyTreeNode):
    """Replicated training state: params + BN running stats + optimizer state.

    The reference's equivalents: module params/buffers on each replica (DDP
    keeps them in sync via grad allreduce + buffer broadcast) and
    ``optimizer.state`` (momentum buffers, train_distributed.py:207).  The
    iteration counter lives in ``opt_state.step``.

    ``ema``: exponential moving average of params (config ``training.ema``;
    empty dict when disabled, so the pytree stays checkpoint- and
    shard_map-friendly without structural branching).
    """

    params: Any
    batch_stats: Any
    opt_state: Any
    ema: Any = struct.field(default_factory=dict)

    @property
    def step(self):
        return self.opt_state.step


def init_train_state(model, optimizer, rng, sample_input) -> TrainState:
    """Same-seed replicated init — the DDP param broadcast (reference :198)
    is redundant when every replica initializes from the same PRNGKey
    (the reference already seeds all ranks identically, :141-142)."""
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        params=params,
        batch_stats=batch_stats,
        opt_state=optimizer.init(params),
    )


def _input_normalizer(input_norm) -> Callable:
    """Build the in-graph ``(x/255 - mean)/std`` affine for uint8 batches.

    ``input_norm`` is ``(mean, std)`` per channel.  Uses the same
    ``x*scale + bias`` form (f32) as the native host kernel
    (native/__init__.py: scale=1/(255*std), bias=-mean/std) so device-side
    normalization matches the host path to float rounding.  Identity when
    ``input_norm`` is None (host-normalized float32 input — reference
    parity).
    """
    if input_norm is None:
        return lambda img: img
    import numpy as np

    mean, std = (np.asarray(x, np.float32) for x in input_norm)
    scale = jnp.asarray(1.0 / (255.0 * std), jnp.float32)
    bias = jnp.asarray(-mean / std, jnp.float32)

    def normalize(img):
        return img.astype(jnp.float32) * scale + bias

    return normalize


def build_train_step(
    model,
    optimizer,
    lr_fn: Callable,
    mesh: Mesh,
    sync_bn: bool,
    donate: bool = True,
    input_norm=None,
    grad_accum: int = 1,
    label_smoothing: float = 0.0,
    ema_decay: Optional[float] = None,
    anomaly_factor: Optional[float] = None,
    comm=None,
):
    """Compile the full training iteration as one SPMD program.

    Args:
      model: a linen module whose ``apply`` takes ``(variables, img, train=...)``
        and mutates ``batch_stats`` in train mode.  When ``sync_bn``, the model
        must carry ``axis_name=DATA_AXIS`` so its BN layers ``pmean`` their
        statistics (the reference's SyncBatchNorm conversion, :196-197).
      optimizer: functional optimizer (``init``/``update``) from
        :mod:`..optimizers`.
      lr_fn: pure schedule ``lr(step)`` evaluated on-device (see
        :mod:`..schedulers`).
      sync_bn: whether BN stats are cross-replica (config ``training.sync_bn``).
      input_norm: optional ``(mean, std)`` — the batch arrives as raw uint8
        and is normalized in-graph (4x less host->device traffic; config
        ``training.device_normalize``).
      grad_accum: micro-batch count (config ``training.grad_accumulation``).
        The per-device batch is processed as ``grad_accum`` sequential
        micro-batches under ``lax.scan`` — activation memory shrinks by the
        factor while the update stays the mean over the full batch (equal
        micro sizes => mean of micro means == full mean).  BN running stats
        update once per micro-batch with per-micro statistics, matching
        torch's behavior when accumulating under DDP.
      label_smoothing: torch-convention smoothing factor (config
        ``training.label_smoothing``; 0 = reference parity).  Deliberately
        applied to the TRAINING objective only — the eval step reports
        unsmoothed CE so validation losses stay comparable across smoothing
        settings (the perplexity convention).
      ema_decay: when set, maintain ``state.ema`` as the exponential moving
        average of the updated params, ``ema <- d*ema + (1-d)*params``
        (config ``training.ema.decay``; the Runner evaluates with the EMA
        params when enabled).
      anomaly_factor: when set, arm the anomaly-step guard (config
        ``training.fault_tolerance.anomaly``).  The step additionally takes
        a host-fed ``gnorm_ref`` scalar (trailing-median grad norm; a
        python float, so feeding a new value never retraces) and computes
        the global grad norm on-device.  A step whose loss/grad-norm is
        non-finite — or whose grad norm exceeds ``anomaly_factor *
        gnorm_ref`` when both are positive (``anomaly_factor == 0`` means
        non-finite-only) — is SKIPPED: params, BN stats, optimizer state
        and EMA are ``jnp.where``-gated back to their inputs, so nothing
        anomalous ever leaves the compiled step and the state stays
        bitwise-identical.  The step then returns ``(state, loss, gnorm,
        applied)`` instead of ``(state, loss)``; ``None`` (the default)
        compiles the exact ungated program.
      comm: optional :class:`..engine.comm.CommConfig` (config
        ``training.comm``).  With ``comm.overlap`` the objective becomes
        the LOCAL shard mean — the backward then carries no collective —
        and the gradients are reduced explicitly afterward as one bucketed
        ``pmean`` per size-bounded bucket in reverse-backward order
        (engine/comm.py).  ``psum(g/n)`` becomes ``psum(g)/n``: bitwise on
        power-of-two meshes, <= 1e-6 otherwise (tests/test_comm_overlap.py).
        ``None``/``overlap: false`` compiles the exact legacy step.
    """
    normalize = _input_normalizer(input_norm)
    overlap = comm is not None and comm.overlap

    def micro_loss(params, batch_stats, img, label):
        # normalize PER MICRO-BATCH: converting uint8 -> f32 up front would
        # pin a 4x-size buffer across the whole accumulation scan, defeating
        # the memory savings grad_accum exists for
        img = normalize(img)

        def loss_fn(p):
            out, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                img,
                train=True,
                mutable=["batch_stats"],
            )
            loss = cross_entropy_loss(out, label, label_smoothing)
            # Make the OBJECTIVE the global-batch mean (each replica's CE is
            # the mean over its local shard).  Differentiating this is the
            # DDP-reducer equivalent: the cotangent of the replicated params
            # is psum-reduced across the mesh by shard_map's AD transpose, so
            # `grads` below is exactly the DDP-averaged gradient — an
            # explicit post-grad collective would double-count the psum
            # (world_size x too large; regression-tested in
            # tests/test_engine.py::test_dp_step_matches_single_device).
            # XLA still overlaps the underlying all-reduce with independent
            # backward compute, like DDP's bucketed reducer (reference :198).
            # comm.overlap instead differentiates the LOCAL mean and moves
            # the reduction after the backward as explicit bucketed pmeans
            # with a pinned schedule (engine/comm.py).
            if not overlap:
                loss = jax.lax.pmean(loss, DATA_AXIS)
            # models without batch statistics (e.g. ViT) mutate nothing
            return loss, mutated.get("batch_stats", {})

        return jax.value_and_grad(loss_fn, has_aux=True)(params)

    # When the optimizer is fused AND EMA is on, fold the EMA decay into the
    # same fused update pass (one kernel per dtype group for update+EMA
    # combined) instead of paying a separate one-kernel-per-leaf tree.map
    # after the shard_map.  Identical math either way (regression-tested in
    # tests/test_profiling.py); the fold only exists for the kernel count.
    fold_ema = ema_decay is not None and getattr(optimizer, "fused", False)
    guard = anomaly_factor is not None

    def body(params, batch_stats, opt_state, img, label, ema, *guard_args):
        if grad_accum > 1:
            b = img.shape[0]
            if b % grad_accum != 0:
                raise ValueError(
                    f"per-device batch {b} not divisible by "
                    f"grad_accumulation {grad_accum}"
                )
            micro = b // grad_accum
            img = img.reshape(grad_accum, micro, *img.shape[1:])
            label = label.reshape(grad_accum, micro)
            zero_grads = jax.tree.map(jnp.zeros_like, params)

            def scan_step(carry, xy):
                bs, acc, loss_acc = carry
                (loss, new_bs), grads = micro_loss(params, bs, *xy)
                acc = jax.tree.map(
                    lambda a, g: a + g / grad_accum, acc, grads
                )
                return (new_bs, acc, loss_acc + loss / grad_accum), None

            (new_bs, grads, loss), _ = jax.lax.scan(
                scan_step, (batch_stats, zero_grads, jnp.float32(0.0)),
                (img, label),
            )
        else:
            (loss, new_bs), grads = micro_loss(params, batch_stats, img, label)
        if overlap:
            # grads/loss are local shard means here; the bucketed pmeans
            # reproduce the implicit reduction (psum(g)/n vs psum(g/n))
            grads = reduce_gradients(grads, comm, DATA_AXIS, op="pmean")
            loss = jax.lax.pmean(loss, DATA_AXIS)
        if not sync_bn:
            # Local BN stats diverge per replica; average them so the state
            # stays replicated (the reference's DDP broadcast_buffers keeps
            # replicas in sync by broadcasting rank-0 — an averaging variant
            # with the same fixed point; deviation documented in SURVEY §2.3).
            new_bs = jax.lax.pmean(new_bs, DATA_AXIS)
        lr = lr_fn(opt_state.step)
        if fold_ema:
            new_params, new_opt, new_ema = optimizer.update_with_ema(
                grads, opt_state, params, lr, ema, float(ema_decay)
            )
        else:
            new_params, new_opt = optimizer.update(grads, opt_state, params, lr)
            new_ema = ema
        if not guard:
            return new_params, new_bs, new_opt, loss, new_ema
        (gnorm_ref,) = guard_args
        # grads are already the psum-reduced (replicated) global gradient —
        # the norm is identical on every replica, no extra collective
        gnorm = jnp.sqrt(
            sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree.leaves(grads)
            )
        )
        ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
        if anomaly_factor > 0:
            # spike check only once a trailing median exists (ref > 0) —
            # the first steps of a run have no baseline to spike against
            ok = ok & (
                (gnorm_ref <= 0.0) | (gnorm <= anomaly_factor * gnorm_ref)
            )

        def sel(new, old):
            return jax.tree.map(lambda n, o: jnp.where(ok, n, o), new, old)

        return (
            sel(new_params, params), sel(new_bs, batch_stats),
            sel(new_opt, opt_state), loss, sel(new_ema, ema), gnorm, ok,
        )

    rep = P()
    img_spec = P(DATA_AXIS, None, None, None)
    label_spec = P(DATA_AXIS)
    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(rep, rep, rep, img_spec, label_spec, rep)
        + ((rep,) if guard else ()),
        out_specs=(rep, rep, rep, rep, rep) + ((rep, rep) if guard else ()),
    )

    def _ema_outside(ok, old_ema, new_params):
        # replicated elementwise update — no collective needed, so it
        # lives outside the shard_map
        d = float(ema_decay)
        if ok is None:
            return jax.tree.map(
                lambda e, p: d * e + (1.0 - d) * p, old_ema, new_params
            )
        # gated: new_params is already the OLD params on a skipped step, so
        # an unguarded decay would still drift the EMA toward them
        return jax.tree.map(
            lambda e, p: jnp.where(ok, d * e + (1.0 - d) * p, e),
            old_ema, new_params,
        )

    if guard:

        @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
        def train_step(state: TrainState, img, label, gnorm_ref):
            new_params, new_bs, new_opt, loss, new_ema, gnorm, ok = sharded(
                state.params, state.batch_stats, state.opt_state, img, label,
                state.ema, gnorm_ref,
            )
            if ema_decay is not None and not fold_ema:
                new_ema = _ema_outside(ok, state.ema, new_params)
            return (
                TrainState(
                    params=new_params, batch_stats=new_bs, opt_state=new_opt,
                    ema=new_ema,
                ),
                loss,
                gnorm,
                ok.astype(jnp.float32),
            )

        return register_compiled(
            f"train_step/gspmd{'_overlap' if overlap else ''}_guarded", train_step
        )

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def train_step(state: TrainState, img, label):
        new_params, new_bs, new_opt, loss, new_ema = sharded(
            state.params, state.batch_stats, state.opt_state, img, label,
            state.ema,
        )
        if ema_decay is not None and not fold_ema:
            new_ema = _ema_outside(None, state.ema, new_params)
        return (
            TrainState(
                params=new_params, batch_stats=new_bs, opt_state=new_opt,
                ema=new_ema,
            ),
            loss,
        )

    return register_compiled(
        f"train_step/gspmd{'_overlap' if overlap else ''}", train_step
    )


def build_eval_step(model, mesh: Mesh, input_norm=None):
    """Compile the distributed validation step (reference :309-321)."""
    normalize = _input_normalizer(input_norm)

    def body(params, batch_stats, img, label):
        img = normalize(img)
        out = model.apply(
            {"params": params, "batch_stats": batch_stats}, img, train=False
        )
        loss = cross_entropy_loss(out, label)
        acc1, acc5 = accuracy(out, label, topk=(1, 5))
        # reference: all_reduce(SUM) then / world_size  ==  pmean
        return jax.lax.pmean((loss, acc1, acc5), DATA_AXIS)

    rep = P()
    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(rep, rep, P(DATA_AXIS, None, None, None), P(DATA_AXIS)),
        out_specs=(rep, rep, rep),
    )

    @jax.jit
    def eval_step(state: TrainState, img, label):
        return sharded(state.params, state.batch_stats, img, label)

    return eval_step


def build_eval_step_exact(model, mesh: Mesh, input_norm=None):
    """Exact-count distributed validation (``validation.exact: true``).

    The parity eval (:func:`build_eval_step` + per-batch ``AverageMeter``)
    inherits two reference biases on non-divisible val sets: the
    ``DistributedSampler`` wrap-padded tail double-counts samples (torch
    semantics, reference train_distributed.py:219-222) and the unweighted
    per-batch meter over-weights a smaller final batch.  This step returns
    GLOBAL SUMS ``(ce_sum, top1_sum, top5_sum, n)`` with a per-sample
    validity mask folded in before the ``psum`` — masked samples (sampler
    wrap-pads, runner batch-padding) contribute nothing, so
    ``sums / n`` is exact for any val-set size.  Default remains the
    parity eval (Runner.validate)."""
    normalize = _input_normalizer(input_norm)

    def body(params, batch_stats, img, label, mask):
        img = normalize(img)
        out = model.apply(
            {"params": params, "batch_stats": batch_stats}, img, train=False
        )
        logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        ce = -jnp.take_along_axis(logp, label[:, None], axis=-1)[:, 0]
        # k clamped like metrics.accuracy's argsort form: < 5 classes must
        # not turn the exact flag into a trace-time crash
        topk = jax.lax.top_k(out, min(5, out.shape[-1]))[1]
        c1 = (topk[:, 0] == label).astype(jnp.float32)
        c5 = jnp.any(topk == label[:, None], axis=-1).astype(jnp.float32)
        m = mask.astype(jnp.float32)
        return jax.lax.psum(
            (jnp.sum(ce * m), jnp.sum(c1 * m), jnp.sum(c5 * m), jnp.sum(m)),
            DATA_AXIS,
        )

    rep = P()
    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            rep, rep, P(DATA_AXIS, None, None, None), P(DATA_AXIS),
            P(DATA_AXIS),
        ),
        out_specs=(rep, rep, rep, rep),
    )

    @jax.jit
    def eval_step(state: TrainState, img, label, mask):
        return sharded(state.params, state.batch_stats, img, label, mask)

    return eval_step
