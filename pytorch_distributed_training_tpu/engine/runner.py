"""The Runner: process orchestration + training loop.

Mirrors the reference's ``Runner`` (train_distributed.py:89-331) with the
same constructor surface and loop semantics, re-architected for TPU
(SURVEY.md §7 design stance): ONE controller process per host — no
``mp.spawn`` of one process per accelerator (boundary #2 of §3.1 collapses);
``--multiprocessing`` is accepted as a compat no-op.  Multi-host bootstrap
goes through ``jax.distributed.initialize`` (see ``parallel.distributed``),
after which the 2-D ``(data, model)`` mesh spans every chip of every host and
the compiled train step handles all cross-device communication in-graph.

Loop parity (reference line refs inline):
  - iteration-based outer loop with ``is_val()`` gating (:251-265),
  - ``train_iter``: one compiled step; loss is pmean-reduced in-graph and
    only synced to host at ``print_interval`` (:267-299); scheduler steps
    every iteration (:299),
  - ``validate``: per-batch compiled eval with in-graph pmean of
    loss/acc1/acc5, AverageMeter accumulation, rank-0 logging + TB (:301-331),
  - batch division: per-device batch = ``batch_size / local_device_count``
    (the reference divides by *local* GPU count, :194 — global batch scales
    with node count; replicated deliberately, SURVEY.md §7 stage 4).  The
    config-gated alternative ``training.batch_division: world`` divides by
    the world device count instead (cfg batch_size == global batch),
  - the val loader reuses the *training* batch size / workers (:235-241);
    the YAML ``validation:`` section stays dead (parity).

Additions beyond the reference (config-gated or additive-only, SURVEY.md §7
deviations): images/sec throughput metering (required by the north-star
metric), optional bf16 compute (``training.dtype: bfloat16``).
"""
from __future__ import annotations

import logging
import time
from logging.handlers import QueueHandler
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import tqdm

from ..config_parsing import validate_cfg
from ..data import (
    DataLoader,
    DistributedShardSampler,
    RandomSampler,
    SequentialSampler,
    device_prefetch,
    get_dataset,
)
from ..metrics import AverageMeter
from ..models import get_model
from ..optimizers import get_optimizer
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import (
    DATA_AXIS,
    batch_sharding,
    initialize_distributed,
    make_mesh,
    make_sp_mesh,
    replicated_sharding,
)
from ..parallel.sequence import SEQUENCE_AXIS
from ..schedulers import get_scheduler
from ..utils import enable_compile_cache, make_deterministic, make_iter_dataloader
from .checkpoint import Checkpointer
from .profiling import TraceProfiler
from .sp_steps import build_lm_eval_step, build_lm_train_step
from .steps import TrainState, build_eval_step, build_train_step, init_train_state

__all__ = ["Runner"]


class Runner:
    """Drop-in counterpart of the reference Runner (train_distributed.py:89)."""

    def __init__(
        self,
        num_nodes: int,
        rank: int,
        seed: Optional[int],
        dist_url: str,
        dist_backend: str,
        multiprocessing: bool,
        logger_queue,
        global_cfg: dict,
        tb_writer_constructor: Callable,
    ):
        self.num_nodes = num_nodes
        self.rank = rank
        self.seed = seed
        self.dist_url = dist_url
        self.dist_backend = dist_backend
        self.multiprocessing = multiprocessing
        self.logger_queue = logger_queue
        self.global_cfg = validate_cfg(global_cfg)
        self.tb_writer_constructor = tb_writer_constructor
        self.iter: int = 0
        self.tb_writer = None

    def __call__(self):
        logger = logging.getLogger("Runner")
        if self.logger_queue is not None:
            logger.addHandler(QueueHandler(self.logger_queue))
        logger.setLevel(logging.INFO)
        if self.multiprocessing:
            # Reference spawns one process per GPU here (:130-132); the TPU
            # runtime is single-controller-per-host, so the flag is a no-op.
            logger.info(
                "--multiprocessing requested: single-controller JAX runtime "
                "drives all local devices from one process (flag is a no-op)"
            )
        logger.info("Start from direct call")
        self.worker(0)

    # ------------------------------------------------------------------ setup
    def worker(self, local_id: int):
        if self.seed is not None:
            make_deterministic(self.seed)  # same seed on all hosts (:141-142)

        if self.num_nodes is not None and self.num_nodes > 1:
            initialize_distributed(
                self.dist_url, self.num_nodes, self.rank, self.dist_backend
            )
        self.current_rank = jax.process_index()
        self.world_size = jax.device_count()  # chips, not processes
        self.distributed = self.world_size > 1

        self.logger = logging.getLogger(f"worker_rank_{self.current_rank}")
        self.logger.propagate = False
        if self.logger_queue is not None:
            self.logger.addHandler(QueueHandler(self.logger_queue))
        self.logger.setLevel(logging.INFO)

        if self.current_rank == 0:
            self.tb_writer = self.tb_writer_constructor()

        self.logger.info(
            "Use %d TPU device(s) across %d process(es), current rank: %d",
            self.world_size,
            jax.process_count(),
            self.current_rank,
        )

        cfg = self.global_cfg
        train_cfg = cfg["training"]

        # Additive key ``training.compile_cache``: persistent XLA compilation
        # cache directory — the autotune analog of the reference's
        # ``cudnn.benchmark`` (train_distributed.py:54, SURVEY §2.3).  Set
        # BEFORE any step is built so the first jit of this process can
        # already hit a previous launch's entry.
        compile_cache = train_cfg.get("compile_cache")
        if compile_cache:
            path = enable_compile_cache(str(compile_cache))
            self.logger.info("Persistent XLA compilation cache at %s", path)

        ds_kwargs = dict(
            n_classes=cfg["dataset"]["n_classes"],
            image_size=cfg["dataset"].get("image_size", 224),
            n_samples=cfg["dataset"].get("n_samples"),
            seq_len=cfg["dataset"].get("seq_len"),
        )
        train_dataset = get_dataset(
            cfg["dataset"]["name"], cfg["dataset"]["root"], split="train", **ds_kwargs
        )
        val_dataset = get_dataset(
            cfg["dataset"]["name"], cfg["dataset"]["root"], split="val", **ds_kwargs
        )

        self.compute_dtype = {
            "float32": jnp.float32,
            "bfloat16": jnp.bfloat16,
        }[train_cfg.get("dtype", "float32")]
        # Model section: ``name`` is the reference's only key (:183-186);
        # extra keys are architecture hyperparameters forwarded to the zoo
        # (additive — e.g. embed_dim/depth/num_heads for TransformerLM).
        model_cfg = dict(cfg["model"])
        model_name = model_cfg.pop("name")
        self.model_name = model_name
        # Additive key ``model.pretrained``: initialize the run from a torch
        # ``state_dict`` checkpoint (torchvision layout for the ResNet family,
        # the twin layout of tests/test_torch_port_lm.py for TransformerLM) —
        # the user-facing form of the reference's TORCH_HOME model-zoo
        # weights (/root/reference/train.sh:2).  Ported via models/torch_port
        # at state construction below; strict shape/name checking raises
        # descriptive errors instead of silently part-loading.
        self.pretrained = model_cfg.pop("pretrained", None)
        # The long-context LM task (beyond the reference, SURVEY.md §5.7):
        # first-class from the config surface — ``model.name:
        # TransformerLM`` + an LM dataset + optional
        # ``training.sequence_parallelism`` (ring/Ulysses over a sequence
        # mesh axis, parallel.sequence).
        self.is_lm = model_name.lower() == "transformerlm"
        # MoE (model.moe_experts > 0, ops/moe.py): trains on the GSPMD path
        # whatever the parallelism degrees — the routing einsums and the
        # sown aux loss need the partitioner's global-token view, and under
        # tensor_parallelism the stacked expert weights shard over the
        # model axis (expert parallelism).
        self.is_moe = self.is_lm and int(model_cfg.get("moe_experts", 0) or 0) > 0
        if self.pretrained and self.is_moe:
            # the torch-twin LM layout has no expert tensors — a part-load
            # would silently leave experts at random init
            raise ValueError(
                "model.pretrained does not support MoE models "
                "(no torch-twin layout for expert weights)"
            )
        sync_bn = (
            bool(train_cfg["sync_bn"]) and self.distributed and not self.is_lm
        )
        self.seq_par = int(train_cfg.get("sequence_parallelism", 1))
        self.tensor_par = int(train_cfg.get("tensor_parallelism", 1))
        # Additive key ``training.pipeline_parallelism``: GPipe microbatch
        # pipeline over a (data, stage) mesh (parallel/pipeline.py,
        # engine/pp_steps.py).  ``training.microbatches`` tunes the schedule
        # (default = stage count; the bubble fraction is (S-1)/(M+S-1)).
        self.pipe_par = int(train_cfg.get("pipeline_parallelism", 1))
        self.microbatches = int(train_cfg.get("microbatches", self.pipe_par))
        if "microbatches" in train_cfg and self.pipe_par <= 1:
            # silently ignoring the key would read as "microbatch streaming
            # enabled" — grad_accumulation is the non-pipelined equivalent
            raise ValueError(
                "training.microbatches requires pipeline_parallelism > 1 "
                "(use training.grad_accumulation for non-pipelined "
                "micro-batching)"
            )
        if (
            self.seq_par > 1 or self.tensor_par > 1 or self.pipe_par > 1
        ) and not self.is_lm:
            raise ValueError(
                "training.sequence_parallelism / tensor_parallelism / "
                "pipeline_parallelism require model.name: TransformerLM"
            )
        if self.pipe_par > 1 and self.seq_par > 1 and self.tensor_par > 1:
            # the pipeline mesh supports ONE inner axis besides stage:
            # model (PP x TP) or sequence (PP x SP) — a 4-axis composition
            # is not wired (parallel/pipeline.make_pp_mesh)
            raise ValueError(
                "pipeline_parallelism x sequence_parallelism x "
                "tensor_parallelism (three-way) is not wired; pick "
                "PP x SP or PP x TP"
            )
        # Additive key ``training.pp_schedule``: microbatch schedule for the
        # pipeline step — "gpipe" (autodiff backward, O(M) activation
        # residuals) or "1f1b" (manual interleaved backward with per-stage
        # recompute, O(S) buffered microbatch inputs; engine/pp_steps.py).
        self.pp_schedule = str(train_cfg.get("pp_schedule", "gpipe"))
        if self.pp_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"training.pp_schedule must be 'gpipe' or '1f1b', "
                f"got {self.pp_schedule!r}"
            )
        if "pp_schedule" in train_cfg and self.pipe_par <= 1:
            raise ValueError(
                "training.pp_schedule requires pipeline_parallelism > 1"
            )
        if self.pipe_par > 1 and self.is_moe:
            # MoE blocks break the homogeneous stacked-layer layout the
            # pipeline step scans over, and its sown aux loss is discarded
            # by the manual per-stage block apply
            raise ValueError(
                "model.moe_experts does not compose with pipeline_parallelism"
            )
        if self.is_moe and int(model_cfg.get("moe_experts")) % self.tensor_par != 0:
            raise ValueError(
                f"model.moe_experts ({model_cfg.get('moe_experts')}) must be "
                f"divisible by training.tensor_parallelism ({self.tensor_par}) "
                "for an even expert split"
            )
        if self.microbatches < max(self.pipe_par, 1):
            raise ValueError(
                f"training.microbatches ({self.microbatches}) must be >= "
                f"pipeline_parallelism ({self.pipe_par})"
            )
        # seq_par alone -> shard_map ring attention (memory-optimal for long
        # context); tensor_par or zero (with or without seq_par) -> the GSPMD
        # path on a (data, sequence, model) mesh, where the partitioner
        # inserts the sequence resharding around attention (tp_steps.py).
        # Additive key ``training.zero``: ZeRO stage 0|1|2 (True = 1) —
        # optimizer-state sharding over the data axis, stage 2 adds sharded
        # gradient buffers (GSPMD LM path; parallel/tensor.py).  Parsed here
        # because it changes BOTH the path selection below and the model's
        # attention mode.
        zero_cfg = train_cfg.get("zero", False)
        if isinstance(zero_cfg, bool):
            self.zero = 1 if zero_cfg else 0  # True = ZeRO-1 (back-compat)
        elif isinstance(zero_cfg, int) and zero_cfg in (0, 1, 2):
            self.zero = zero_cfg
        else:
            raise ValueError(
                f"training.zero must be a bool or a stage in (0, 1, 2), "
                f"got {zero_cfg!r}"
            )
        if self.zero and not self.is_lm:
            raise ValueError(
                "training.zero is only wired for the LM task (GSPMD path)"
            )
        if self.zero >= 2 and self.pipe_par > 1:
            # the pipeline step computes grads inside a manual shard_map with
            # stage-sharded layouts — a different contract than ZeRO-2's
            # data-axis gradient scatter (ZeRO-1 moments do compose there)
            raise ValueError(
                "training.zero: 2 does not compose with "
                "pipeline_parallelism — use zero: 1 (sharded moments) "
                "under the pipeline"
            )
        if self.is_lm:
            for key, par in (
                ("sequence_parallelism", self.seq_par),
                ("tensor_parallelism", self.tensor_par),
                ("pipeline_parallelism", self.pipe_par),
            ):
                if par < 1 or jax.local_device_count() % par != 0:
                    # the host-batch layout (and
                    # make_array_from_process_local_data) assumes each host
                    # holds whole shard groups
                    raise ValueError(
                        f"training.{key} ({par}) must divide the local "
                        f"device count ({jax.local_device_count()})"
                    )
            non_data_par = self.seq_par * self.tensor_par * self.pipe_par
            if jax.local_device_count() % non_data_par != 0:
                # combined: one data shard spans a seq x tensor x pipe
                # device group — the whole group must fit within a host or
                # units_local becomes 0 and the host batch degenerates
                raise ValueError(
                    f"sequence_parallelism x tensor_parallelism x "
                    f"pipeline_parallelism ({self.seq_par} x {self.tensor_par}"
                    f" x {self.pipe_par}) must divide the local device count "
                    f"({jax.local_device_count()})"
                )
            sample_inp, _ = train_dataset[0]
            self.seq_len = int(sample_inp.shape[0])
            if self.seq_len % self.seq_par != 0:
                raise ValueError(
                    f"dataset.seq_len ({self.seq_len}) must be divisible by "
                    f"training.sequence_parallelism ({self.seq_par})"
                )
            model_cfg.setdefault("max_len", self.seq_len)
            if (
                self.seq_par > 1
                and self.tensor_par == 1
                and self.pipe_par == 1
                and not self.zero
                and not self.is_moe
            ):
                # ring-attention path only; the GSPMD path (tensor_par or
                # zero or MoE) keeps seq_axis=None and lets the partitioner
                # distribute, and the PP x SP path builds its own
                # seq_axis'd stage blocks (pp_steps._stage_applies) — a
                # seq_axis model requires shard_map
                model_cfg.setdefault("seq_axis", SEQUENCE_AXIS)
            self.model = get_model(
                model_name,
                num_classes=cfg["dataset"]["n_classes"],
                dtype=self.compute_dtype,
                **model_cfg,
            )
            if self.is_moe and not (
                1 <= self.model.moe_every <= self.model.depth
            ):
                # read from the CONSTRUCTED model, not re-hardcoded class
                # defaults (r2 review): moe_every 0 would div-by-zero at
                # init; > depth silently trains a fully dense model while
                # every MoE restriction still applies
                raise ValueError(
                    f"model.moe_every ({self.model.moe_every}) must be in "
                    f"[1, depth={self.model.depth}] (moe_every > depth "
                    "would make no block MoE)"
                )
        else:
            # reference behavior: only ``model.name`` is read for the image
            # zoo — extra keys stay ignored (forwarding them would crash
            # ResNet/ViT constructors on e.g. annotation-only keys)
            self.model = get_model(
                model_name,
                num_classes=cfg["dataset"]["n_classes"],
                axis_name=DATA_AXIS if sync_bn else None,
                dtype=self.compute_dtype,
            )

        batch_size = train_cfg["batch_size"]
        n_workers = train_cfg["num_workers"]
        local_devices = jax.local_device_count()
        # SURVEY §7 stage 4 decision, config-gated (additive key, unknown to
        # the reference schema):
        #   batch_division: local  — reference parity (:194): per-device batch
        #       divides by the LOCAL device count, so the global batch scales
        #       with node count (default).
        #   batch_division: world  — divide by the WORLD device count, so cfg
        #       batch_size IS the global batch at any topology.
        division = train_cfg.get("batch_division", "local")
        if division not in ("local", "world"):
            raise ValueError(
                f"training.batch_division must be 'local' or 'world', got {division!r}"
            )
        # Batch rows shard over the DATA axis only; each data shard spans a
        # seq_par x tensor_par device group (either may be 1), so the
        # division unit is a data shard, not a device.
        non_data = (
            self.seq_par * self.tensor_par * self.pipe_par if self.is_lm else 1
        )
        units_local = local_devices // non_data
        units_world = self.world_size // non_data
        # Additive key ``training.grad_accumulation``: per-step micro-batch
        # count (lax.scan inside the compiled step — activation memory / N,
        # identical update math; engine/steps.py).
        self.grad_accum = int(train_cfg.get("grad_accumulation", 1))
        if self.grad_accum < 1:
            raise ValueError(f"grad_accumulation must be >= 1, got {self.grad_accum}")
        if self.grad_accum > 1 and self.pipe_par > 1:
            raise ValueError(
                "grad_accumulation is redundant under pipeline_parallelism — "
                "raise training.microbatches instead (same memory effect, "
                "and it also shrinks the pipeline bubble)"
            )
        # Additive keys: torch-convention label smoothing + params EMA
        # (evaluation runs with the EMA weights when enabled).
        self.label_smoothing = float(train_cfg.get("label_smoothing", 0.0))
        if not (0.0 <= self.label_smoothing < 1.0):
            raise ValueError(
                f"label_smoothing must be in [0, 1), got {self.label_smoothing}"
            )
        ema_cfg = train_cfg.get("ema")
        self.ema_decay = float(ema_cfg["decay"]) if ema_cfg else None
        if self.ema_decay is not None and not (0.0 < self.ema_decay < 1.0):
            raise ValueError(f"ema.decay must be in (0, 1), got {self.ema_decay}")
        if self.ema_decay is not None and self.is_lm:
            raise ValueError("training.ema is only wired for the image task")
        if self.distributed:
            divisor = units_world if division == "world" else units_local
            per_device_batch = batch_size // max(divisor, 1)
            if per_device_batch == 0 or divisor == 0:
                raise ValueError(
                    f"batch_size {batch_size} < {division} batch-shard count {divisor}"
                )
            if division == "world" and batch_size % divisor != 0:
                # the mode's whole contract is "cfg batch_size IS the global
                # batch" — a silent floor would break it, so fail loudly
                raise ValueError(
                    f"batch_division: world requires batch_size ({batch_size}) "
                    f"divisible by the world batch-shard count ({divisor})"
                )
            host_batch = per_device_batch * units_local
        else:
            host_batch = batch_size
            per_device_batch = batch_size
        if per_device_batch % self.grad_accum != 0:
            # fail fast like every other config error, not at jit trace time
            raise ValueError(
                f"per-shard batch ({per_device_batch}) not divisible by "
                f"training.grad_accumulation ({self.grad_accum})"
            )
        if self.pipe_par > 1 and per_device_batch % self.microbatches != 0:
            raise ValueError(
                f"per-shard batch ({per_device_batch}) not divisible by "
                f"training.microbatches ({self.microbatches})"
            )
        # One controller per host: cfg num_workers = decode threads per host
        # (the reference divides workers among its per-GPU processes, :195 —
        # same total per host).
        self.logger.info("host batch_size: %d, workers: %d", host_batch, n_workers)

        optimizer_params = dict(train_cfg["optimizer"])
        optimizer_cls = get_optimizer(optimizer_params)
        optimizer_params.pop("name")
        self.optimizer = optimizer_cls(**optimizer_params)
        self.logger.info("Loaded optimizer: %s(%s)", optimizer_cls.__name__, optimizer_params)

        self.scheduler = get_scheduler(self.optimizer, train_cfg["lr_schedule"])

        n_hosts = jax.process_count()
        seed = self.seed if self.seed is not None else 0
        if self.distributed:
            train_sampler = DistributedShardSampler(
                len(train_dataset),
                num_replicas=n_hosts,
                rank=self.current_rank,
                shuffle=True,
                drop_last=True,
                seed=seed,
            )
            val_sampler = DistributedShardSampler(
                len(val_dataset),
                num_replicas=n_hosts,
                rank=self.current_rank,
                shuffle=False,
                seed=seed,
            )
        else:
            train_sampler = RandomSampler(len(train_dataset), seed=seed)
            val_sampler = SequentialSampler(len(val_dataset))

        # Additive key (unknown to the reference schema): loader backend —
        # "auto" picks the native C++ batch decoder for JPEG folder datasets,
        # threads otherwise; "process"/"thread" force a backend (loader.py).
        worker_mode = train_cfg.get("worker_mode", "auto")
        # Additive key ``training.device_normalize``: ship raw uint8 pixels
        # and run the (x/255 - mean)/std affine in-graph on the accelerator —
        # 4x less host->device traffic and one fewer host pass per image.
        # Default False = host-side normalization (reference parity).
        self.device_normalize = bool(train_cfg.get("device_normalize", False))
        norm_mean = getattr(train_dataset, "norm_mean", None)
        if self.device_normalize and (self.is_lm or norm_mean is None):
            raise ValueError(
                "training.device_normalize requires an image dataset with "
                "norm_mean/norm_std (e.g. imagenet)"
            )
        output_dtype = "uint8" if self.device_normalize else "float32"
        self._input_norm = (
            (train_dataset.norm_mean, train_dataset.norm_std)
            if self.device_normalize
            else None
        )
        # Additive key ``training.dct_denom``: libjpeg DCT-domain pre-scale
        # for the native decoder (1 = exact full decode, 2/4/8 = fixed,
        # 0 = auto-pick the largest that keeps the crop >= output size —
        # large speedup on big photos at a small resampling-fidelity cost).
        # TRAINING loader only: validation always decodes at full fidelity
        # so eval metrics stay comparable across dct settings.
        dct_denom = int(train_cfg.get("dct_denom", 1))
        if dct_denom not in (0, 1, 2, 4, 8):
            raise ValueError(
                f"training.dct_denom must be 0 (auto), 1, 2, 4, or 8; got {dct_denom}"
            )
        self.train_loader = train_loader = DataLoader(
            train_dataset,
            batch_size=host_batch,
            sampler=train_sampler,
            num_workers=n_workers,
            drop_last=True,
            worker_mode=worker_mode,
            output_dtype=output_dtype,
            dct_denom=dct_denom,
        )
        # Parity: val loader reuses TRAINING batch/workers (:235-241).
        self.val_loader = DataLoader(
            val_dataset,
            batch_size=host_batch,
            sampler=val_sampler,
            num_workers=n_workers,
            drop_last=False,
            worker_mode=worker_mode,
            output_dtype=output_dtype,
        )
        self.logger.info(
            "Load dataset done\nTraining: %d imgs, %d batchs\nEval: %d imgs, %d batchs",
            len(train_dataset),
            len(train_loader),
            len(val_dataset),
            len(self.val_loader),
        )

        # --- mesh + compiled steps + replicated state -----------------------
        if self.is_lm and self.pipe_par > 1:
            # (data, stage) mesh, GPipe microbatch schedule as one shard_map
            # program (parallel/pipeline.py, engine/pp_steps.py): decoder
            # blocks stack into a leading layer axis sharded over stage,
            # activations rotate stage-to-stage via ppermute each tick.
            from ..optimizers import LARS
            from ..parallel import (
                make_pp_mesh,
                pp_stack_params,
                pp_state_shardings,
            )
            from .pp_steps import build_pp_lm_eval_step, build_pp_lm_train_step

            if self.model.depth % self.pipe_par != 0:
                raise ValueError(
                    f"model.depth ({self.model.depth}) must be divisible by "
                    f"training.pipeline_parallelism ({self.pipe_par})"
                )
            if isinstance(self.optimizer, LARS):
                # LARS takes per-parameter norms; on the stacked layer axis
                # those would span a whole stage's layers — different math
                raise ValueError(
                    "optimizer LARS is not supported with "
                    "pipeline_parallelism (per-parameter trust ratios do not "
                    "survive the stacked-layer param layout)"
                )
            if self.tensor_par > 1 and self.model.num_heads % self.tensor_par:
                # same whole-head Megatron split constraint as the TP path
                raise ValueError(
                    f"model.num_heads ({self.model.num_heads}) must be "
                    f"divisible by training.tensor_parallelism "
                    f"({self.tensor_par})"
                )
            self.mesh = make_pp_mesh(
                self.pipe_par, self.tensor_par, self.seq_par
            )
            pp_seq_axis = SEQUENCE_AXIS if self.seq_par > 1 else None
            sample = jnp.zeros((1, self.seq_len), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed), sample)["params"]
            if self.pretrained:
                params = self._apply_pretrained_lm(params)
            pp_params = pp_stack_params(params, self.model.depth)
            state = TrainState(
                params=pp_params,
                batch_stats={},
                opt_state=self.optimizer.init(pp_params),
            )
            self.state = jax.device_put(
                state, pp_state_shardings(state, self.mesh, zero=self.zero)
            )
            self.train_step = build_pp_lm_train_step(
                self.model, self.optimizer, self.scheduler.lr_fn, self.mesh,
                num_microbatches=self.microbatches,
                label_smoothing=self.label_smoothing,
                schedule=self.pp_schedule,
                seq_axis=pp_seq_axis,
                zero=self.zero,
            )(self.state)
            self.eval_step = build_pp_lm_eval_step(
                self.model, self.mesh, self.microbatches,
                seq_axis=pp_seq_axis,
            )(self.state)
            tok_sharding = NamedSharding(
                self.mesh, P(DATA_AXIS, pp_seq_axis)
            )
            self._img_sharding = tok_sharding
            self._label_sharding = tok_sharding
        elif self.is_lm and (self.tensor_par > 1 or self.zero or self.is_moe):
            # (data, sequence, model) mesh, GSPMD Megatron sharding
            # (parallel/tensor): params live sharded over the model axis;
            # XLA inserts the row-parallel all-reduces, the gradient
            # all-reduce, and — when sequence_parallelism > 1 — the
            # sequence resharding around attention.  ``training.zero``
            # additionally shards optimizer moments over the data axis
            # (ZeRO-1) and selects this GSPMD path even at tensor_par == 1.
            # MoE models (``model.moe_experts``) also land here: expert
            # weights shard over the model axis (expert parallelism) and
            # the train step folds the sown aux loss into the objective
            from ..parallel import make_3d_mesh
            from ..parallel.tensor import tp_state_shardings
            from .tp_steps import build_tp_lm_eval_step, build_tp_lm_train_step

            if self.model.num_heads % self.tensor_par != 0:
                # the Megatron column split lands on whole-head boundaries
                raise ValueError(
                    f"model.num_heads ({self.model.num_heads}) must be "
                    f"divisible by training.tensor_parallelism ({self.tensor_par})"
                )
            self.mesh = make_3d_mesh(self.seq_par, self.tensor_par)
            sample = jnp.zeros((1, self.seq_len), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed), sample)["params"]
            if self.pretrained:
                params = self._apply_pretrained_lm(params)
            state = TrainState(
                params=params,
                batch_stats={},
                opt_state=self.optimizer.init(params),
            )
            self.state = jax.device_put(
                state, tp_state_shardings(state, self.mesh, zero=self.zero)
            )
            self.train_step = build_tp_lm_train_step(
                self.model, self.optimizer, self.scheduler.lr_fn, self.mesh,
                label_smoothing=self.label_smoothing, zero=self.zero,
                grad_accum=self.grad_accum,
            )(self.state)
            self.eval_step = build_tp_lm_eval_step(
                self.model, self.mesh, zero=self.zero
            )(self.state)
            tok_sharding = NamedSharding(
                self.mesh, P(DATA_AXIS, SEQUENCE_AXIS)
            )
            self._img_sharding = tok_sharding
            self._label_sharding = tok_sharding
        elif self.is_lm:
            # (data, sequence) mesh; with sequence_parallelism == 1 the
            # sequence axis is trivial and this is plain DP over tokens
            self.mesh = make_sp_mesh(self.seq_par)
            sample = jnp.zeros((1, self.seq_len), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed), sample)["params"]
            if self.pretrained:
                params = self._apply_pretrained_lm(params)
            state = TrainState(
                params=params,
                batch_stats={},
                opt_state=self.optimizer.init(params),
            )
            self.state = jax.device_put(state, replicated_sharding(self.mesh))
            self.train_step = build_lm_train_step(
                self.model, self.optimizer, self.scheduler.lr_fn, self.mesh,
                grad_accum=self.grad_accum,
                label_smoothing=self.label_smoothing,
            )
            self.eval_step = build_lm_eval_step(self.model, self.mesh)
            # tokens/targets are [batch, seq], sharded over BOTH mesh axes
            tok_sharding = NamedSharding(self.mesh, P(DATA_AXIS, SEQUENCE_AXIS))
            self._img_sharding = tok_sharding
            self._label_sharding = tok_sharding
        else:
            self.mesh = make_mesh()
            sample_img, _ = train_dataset[0]
            sample = jnp.zeros((1,) + tuple(sample_img.shape), jnp.float32)
            state = init_train_state(
                self.model, self.optimizer, jax.random.PRNGKey(seed), sample
            )
            if self.pretrained:
                # before the EMA copy below, so the average starts from the
                # pretrained weights too
                state = self._apply_pretrained_image(state)
            if self.ema_decay is not None:
                # EMA starts at the initial weights (standard convention).
                # jnp.copy: ema must NOT alias the params buffers — the
                # donated train step would otherwise donate them twice
                state = state.replace(ema=jax.tree.map(jnp.copy, state.params))
            self.state = jax.device_put(state, replicated_sharding(self.mesh))
            self.train_step = build_train_step(
                self.model,
                self.optimizer,
                self.scheduler.lr_fn,
                self.mesh,
                sync_bn=sync_bn,
                input_norm=self._input_norm,
                grad_accum=self.grad_accum,
                label_smoothing=self.label_smoothing,
                ema_decay=self.ema_decay,
            )
            self.eval_step = build_eval_step(
                self.model, self.mesh, input_norm=self._input_norm
            )
            self._img_sharding = batch_sharding(self.mesh, ndim=4)
            self._label_sharding = batch_sharding(self.mesh, ndim=1)
        self.global_batch = host_batch * n_hosts
        self._tput_t0 = time.monotonic()
        self._tput_iters = 0

        # --- optional checkpoint/resume (absent in reference; config-gated) --
        self.checkpointer = Checkpointer.from_config(train_cfg)
        if self.checkpointer:
            if train_cfg["checkpoint"].get("resume", True):
                self.state, start_iter = self.checkpointer.restore_latest(
                    self.state, self.logger
                )
                self.iter = start_iter
                self.scheduler.last_epoch = start_iter
            elif self.checkpointer.latest() is not None:
                # orbax never overwrites an existing step; starting a fresh
                # run into a populated dir would crash at the first save
                raise ValueError(
                    f"checkpoint dir {self.checkpointer.directory} already has "
                    f"step {self.checkpointer.latest()} but resume is False — "
                    "clear the directory or point checkpoint.dir elsewhere"
                )

        # --- optional jax.profiler trace window (absent in reference; §5.1) --
        self.profiler = (
            TraceProfiler.from_config(train_cfg, self.logger)
            if self.current_rank == 0
            else None
        )

        # device-side double buffering: the next batch's H2D transfer is
        # dispatched while the current step computes (the reference's pinned
        # memory + non_blocking copies, :272-273)
        iter_generator = device_prefetch(
            make_iter_dataloader(train_loader, start_iter=self.iter),
            self._put_batch,
        )

        # --- preemption safety (engine/preemption.py; beyond reference) -----
        # SIGTERM (spot/preemptible eviction notice) -> checkpoint at the
        # current iteration and exit cleanly; the relaunch resumes from it.
        # Active whenever checkpointing is configured, opt-out via
        # ``training.checkpoint.preemption: False``.
        from .preemption import PreemptionGuard

        use_guard = self.checkpointer is not None and train_cfg["checkpoint"].get(
            "preemption", True
        )
        self._preempt = (
            PreemptionGuard(logger=self.logger) if use_guard else None
        )
        # Multi-process: checkpointer.save is a COLLECTIVE, and the signal
        # may land on one host only (or at different loop positions), so
        # hosts must AGREE on preemption at the same iteration or the save
        # deadlocks with mismatched participants (r2 code-review finding).
        # Every ``preemption_sync_interval`` iters (default 10) all hosts
        # allgather their local flags and act only on the global OR —
        # well within any eviction grace window.  Single process acts on
        # the local flag immediately, no collective.
        self._preempt_sync = 10
        if use_guard:
            self._preempt_sync = int(
                train_cfg["checkpoint"].get("preemption_sync_interval", 10)
            )
            if self._preempt_sync < 1:
                raise ValueError(
                    f"checkpoint.preemption_sync_interval must be >= 1, got "
                    f"{self._preempt_sync}"
                )
        import contextlib

        with self._preempt if self._preempt else contextlib.nullcontext():
            self._train_loop(iter_generator, train_cfg)
        if self.profiler:
            self.profiler.finalize()
        if self.checkpointer:
            self.checkpointer.wait()
            self.checkpointer.close()
        self.train_loader.close()
        self.val_loader.close()

    # ------------------------------------------------- pretrained ingestion
    def _load_torch_state_dict(self) -> dict:
        """Read ``model.pretrained`` as a torch ``state_dict`` mapping."""
        import os

        path = self.pretrained
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"model.pretrained: checkpoint '{path}' does not exist"
            )
        import torch

        state_dict = torch.load(path, map_location="cpu", weights_only=True)
        if isinstance(state_dict, dict) and "state_dict" in state_dict:
            state_dict = state_dict["state_dict"]  # harness checkpoints nest it
        if not isinstance(state_dict, dict):
            raise ValueError(
                f"model.pretrained: '{path}' does not contain a state_dict "
                f"mapping (got {type(state_dict).__name__})"
            )
        return state_dict

    def _apply_pretrained_image(self, state: TrainState) -> TrainState:
        """Replace params + BN stats with a ported torchvision checkpoint."""
        from ..models.resnet import ResNet
        from ..models.torch_port import import_torch_resnet_state_dict

        if not isinstance(self.model, ResNet):
            raise ValueError(
                f"model.pretrained: only the ResNet family has a torchvision "
                f"state_dict layout (got model.name: {self.model_name})"
            )
        variables = {"params": state.params, "batch_stats": state.batch_stats}
        loaded = import_torch_resnet_state_dict(
            variables, self._load_torch_state_dict()
        )
        self.logger.info(
            "Initialized %s from pretrained torch checkpoint %s",
            self.model_name, self.pretrained,
        )
        return state.replace(
            params=loaded["params"], batch_stats=loaded["batch_stats"]
        )

    def _apply_pretrained_lm(self, params):
        """Replace LM params with a ported torch decoder checkpoint."""
        from ..models.torch_port import import_torch_lm_state_dict

        loaded = import_torch_lm_state_dict(params, self._load_torch_state_dict())
        self.logger.info(
            "Initialized %s from pretrained torch checkpoint %s",
            self.model_name, self.pretrained,
        )
        return loaded

    def _train_loop(self, iter_generator, train_cfg):
        # --- the reference outer loop (:251-265), line for line -------------
        while self.iter < train_cfg["train_iters"]:
            g_img, g_label = next(iter_generator)
            self.train_iter(g_img, g_label)
            if self._preempt and self._globally_preempted():
                self.logger.warning(
                    "Preemption signal received: saving checkpoint at iter "
                    "%d and exiting",
                    self.iter,
                )
                self.checkpointer.save(self.iter, self.state)
                self.checkpointer.wait()
                return
            if self.profiler:
                self.profiler.after_step(self.iter, sync=self.state)

            def is_val():
                p1 = self.iter != 0
                p2 = (self.iter + 1) % train_cfg["val_interval"] == 0
                p3 = self.iter == train_cfg["train_iters"] - 1
                return (p1 and p2) or p3

            if is_val():
                # keep validation (and checkpoint I/O below) out of the trace:
                # the window is a bounded steady-state sample of train steps
                if self.profiler:
                    self.profiler.stop(sync=self.state)
                self.validate()
            if self.checkpointer and self.checkpointer.should_save(
                self.iter, train_cfg["train_iters"]
            ):
                if self.profiler:
                    self.profiler.stop(sync=self.state)
                self.checkpointer.save(self.iter, self.state)
                if self.profiler:
                    # orbax saves are async — block until the write finishes
                    # so the window can't reopen over in-flight checkpoint I/O
                    self.checkpointer.wait()
            self.iter += 1

    def _globally_preempted(self) -> bool:
        """Whether to act on preemption at THIS iteration, agreed across
        processes (see the wiring comment in ``worker``).  Single process:
        the local flag, immediately.  Multi-process: all hosts execute the
        same allgather at the same iterations (the condition depends only
        on the shared iteration counter), so the collective cannot
        mismatch, and every host sees the same OR-ed verdict."""
        if jax.process_count() == 1:
            return self._preempt.triggered
        if (self.iter + 1) % self._preempt_sync != 0:
            return False
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(bool(self._preempt.triggered))
        )
        return bool(np.any(flags))

    # ------------------------------------------------------------- hot loop
    def _put_batch(self, img: np.ndarray, label: np.ndarray):
        """Host shard -> globally-sharded device arrays (the reference's
        pinned-memory ``non_blocking`` H2D copies, :272-273).  For the LM
        task both halves are int32 token grids (inputs, next-token targets)."""
        if self.is_lm:
            img_dtype = np.int32
        elif self.device_normalize:
            img_dtype = np.uint8  # normalized in-graph (4x smaller transfer)
        else:
            img_dtype = np.float32
        img = np.asarray(img, dtype=img_dtype)
        label = np.asarray(label, dtype=np.int32)
        g_img = jax.make_array_from_process_local_data(self._img_sharding, img)
        g_label = jax.make_array_from_process_local_data(self._label_sharding, label)
        return g_img, g_label

    def train_iter(self, g_img, g_label):
        """One training iteration on already-device-resident arrays."""
        train_cfg = self.global_cfg["training"]
        self.state, loss = self.train_step(self.state, g_img, g_label)
        self._tput_iters += 1

        if self.iter % train_cfg["print_interval"] == 0:
            # loss is already replica-averaged in-graph; this is the only
            # host<->device sync of the steady-state loop (reference :280-284).
            loss_val = float(loss)
            last_lr_group = self.scheduler.get_last_lr()
            now = time.monotonic()
            if self.iter == 0:
                # the first window is dominated by XLA compilation — don't
                # pollute the throughput metric with it
                imgs_per_sec = None
            else:
                imgs_per_sec = (
                    self.global_batch * self._tput_iters / max(now - self._tput_t0, 1e-9)
                )
            self._tput_t0, self._tput_iters = now, 0
            if self.current_rank == 0:
                tput_str = (
                    f" ({imgs_per_sec:.1f} img/s, {imgs_per_sec / self.world_size:.1f} img/s/chip)"
                    if imgs_per_sec is not None
                    else ""
                )
                self.logger.info(
                    "Iter [%d/%d] Lr: %s Loss: %.4f%s",
                    self.iter,
                    train_cfg["train_iters"],
                    last_lr_group,
                    loss_val,
                    tput_str,
                )
                if self.tb_writer is not None:
                    self.tb_writer.add_scalar("loss/train", loss_val, self.iter)
                    for gid, lr in enumerate(last_lr_group):
                        self.tb_writer.add_scalar(f"lr_group/{gid}", lr, self.iter)
                    if imgs_per_sec is not None:
                        self.tb_writer.add_scalar(
                            "throughput/images_per_sec", imgs_per_sec, self.iter
                        )
        self.scheduler.step()  # every iteration (:299)

    # ------------------------------------------------------------ validation
    def validate(self):
        if self.current_rank == 0:
            self.logger.info("Start valuation")
        loss_meter = AverageMeter()
        top_1 = AverageMeter()
        top_5 = AverageMeter()
        # with EMA enabled, validation runs on the averaged weights
        eval_state = (
            self.state.replace(params=self.state.ema)
            if getattr(self, "ema_decay", None) is not None
            else self.state
        )
        for img, label in tqdm.tqdm(self.val_loader, disable=self.current_rank != 0):
            g_img, g_label = self._put_batch(img, label)
            loss, acc1, acc5 = self.eval_step(eval_state, g_img, g_label)
            # already replica-averaged in-graph (reference :315-321)
            loss_meter.update(float(loss))
            top_1.update(float(acc1))
            top_5.update(float(acc5))
        if self.current_rank == 0:
            self.logger.info(
                "Acc@1: %.4f, Acc@5: %.4f, Loss: %.5f",
                top_1.value(),
                top_5.value(),
                loss_meter.value(),
            )
            if self.tb_writer is not None:
                self.tb_writer.add_scalar("eval/Acc@1", top_1.value(), self.iter)
                self.tb_writer.add_scalar("eval/Acc@5", top_5.value(), self.iter)
                self.tb_writer.add_scalar("eval/loss", loss_meter.value(), self.iter)
