"""The Runner: process orchestration + training loop.

Mirrors the reference's ``Runner`` (train_distributed.py:89-331) with the
same constructor surface and loop semantics, re-architected for TPU
(SURVEY.md §7 design stance): ONE controller process per host — no
``mp.spawn`` of one process per accelerator (boundary #2 of §3.1 collapses);
``--multiprocessing`` is accepted as a compat no-op.  Multi-host bootstrap
goes through ``jax.distributed.initialize`` (see ``parallel.distributed``),
after which the 2-D ``(data, model)`` mesh spans every chip of every host and
the compiled train step handles all cross-device communication in-graph.

Loop parity (reference line refs inline):
  - iteration-based outer loop with ``is_val()`` gating (:251-265),
  - ``train_iter``: one compiled step; loss is pmean-reduced in-graph and
    only synced to host at ``print_interval`` (:267-299); scheduler steps
    every iteration (:299),
  - ``validate``: per-batch compiled eval with in-graph pmean of
    loss/acc1/acc5, AverageMeter accumulation, rank-0 logging + TB (:301-331),
  - batch division: per-device batch = ``batch_size / local_device_count``
    (the reference divides by *local* GPU count, :194 — global batch scales
    with node count; replicated deliberately, SURVEY.md §7 stage 4).  The
    config-gated alternative ``training.batch_division: world`` divides by
    the world device count instead (cfg batch_size == global batch),
  - the val loader reuses the *training* batch size / workers (:235-241);
    the YAML ``validation:`` section stays dead (parity).

Additions beyond the reference (config-gated or additive-only, SURVEY.md §7
deviations): images/sec throughput metering (required by the north-star
metric), optional bf16 compute (``training.dtype: bfloat16``).
"""
from __future__ import annotations

import contextlib
import logging
import os
import time
from collections import deque
from logging.handlers import QueueHandler
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import tqdm

from ..config_parsing import validate_cfg
from ..data import (
    DataLoader,
    DistributedShardSampler,
    RandomSampler,
    SequentialSampler,
    device_prefetch,
    get_dataset,
)
from ..metrics import AverageMeter
from ..optimizers import get_optimizer
from ..parallel import initialize_distributed
from ..schedulers import get_scheduler
from ..utils import enable_compile_cache, make_deterministic, make_iter_dataloader
from ..telemetry import Telemetry
from . import fault
from .checkpoint import Checkpointer
from .elastic import ElasticCoordinator, PeerLostError
from .integrity import DivergedReplicaError, IntegritySentinel
from .paths import select_path
from .profiling import TraceProfiler
from .steps import TrainState
from .topology import (
    parse_batch,
    parse_comm,
    parse_elastic,
    parse_fault_tolerance,
    parse_integrity,
    parse_telemetry,
    parse_topology,
)
from .watchdog import StepWatchdog

__all__ = ["Runner"]


class Runner:
    """Drop-in counterpart of the reference Runner (train_distributed.py:89)."""

    def __init__(
        self,
        num_nodes: int,
        rank: int,
        seed: Optional[int],
        dist_url: str,
        dist_backend: str,
        multiprocessing: bool,
        logger_queue,
        global_cfg: dict,
        tb_writer_constructor: Callable,
    ):
        self.num_nodes = num_nodes
        self.rank = rank
        self.seed = seed
        self.dist_url = dist_url
        self.dist_backend = dist_backend
        self.multiprocessing = multiprocessing
        self.logger_queue = logger_queue
        self.global_cfg = validate_cfg(global_cfg)
        self.tb_writer_constructor = tb_writer_constructor
        self.iter: int = 0
        self.tb_writer = None
        self._telemetry: Optional[Telemetry] = None

    def __call__(self):
        logger = logging.getLogger("Runner")
        if self.logger_queue is not None:
            logger.addHandler(QueueHandler(self.logger_queue))
        logger.setLevel(logging.INFO)
        if self.multiprocessing:
            # Reference spawns one process per GPU here (:130-132); the TPU
            # runtime is single-controller-per-host, so the flag is a no-op.
            logger.info(
                "--multiprocessing requested: single-controller JAX runtime "
                "drives all local devices from one process (flag is a no-op)"
            )
        logger.info("Start from direct call")
        self.worker(0)

    # ------------------------------------------------------------------ setup
    def worker(self, local_id: int):
        if self.seed is not None:
            make_deterministic(self.seed)  # same seed on all hosts (:141-142)

        if self.num_nodes is not None and self.num_nodes > 1:
            initialize_distributed(
                self.dist_url, self.num_nodes, self.rank, self.dist_backend
            )
        # confined: api — setup writes happen before any watchdog/callback
        # thread exists; _on_hang's cross-thread reads are best-effort
        # diagnostics on purpose
        self.current_rank = jax.process_index()  # confined: api
        self.world_size = jax.device_count()  # chips, not processes
        self.distributed = self.world_size > 1

        self.logger = logging.getLogger(f"worker_rank_{self.current_rank}")  # confined: api
        self.logger.propagate = False
        if self.logger_queue is not None:
            self.logger.addHandler(QueueHandler(self.logger_queue))
        self.logger.setLevel(logging.INFO)

        if self.current_rank == 0:
            self.tb_writer = self.tb_writer_constructor()

        self.logger.info(
            "Use %d TPU device(s) across %d process(es), current rank: %d",
            self.world_size,
            jax.process_count(),
            self.current_rank,
        )

        cfg = self.global_cfg
        train_cfg = cfg["training"]

        # Additive key ``training.compile_cache``: persistent XLA compilation
        # cache directory — the autotune analog of the reference's
        # ``cudnn.benchmark`` (train_distributed.py:54, SURVEY §2.3).  Set
        # BEFORE any step is built so the first jit of this process can
        # already hit a previous launch's entry.
        compile_cache = train_cfg.get("compile_cache")
        if compile_cache:
            path = enable_compile_cache(str(compile_cache))
            self.logger.info("Persistent XLA compilation cache at %s", path)

        ds_kwargs = dict(
            n_classes=cfg["dataset"]["n_classes"],
            image_size=cfg["dataset"].get("image_size", 224),
            n_samples=cfg["dataset"].get("n_samples"),
            seq_len=cfg["dataset"].get("seq_len"),
        )
        train_dataset = get_dataset(
            cfg["dataset"]["name"], cfg["dataset"]["root"], split="train", **ds_kwargs
        )
        val_dataset = get_dataset(
            cfg["dataset"]["name"], cfg["dataset"]["root"], split="val", **ds_kwargs
        )

        # Flags, parallelism degrees, cross-constraints + model construction
        # (engine/topology.py — extracted, semantics unchanged; every
        # documented config error lives there).
        parse_topology(self, cfg, train_cfg, train_dataset)
        host_batch = parse_batch(self, train_cfg)
        # Gradient-communication keys (additive, off by default): bucketed
        # backward-overlapped reduction + ZeRO-1 routing (engine/comm.py).
        parse_comm(self, train_cfg)
        # Fault-tolerance keys (additive, all off by default) + the fault
        # injector: the PDT_FAULT_SPEC env var wins over the config key so a
        # chaos wrapper can override any run (engine/fault.py).
        parse_fault_tolerance(self, train_cfg)
        # Elastic multi-host recovery keys (additive, off by default):
        # heartbeat coordinator + peer-loss guard (engine/elastic.py).
        parse_elastic(self, train_cfg)
        # Unified telemetry keys (additive, in-memory layer on by default;
        # files only when dir is set — telemetry/ package, README
        # "Observability").
        parse_telemetry(self, train_cfg)
        # Integrity-sentinel keys (additive, off by default): periodic
        # state-fingerprint votes + quarantine (engine/integrity.py,
        # README "Integrity").
        parse_integrity(self, train_cfg)
        if self.fault_spec and not os.environ.get(fault.ENV_VAR):
            fault.install(self.fault_spec)
        self._injector = fault.get_injector()
        if self._injector.active:
            self.logger.warning(
                "fault injection ACTIVE: %s", self._injector.spec
            )
        if self.anomaly_enabled:
            # host-side trailing-median state for the on-device guard: the
            # history holds APPLIED steps' grad norms only, so one spike
            # cannot poison its own reference
            self._gnorm_hist: deque = deque(maxlen=self.anomaly_window)
            self._consec_anomalies = 0
        n_workers = train_cfg["num_workers"]
        # One controller per host: cfg num_workers = decode threads per host
        # (the reference divides workers among its per-GPU processes, :195 —
        # same total per host).
        self.logger.info("host batch_size: %d, workers: %d", host_batch, n_workers)

        optimizer_params = dict(train_cfg["optimizer"])
        optimizer_cls = get_optimizer(optimizer_params)
        optimizer_params.pop("name")
        self.optimizer = optimizer_cls(**optimizer_params)
        self.logger.info("Loaded optimizer: %s(%s)", optimizer_cls.__name__, optimizer_params)

        self.scheduler = get_scheduler(self.optimizer, train_cfg["lr_schedule"])

        n_hosts = jax.process_count()
        seed = self.seed if self.seed is not None else 0
        if self.distributed:
            train_sampler = DistributedShardSampler(
                len(train_dataset),
                num_replicas=n_hosts,
                rank=self.current_rank,
                shuffle=True,
                drop_last=True,
                seed=seed,
            )
            val_sampler = DistributedShardSampler(
                len(val_dataset),
                num_replicas=n_hosts,
                rank=self.current_rank,
                shuffle=False,
                seed=seed,
            )
        else:
            train_sampler = RandomSampler(len(train_dataset), seed=seed)
            val_sampler = SequentialSampler(len(val_dataset))

        # Additive key (unknown to the reference schema): loader backend —
        # "auto" picks the native C++ batch decoder for JPEG folder datasets,
        # threads otherwise; "process"/"thread" force a backend (loader.py).
        worker_mode = train_cfg.get("worker_mode", "auto")
        # Additive key ``training.device_normalize``: ship raw uint8 pixels
        # and run the (x/255 - mean)/std affine in-graph on the accelerator —
        # 4x less host->device traffic and one fewer host pass per image.
        # Default False = host-side normalization (reference parity).
        self.device_normalize = bool(train_cfg.get("device_normalize", False))
        norm_mean = getattr(train_dataset, "norm_mean", None)
        if self.device_normalize and (self.is_lm or norm_mean is None):
            raise ValueError(
                "training.device_normalize requires an image dataset with "
                "norm_mean/norm_std (e.g. imagenet)"
            )
        output_dtype = "uint8" if self.device_normalize else "float32"
        self._input_norm = (
            (train_dataset.norm_mean, train_dataset.norm_std)
            if self.device_normalize
            else None
        )
        # Additive key ``training.dct_denom``: libjpeg DCT-domain pre-scale
        # for the native decoder (1 = exact full decode, 2/4/8 = fixed,
        # 0 = auto-pick the largest that keeps the crop >= output size —
        # large speedup on big photos at a small resampling-fidelity cost).
        # TRAINING loader only: validation always decodes at full fidelity
        # so eval metrics stay comparable across dct settings.
        dct_denom = int(train_cfg.get("dct_denom", 1))
        if dct_denom not in (0, 1, 2, 4, 8):
            raise ValueError(
                f"training.dct_denom must be 0 (auto), 1, 2, 4, or 8; got {dct_denom}"
            )
        self.train_loader = train_loader = DataLoader(  # confined: api
            train_dataset,
            batch_size=host_batch,
            sampler=train_sampler,
            num_workers=n_workers,
            drop_last=True,
            worker_mode=worker_mode,
            output_dtype=output_dtype,
            dct_denom=dct_denom,
        )
        # Parity: val loader reuses TRAINING batch/workers (:235-241).
        self.val_loader = DataLoader(
            val_dataset,
            batch_size=host_batch,
            sampler=val_sampler,
            num_workers=n_workers,
            drop_last=False,
            worker_mode=worker_mode,
            output_dtype=output_dtype,
        )
        self.logger.info(
            "Load dataset done\nTraining: %d imgs, %d batchs\nEval: %d imgs, %d batchs",
            len(train_dataset),
            len(train_loader),
            len(val_dataset),
            len(self.val_loader),
        )

        # Exact-count eval (``validation.exact: true``; beyond reference —
        # masks the DistributedSampler wrap-padded tail + ragged-batch
        # padding out of the in-graph psum, steps.build_eval_step_exact).
        # Default off = reference parity (tail double-count, SURVEY §2.3).
        self._exact_eval = bool(cfg.get("validation", {}).get("exact", False))
        self._eval_step_exact = None
        self._host_batch = host_batch
        self._val_len = len(val_dataset)
        self._val_n_hosts = n_hosts if self.distributed else 1
        if self._exact_eval and self.is_lm:
            self.logger.warning(
                "validation.exact is implemented for the image eval path; "
                "LM validation keeps the parity (per-batch meter) semantics"
            )

        # --- mesh + compiled steps + sharded state (engine/paths.py) --------
        # Strategy table: the first matching PathSpec builds mesh, state,
        # train/eval steps, and the input shardings for this topology.
        path = select_path(self)
        self.logger.info("Execution path: %s", path.name)
        path.build(self, seed, train_dataset)
        self.global_batch = host_batch * n_hosts
        self._tput_t0 = time.monotonic()
        self._tput_iters = 0

        # --- optional checkpoint/resume (absent in reference; config-gated) --
        self.checkpointer = Checkpointer.from_config(train_cfg)
        if self.checkpointer:
            if train_cfg["checkpoint"].get("resume", True):
                self.state, start_iter = self.checkpointer.restore_latest(
                    self.state, self.logger
                )
                self.iter = start_iter
                self.scheduler.last_epoch = start_iter
            elif self.checkpointer.latest() is not None:
                # orbax never overwrites an existing step; starting a fresh
                # run into a populated dir would crash at the first save
                raise ValueError(
                    f"checkpoint dir {self.checkpointer.directory} already has "
                    f"step {self.checkpointer.latest()} but resume is False — "
                    "clear the directory or point checkpoint.dir elsewhere"
                )

        # --- input-pipeline position (mid-epoch resume; elastic layer) ------
        # (epoch, batches consumed this epoch) — persisted as a sidecar next
        # to every checkpoint so a resume (even at a DIFFERENT topology under
        # batch_division: world, where batches/epoch is world-invariant)
        # restarts the stream on exactly the next unseen batch.
        self._init_pipeline_position()

        # --- integrity sentinel (engine/integrity.py; config-gated) ---------
        # Fingerprint votes between steps + a retained known-good snapshot;
        # seeded with the state we are about to train from (post-restore),
        # so even the first check has a recovery point to replay from.
        self._integrity = None
        if self.integrity_enabled:
            self._integrity = IntegritySentinel(
                check_interval=self.integrity_check_interval,
                replicas=self.integrity_replicas,
                rank=self.current_rank,
                process_count=jax.process_count(),
                max_consecutive=self.integrity_max_consecutive,
                logger=self.logger,
            )
            self._integrity.retain(
                self.state, self.iter - 1, self._pipeline_extras()
            )
            self.logger.info(
                "integrity sentinel ON: fingerprint vote every %d step(s) "
                "across %d replica(s)%s, quarantine after %d consecutive "
                "diverged check(s)",
                self._integrity.check_interval, self._integrity.replicas,
                " (simulated)" if self._integrity.simulated else "",
                self._integrity.max_consecutive,
            )

        # --- elastic heartbeat coordinator (engine/elastic.py; config-gated) -
        self._elastic = None
        if self.elastic_enabled:
            hb_dir = self.elastic_dir or os.path.join(
                self.checkpointer.directory, "heartbeats"
            )
            self._elastic = ElasticCoordinator(
                hb_dir,
                process_index=jax.process_index(),
                num_processes=jax.process_count(),
                heartbeat_interval=self.elastic_heartbeat_interval,
                timeout=self.elastic_timeout,
                startup_grace=self.elastic_startup_grace,
                logger=self.logger,
            )
            self._elastic.start()
            self.logger.info(
                "elastic recovery ON: heartbeats in %s every %.2fs, peer "
                "timeout %.2fs", hb_dir, self.elastic_heartbeat_interval,
                self.elastic_timeout,
            )

        # --- unified telemetry (telemetry/; README "Observability") ---------
        # Built after the step path so its span recorder is live for the
        # whole loop; the compiled step families already registered with the
        # process-global jit-cache probe during path.build.
        self._telemetry = Telemetry(  # confined: api
            enabled=self.telemetry_enabled,
            dir=self.telemetry_dir,
            host=self.current_rank,
            is_rank0=self.current_rank == 0,
            snapshot_interval=self.telemetry_interval,
            span_ring=self.telemetry_span_ring,
            retrace_warn=self.telemetry_retrace_warn,
            tb_writer=self.tb_writer,
            use_tensorboard=self.telemetry_tensorboard,
            capture_signal=self.telemetry_capture_signal,
            capture_iters=self.telemetry_capture_iters,
            capture_at_iter=self.telemetry_capture_at_iter,
            capture_dir=self.telemetry_capture_dir,
            logger=self.logger,
        )

        # --- optional jax.profiler trace window (absent in reference; §5.1) --
        self.profiler = (
            TraceProfiler.from_config(train_cfg, self.logger)
            if self.current_rank == 0
            else None
        )

        iter_generator = self._make_stream()

        # --- preemption safety (engine/preemption.py; beyond reference) -----
        # Eviction notice (default SIGTERM; the latched set is configurable
        # via ``training.checkpoint.preemption_signals`` for platforms that
        # notify on other signals) -> checkpoint at the current iteration and
        # exit cleanly; the relaunch resumes from it.  Active whenever
        # checkpointing is configured, opt-out via
        # ``training.checkpoint.preemption: False``.
        from .preemption import PreemptionGuard

        use_guard = self.checkpointer is not None and train_cfg["checkpoint"].get(
            "preemption", True
        )
        self._preempt = None  # confined: api
        if use_guard:
            sigs = PreemptionGuard.parse_signals(
                train_cfg["checkpoint"].get("preemption_signals", ("SIGTERM",))
            )
            self._preempt = PreemptionGuard(signals=sigs, logger=self.logger)
        if self.watchdog_exit and not use_guard:
            raise ValueError(
                "fault_tolerance.watchdog.checkpoint_and_exit needs the "
                "preemption path: configure training.checkpoint.dir and "
                "leave checkpoint.preemption enabled"
            )
        # Multi-process: checkpointer.save is a COLLECTIVE, and the signal
        # may land on one host only (or at different loop positions), so
        # hosts must AGREE on preemption at the same iteration or the save
        # deadlocks with mismatched participants (r2 code-review finding).
        # Every ``preemption_sync_interval`` iters (default 10) all hosts
        # allgather their local flags and act only on the global OR —
        # well within any eviction grace window.  Single process acts on
        # the local flag immediately, no collective.
        self._preempt_sync = 10
        if use_guard:
            self._preempt_sync = int(
                train_cfg["checkpoint"].get("preemption_sync_interval", 10)
            )
            if self._preempt_sync < 1:
                raise ValueError(
                    f"checkpoint.preemption_sync_interval must be >= 1, got "
                    f"{self._preempt_sync}"
                )
        # --- hung-step watchdog (engine/watchdog.py; config-gated) ----------
        self._watchdog = None  # confined: api
        if self.watchdog_enabled:
            self._watchdog = StepWatchdog(
                factor=self.watchdog_factor,
                min_seconds=self.watchdog_min_seconds,
                window=self.watchdog_window,
                warmup=self.watchdog_warmup,
                poll_seconds=self.watchdog_poll,
                on_hang=self._on_hang,
                logger=self.logger,
            )

        try:
            with self._preempt if self._preempt else contextlib.nullcontext():
                self._train_loop(iter_generator, train_cfg)
        except DivergedReplicaError as e:
            # persistent silent corruption: quarantine — a healthy rank
            # emergency-checkpoints, the corrupt one just exits with the
            # diagnosis; the relaunch reshapes without it (the subclass
            # relationship with PeerLostError is the contract: peers see
            # this process's exit as an ordinary peer loss)
            self._on_diverged(e)
            raise
        except PeerLostError as e:
            # diagnosed dead peer: emergency-checkpoint what this process can
            # still save, then propagate — the caller relaunches at the new
            # world size and the restore path picks the emergency step up
            self._on_peer_lost(e)
            raise
        finally:
            if self._watchdog:
                self._watchdog.close()
            if self._elastic:
                self._elastic.close()
            # crash-path flush: buffered spans reach disk even when an
            # exception is propagating (full close happens below on the
            # clean path only)
            self._telemetry.flush()
        if self.profiler:
            self.profiler.finalize()
        if self.checkpointer:
            self.checkpointer.wait()
            self.checkpointer.close()
        self.train_loader.close()
        self.val_loader.close()
        # final snapshot + human summary AFTER the checkpointer drained, so
        # the last async write's stall/commit numbers are in the ledger
        self._telemetry.close(step=self.iter)

    # ------------------------------------------------- pretrained ingestion
    def _load_torch_state_dict(self) -> dict:
        """Read ``model.pretrained`` as a torch ``state_dict`` mapping."""
        import os

        path = self.pretrained
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"model.pretrained: checkpoint '{path}' does not exist"
            )
        import torch

        state_dict = torch.load(path, map_location="cpu", weights_only=True)
        if isinstance(state_dict, dict) and "state_dict" in state_dict:
            state_dict = state_dict["state_dict"]  # harness checkpoints nest it
        if not isinstance(state_dict, dict):
            raise ValueError(
                f"model.pretrained: '{path}' does not contain a state_dict "
                f"mapping (got {type(state_dict).__name__})"
            )
        return state_dict

    def _apply_pretrained_image(self, state: TrainState) -> TrainState:
        """Replace params (+ BN stats) with a ported torchvision checkpoint.

        ResNets use the torchvision ResNet layout (params + running stats);
        ViTs the torchvision ``VisionTransformer`` layout (params only — no
        batch statistics).  Anything else is rejected with the family list.
        """
        from ..models.resnet import ResNet
        from ..models.vit import ViT

        if not isinstance(self.model, (ResNet, ViT)):
            # family check BEFORE the (possibly multi-GB) torch.load
            raise ValueError(
                f"model.pretrained: only the ResNet and ViT families have a "
                f"torchvision state_dict layout (got model.name: "
                f"{self.model_name})"
            )
        state_dict = self._load_torch_state_dict()
        if isinstance(self.model, ResNet):
            from ..models.torch_port import import_torch_resnet_state_dict

            variables = {
                "params": state.params, "batch_stats": state.batch_stats,
            }
            loaded = import_torch_resnet_state_dict(variables, state_dict)
            new = state.replace(
                params=loaded["params"], batch_stats=loaded["batch_stats"]
            )
        else:
            from ..models.torch_port import import_torch_vit_state_dict

            params = import_torch_vit_state_dict(
                {"params": state.params}, state_dict,
                num_heads=self.model.num_heads,
            )
            new = state.replace(params=params)
        self.logger.info(
            "Initialized %s from pretrained torch checkpoint %s",
            self.model_name, self.pretrained,
        )
        return new

    def _apply_pretrained_lm(self, params):
        """Replace LM params with a ported torch decoder checkpoint."""
        from ..models.torch_port import import_torch_lm_state_dict

        loaded = import_torch_lm_state_dict(params, self._load_torch_state_dict())
        self.logger.info(
            "Initialized %s from pretrained torch checkpoint %s",
            self.model_name, self.pretrained,
        )
        return loaded

    # ------------------------------------------------------- fault tolerance
    def _init_pipeline_position(self):
        """Set (``_epoch``, ``_batch_in_epoch``) for the NEXT batch to draw.

        Preference order: the persisted sidecar of the checkpoint we resumed
        from (topology-independent under ``batch_division: world`` — a mesh
        reshape changes neither the global batch nor batches/epoch), else
        derive from the step counter and the CURRENT epoch length (exact
        whenever the topology didn't change)."""
        self._batches_per_epoch = len(self.train_loader)
        self._epoch, self._batch_in_epoch = divmod(
            self.iter, self._batches_per_epoch
        )
        if self.checkpointer is None or self.iter == 0:
            return
        extras = self.checkpointer.read_extras(self.iter - 1)
        if extras is None:
            return
        saved_bpe = int(extras.get("batches_per_epoch", self._batches_per_epoch))
        if saved_bpe != self._batches_per_epoch:
            self.logger.warning(
                "pipeline sidecar was written with %d batches/epoch but this "
                "topology yields %d — resuming at its recorded position, but "
                "bit-exact batch identity is not guaranteed (is "
                "training.batch_division 'world' on both runs?)",
                saved_bpe, self._batches_per_epoch,
            )
        self._epoch = int(extras["epoch"])
        self._batch_in_epoch = int(extras["batch_in_epoch"])
        self.logger.info(
            "pipeline position restored from sidecar: epoch %d, %d/%d "
            "batches consumed", self._epoch, self._batch_in_epoch,
            self._batches_per_epoch,
        )

    def _pipeline_extras(self) -> dict:
        """The sidecar payload persisted with each checkpoint (JSON-safe)."""
        return {
            "epoch": int(self._epoch),
            "batch_in_epoch": int(self._batch_in_epoch),
            "seed": int(self.seed) if self.seed is not None else 0,
            "world_processes": int(jax.process_count()),
            "batches_per_epoch": int(self._batches_per_epoch),
        }

    def _advance_pipeline(self):
        """Account one consumed training batch (called once per step)."""
        self._batch_in_epoch += 1
        if self._batch_in_epoch >= self._batches_per_epoch:
            self._epoch += 1
            self._batch_in_epoch = 0

    def _make_stream(self):
        """Build the training input stream: epoch iterator (fast-forwarded
        to ``self.iter``) -> optional NaN-batch injection -> device-side
        double buffering (the next batch's H2D transfer is dispatched while
        the current step computes — the reference's pinned memory +
        non_blocking copies, :272-273).  A rollback rebuilds the whole
        stream from the restored iteration."""
        host_iter = make_iter_dataloader(
            self.train_loader,
            start_iter=self.iter,
            start_epoch=self._epoch,
            skip_batches=self._batch_in_epoch,
        )
        if self._injector.active:
            host_iter = fault.poison_batches(
                host_iter, self._injector, start_iter=self.iter,
                logger=self.logger,
            )
        return device_prefetch(host_iter, self._put_batch)

    def _apply_step_faults(self):
        """Fire any host-side injected faults keyed to this step (the
        NaN-batch fault lives in the stream instead — see _make_stream)."""
        inj = self._injector
        if not inj.active:
            return
        k = inj.take("kill_peer", self.iter)
        if k is not None:
            target = int(k)
            if target < 0 or target == jax.process_index():
                import signal as _signal

                self.logger.error(
                    "fault injection: kill_peer@%d — SIGKILL self "
                    "(process %d, pid %d); surviving ranks must detect the "
                    "silence via the elastic heartbeat layer",
                    self.iter, jax.process_index(), os.getpid(),
                )
                os.kill(os.getpid(), _signal.SIGKILL)
        w = inj.take("kill_worker", self.iter)
        if w is not None:
            import signal as _signal

            pool = getattr(self.train_loader, "_pool", None)
            if pool is None:
                self.logger.warning(
                    "fault injection: kill_worker@%d ignored — the loader "
                    "has no process pool (worker_mode)", self.iter,
                )
            else:
                wid = int(w) % pool.num_workers
                pid = pool._procs[wid].pid
                self.logger.warning(
                    "fault injection: SIGKILL loader worker %d (pid %d) at "
                    "step %d", wid, pid, self.iter,
                )
                os.kill(pid, _signal.SIGKILL)
        s = inj.take("stall_step", self.iter)
        if s is not None:
            self.logger.warning(
                "fault injection: stalling step %d for %.2fs", self.iter, s
            )
            time.sleep(float(s))
        f = inj.take("sdc_flip", self.iter)
        if f is not None:
            if self._integrity is None:
                self.logger.warning(
                    "fault injection: sdc_flip@%d ignored — the integrity "
                    "sentinel is not configured (training.integrity)",
                    self.iter,
                )
            else:
                self.logger.warning(
                    "fault injection: arming silent bit flip on replica %d "
                    "at step %d — the sentinel's next fingerprint vote must "
                    "attribute it", int(f), self.iter,
                )
                self._integrity.arm_flip(int(f))

    def _on_hang(self, step: int, elapsed: float, limit: float) -> None:
        """Watchdog diagnostic dump (monitor thread): step identity,
        per-host progress, loader queue depth, and all-thread stacks."""
        fault.bump("watchdog_fires")
        pool = getattr(self.train_loader, "_pool", None)
        median = self._watchdog.trailing_median()
        self.logger.error(
            "watchdog: host %d stuck in step %d for %.1fs (limit %.1fs, "
            "trailing median %.3fs); loader pool tasks outstanding: %s",
            self.current_rank, step, elapsed, limit,
            -1.0 if median is None else median,
            getattr(pool, "_outstanding", "n/a"),
        )
        try:
            # GIL-safe all-thread dump: sys._current_frames + format_stack
            # run as ordinary Python, so frame objects stay refcounted while
            # walked.  (faulthandler.dump_traceback walks OTHER threads'
            # frames without synchronization — against a main thread busy
            # inside a compiled step it reads freed frames and segfaults.)
            import sys
            import threading
            import traceback

            names = {t.ident: t.name for t in threading.enumerate()}
            dump = []
            for tid, frame in sys._current_frames().items():
                dump.append(
                    f"Thread {names.get(tid, '?')} (id {tid}):\n"
                    + "".join(traceback.format_stack(frame))
                )
            self.logger.error("watchdog stack dump:\n%s", "\n".join(dump))
        except Exception:  # the dump is best-effort diagnostics
            pass
        tel = self._telemetry
        if tel is not None and tel.enabled:
            try:
                # what the process was DOING when it stalled: the last phase
                # spans + the full counter ledger (telemetry/runtime.py)
                self.logger.error(
                    "watchdog telemetry diagnostics:\n%s", tel.diagnostics()
                )
            except Exception:  # pragma: no cover - best-effort diagnostics
                pass
        if self.watchdog_exit and self._preempt is not None:
            # reuse the eviction path: the loop checkpoints at the current
            # iteration and exits cleanly (multi-host agreement included)
            self.logger.error(
                "watchdog: requesting checkpoint-and-exit via the "
                "preemption flag"
            )
            self._preempt.triggered = True

    def _synced_train_iter(self, g_img, g_label):
        """One training iteration, blocked to completion — elastic mode runs
        this under :meth:`ElasticCoordinator.guard` so the step's collectives
        cannot outlive the peer-liveness watch (the per-step sync is the
        documented cost of enabling elastic recovery)."""
        self.train_iter(g_img, g_label)
        jax.block_until_ready(self.state)

    def _on_peer_lost(self, e: PeerLostError):
        """A peer stopped heartbeating: checkpoint what this process can
        still save, log the diagnosis, and let the error propagate (the
        relaunch — possibly at a different world size — resumes from the
        emergency step via the mesh-reshape-tolerant restore path)."""
        fault.bump("peer_lost")
        self.logger.error("elastic recovery: %s", e)
        tel = self._telemetry
        if tel is not None and tel.enabled:
            try:
                # same dump the watchdog makes: where the loop was when the
                # peer's silence surfaced, plus every recovery counter
                self.logger.error(
                    "peer-loss telemetry diagnostics:\n%s", tel.diagnostics()
                )
            except Exception:  # pragma: no cover - best-effort diagnostics
                pass
        if e.mid_step:
            # the in-flight step donated the previous state's buffers into
            # an unfinished computation — nothing consistent left to save
            self.logger.error(
                "peer died mid-step %d: the in-flight step is unrecoverable; "
                "the relaunch resumes from the last durable checkpoint",
                self.iter,
            )
            return
        if self.checkpointer is None or self.iter == 0:
            self.logger.error(
                "no emergency checkpoint possible (%s) — the relaunch "
                "starts from the last durable checkpoint, if any",
                "no checkpointer configured" if self.checkpointer is None
                else "no step has completed yet",
            )
            return
        step = self.iter - 1
        try:
            path = self.checkpointer.save_emergency(
                step, self.state, extras=self._pipeline_extras()
            )
            self.logger.error(
                "EMERGENCY checkpoint for step %d written to %s — exiting; "
                "the relaunch resumes from it at any world size",
                step, path,
            )
        except ValueError as ve:
            # non-replicated state: a single survivor only holds one shard
            self.logger.error(
                "emergency checkpoint skipped: %s — the relaunch resumes "
                "from the last durable checkpoint", ve,
            )

    def _rollback(self, iter_generator, train_cfg):
        """N consecutive anomalous steps: restore the last checkpoint and
        rebuild the input stream from the restored iteration."""
        fault.bump("rollbacks")
        if self.checkpointer is None:
            raise RuntimeError(
                f"{self._consec_anomalies} consecutive anomalous steps at "
                f"iter {self.iter} and no training.checkpoint configured "
                "to roll back to"
            )
        self.logger.error(
            "anomaly guard: %d consecutive anomalous steps at iter %d — "
            "rolling back to the last checkpoint",
            self._consec_anomalies, self.iter,
        )
        try:
            iter_generator.close()
        except Exception:  # pragma: no cover - abandoned stream cleanup
            pass
        # flush the in-flight async save before restoring: the writer must
        # not race the restore on the checkpoint dir, and a save that
        # FAILED in the background must not abort the rollback — the
        # restore is the recovery (errors are logged and dropped)
        self.checkpointer.drain(raise_errors=False)
        self.state, start_iter = self.checkpointer.restore_latest(
            self.state, self.logger
        )
        # A restore that hands back non-finite params would immediately
        # re-trip the anomaly guard and loop rollback -> restore forever;
        # fail loudly instead (seen in the wild when a stale persistent
        # compile cache corrupted the restore path).
        restored_finite = all(
            bool(jnp.isfinite(leaf).all())
            for leaf in jax.tree.leaves(self.state.params)
        )
        if not restored_finite:
            raise RuntimeError(
                f"rollback restore of step {start_iter} returned non-finite "
                "parameters — checkpoint or restore path is corrupt"
            )
        self.iter = start_iter
        self.scheduler.last_epoch = start_iter
        self._init_pipeline_position()
        self._consec_anomalies = 0
        self._gnorm_hist.clear()
        # The restored steps replay against a cold pipeline (recompiles,
        # page cache misses) — a trailing median learned before the fault
        # would read the first replayed step as a hang/anomaly.  Re-enter
        # the watchdog's warmup instead of trusting the stale window.
        if self._watchdog:
            self._watchdog.reset()
        # Re-base the integrity sentinel on the restored state: its retained
        # snapshot still belongs to the ABANDONED pre-rollback timeline, so
        # an SDC detected during the replay would "recover" to state the
        # rollback just discarded (or all the way to the startup snapshot),
        # silently resurrecting the dropped anomalous steps.
        if self._integrity is not None:
            self._integrity.rebase(
                self.state, start_iter - 1, self._pipeline_extras()
            )
        return self._make_stream()

    def _integrity_recover(self, iter_generator, verdict):
        """This replica's fingerprint fell outside the healthy majority:
        restore the retained known-good snapshot in place and replay from
        it.  A transient flip heals here — the replayed steps recompute
        bit-identically (deterministic input stream, one-shot faults
        consumed) and the next check passes, resetting the consecutive
        count.  A flip that survives the restore (the snapshot's
        fingerprint does not reproduce) is persistent by definition —
        escalate to quarantine instead of looping restore→diverge."""
        sen = self._integrity
        self.logger.error(
            "integrity: replica %d diverged at step %d (reports %s) — "
            "restoring the retained snapshot of step %s and replaying",
            self.current_rank, self.iter,
            [f"{r:08x}" for r in verdict["reports"]], sen.snapshot_step,
        )
        try:
            iter_generator.close()
        except Exception:  # pragma: no cover - abandoned stream cleanup
            pass
        restored, snap_step, position, ok = sen.restore_snapshot(self.state)
        if not ok:
            raise DivergedReplicaError(
                f"replica {self.current_rank}'s state diverged at step "
                f"{self.iter} and restoring the retained snapshot of step "
                f"{snap_step} did not reproduce its fingerprint — the "
                "corruption is persistent (bad host/device memory), "
                "quarantining",
                ranks=(self.current_rank,), step=self.iter,
            )
        self.state = restored
        fault.bump("integrity_transient_flips")
        self.iter = snap_step + 1
        self.scheduler.last_epoch = self.iter
        if position is not None:
            self._epoch = int(position["epoch"])
            self._batch_in_epoch = int(position["batch_in_epoch"])
        else:
            self._epoch, self._batch_in_epoch = divmod(
                self.iter, self._batches_per_epoch
            )
        # Same staleness hazard as _rollback: the replay runs cold, so the
        # hang watchdog and the anomaly guard's grad-norm median must both
        # re-warm instead of judging replayed steps by pre-fault timings.
        self._consec_anomalies = 0
        self._gnorm_hist.clear()
        if self._watchdog:
            self._watchdog.reset()
        return self._make_stream()

    def _on_diverged(self, e: DivergedReplicaError):
        """Persistent corruption diagnosed: log, count, and emergency-
        checkpoint — but ONLY when this replica is healthy (a quarantined
        rank must never persist its corrupted state; peers save theirs,
        and the heartbeat layer turns this process's exit into an ordinary
        peer loss the relaunch reshapes around)."""
        fault.bump("integrity_quarantines")
        self.logger.error("integrity quarantine: %s", e)
        tel = self._telemetry
        if tel is not None and tel.enabled:
            try:
                self.logger.error(
                    "quarantine telemetry diagnostics:\n%s", tel.diagnostics()
                )
            except Exception:  # pragma: no cover - best-effort diagnostics
                pass
        if self.current_rank in e.ranks:
            self.logger.error(
                "local replica %d is the quarantined one — skipping the "
                "emergency checkpoint (corrupted state must not be saved); "
                "a healthy rank's emergency step or the last verified "
                "periodic checkpoint carries the resume", self.current_rank,
            )
            return
        if self.checkpointer is None:
            self.logger.error(
                "no checkpointer configured — the relaunch starts from "
                "the last durable checkpoint, if any"
            )
            return
        try:
            path = self.checkpointer.save_emergency(
                self.iter, self.state, extras=self._pipeline_extras()
            )
            self.logger.error(
                "EMERGENCY checkpoint for step %d written to %s by healthy "
                "rank %d — the relaunch resumes from it without the "
                "quarantined replica(s) %s",
                self.iter, path, self.current_rank, list(e.ranks),
            )
        except ValueError as ve:
            # non-replicated state: a single survivor only holds one shard
            self.logger.error(
                "emergency checkpoint skipped: %s — the relaunch resumes "
                "from the last durable checkpoint", ve,
            )

    def _train_loop(self, iter_generator, train_cfg):
        tel = self._telemetry
        # goodput accounting: a step at an iteration index we already passed
        # is a post-rollback REPLAY (paid-again work, not fresh progress)
        self._max_iter_seen = self.iter - 1
        self._last_step_applied = True
        # --- the reference outer loop (:251-265), line for line -------------
        while self.iter < train_cfg["train_iters"]:
            step_t0 = time.monotonic()
            if self._watchdog:
                self._watchdog.step_started(self.iter)
            self._apply_step_faults()
            if self._elastic is not None:
                # pre-step liveness gate: a peer that died BETWEEN steps is
                # caught here, before this process enters any collective —
                # the committed state is still saveable (emergency path)
                self._elastic.check_peers()
            with tel.span("data_wait", step=self.iter):
                g_img, g_label = next(iter_generator)
            if self._elastic is not None:
                # elastic mode's documented per-step cost: the step runs
                # under the peer-loss guard and is synced to completion, so
                # a peer dying MID-collective turns an indefinite hang into
                # a diagnosed PeerLostError within the heartbeat timeout
                with tel.span("step_dispatch", step=self.iter):
                    self._elastic.guard(
                        self._synced_train_iter, g_img, g_label,
                        what=f"train step {self.iter}",
                    )
            else:
                with tel.span("step_dispatch", step=self.iter):
                    self.train_iter(g_img, g_label)
            self._advance_pipeline()
            if self._watchdog:
                self._watchdog.step_finished()
            replayed = self.iter <= self._max_iter_seen
            self._max_iter_seen = max(self._max_iter_seen, self.iter)
            tel.note_step(
                time.monotonic() - step_t0,
                applied=self._last_step_applied,
                replayed=replayed,
            )
            if (
                self.anomaly_enabled
                and self._consec_anomalies >= self.anomaly_max_consec
            ):
                rb_t0 = time.monotonic()
                with tel.span("rollback", step=self.iter):
                    iter_generator = self._rollback(iter_generator, train_cfg)
                tel.note_lost("rollback", time.monotonic() - rb_t0)
                continue
            if self._integrity is not None and self._integrity.due(self.iter):
                # between steps the state is quiescent and owned (no
                # donation conflict with the compiled step) — fingerprint,
                # vote, and either retain a new known-good snapshot or
                # enter the classify-then-quarantine ladder
                with tel.span("integrity_check", step=self.iter):
                    self.state, verdict = self._integrity.check(
                        self.state, self.iter
                    )
                if verdict["persistent"]:
                    raise DivergedReplicaError(
                        f"replica(s) {verdict['persistent']} stayed outside "
                        f"the healthy fingerprint majority for "
                        f"{self._integrity.max_consecutive} consecutive "
                        f"checks at step {self.iter} — persistent "
                        "corruption, quarantining",
                        ranks=verdict["persistent"], step=self.iter,
                    )
                if verdict["local_diverged"]:
                    rc_t0 = time.monotonic()
                    with tel.span("integrity_restore", step=self.iter):
                        iter_generator = self._integrity_recover(
                            iter_generator, verdict
                        )
                    tel.note_lost(
                        "integrity_restore", time.monotonic() - rc_t0
                    )
                    continue
                # healthy consensus (a diverged SIMULATED peer restores
                # its own copy; our state is good) — retain it as the
                # recovery point for the next check
                self._integrity.retain(
                    self.state, self.iter, self._pipeline_extras()
                )
            if self._preempt and self._globally_preempted():
                self.logger.warning(
                    "Preemption signal received: saving checkpoint at iter "
                    "%d and exiting",
                    self.iter,
                )
                self.checkpointer.save(
                    self.iter, self.state, extras=self._pipeline_extras()
                )
                self.checkpointer.wait()
                return
            if self.profiler:
                self.profiler.after_step(self.iter, sync=self.state)

            def is_val():
                p1 = self.iter != 0
                p2 = (self.iter + 1) % train_cfg["val_interval"] == 0
                p3 = self.iter == train_cfg["train_iters"] - 1
                return (p1 and p2) or p3

            if is_val():
                # keep validation (and checkpoint I/O below) out of the trace:
                # the window is a bounded steady-state sample of train steps
                if self.profiler:
                    self.profiler.stop(sync=self.state)
                with tel.span("eval", step=self.iter):
                    self.validate()
            if self.checkpointer and self.checkpointer.should_save(
                self.iter, train_cfg["train_iters"]
            ):
                if self.profiler:
                    self.profiler.stop(sync=self.state)
                with tel.span("ckpt_save", step=self.iter):
                    self.checkpointer.save(
                        self.iter, self.state, extras=self._pipeline_extras()
                    )
                if self.profiler:
                    # with checkpoint.async the write is in flight — block
                    # until it commits so the profiler window can't reopen
                    # over background checkpoint I/O
                    self.checkpointer.wait()
            # retrace-probe poll + on-demand capture window + periodic export
            tel.after_step(self.iter, sync=self.state)
            self.iter += 1

    def _globally_preempted(self) -> bool:
        """Whether to act on preemption at THIS iteration, agreed across
        processes (see the wiring comment in ``worker``).  Single process:
        the local flag, immediately.  Multi-process: all hosts execute the
        same allgather at the same iterations (the condition depends only
        on the shared iteration counter), so the collective cannot
        mismatch, and every host sees the same OR-ed verdict."""
        if jax.process_count() == 1:
            return self._preempt.triggered
        if (self.iter + 1) % self._preempt_sync != 0:
            return False
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(bool(self._preempt.triggered))
        )
        return bool(np.any(flags))

    # ------------------------------------------------------------- hot loop
    def _put_batch(self, img: np.ndarray, label: np.ndarray):
        """Host shard -> globally-sharded device arrays (the reference's
        pinned-memory ``non_blocking`` H2D copies, :272-273).  For the LM
        task both halves are int32 token grids (inputs, next-token targets)."""
        if self.is_lm:
            img_dtype = np.int32
        elif self.device_normalize:
            img_dtype = np.uint8  # normalized in-graph (4x smaller transfer)
        else:
            img_dtype = np.float32
        img = np.asarray(img, dtype=img_dtype)
        label = np.asarray(label, dtype=np.int32)
        g_img = jax.make_array_from_process_local_data(self._img_sharding, img)
        g_label = jax.make_array_from_process_local_data(self._label_sharding, label)
        return g_img, g_label

    def _tspan(self, kind: str, **extra):
        """Telemetry span bound to the current iteration (no-op before the
        telemetry facade is built — direct ``train_iter`` calls in tests)."""
        tel = self._telemetry
        if tel is None:
            return contextlib.nullcontext()
        return tel.span(kind, step=self.iter, **extra)

    def train_iter(self, g_img, g_label):
        """One training iteration on already-device-resident arrays."""
        train_cfg = self.global_cfg["training"]
        if self.anomaly_enabled:
            # the trailing median rides into the compiled step as a python
            # float (weak-typed scalar: a new value never retraces); the
            # returned ``applied`` flag is the guard's one extra per-step
            # host sync — the documented cost of arming it
            ref = float(np.median(self._gnorm_hist)) if self._gnorm_hist else 0.0
            self.state, loss, gnorm, applied = self.train_step(
                self.state, g_img, g_label, ref
            )
            with self._tspan("device_block"):
                applied_host = float(applied)
            self._last_step_applied = applied_host >= 0.5
            if self._last_step_applied:
                self._gnorm_hist.append(float(gnorm))
                self._consec_anomalies = 0
            else:
                self._consec_anomalies += 1
                fault.bump("skipped_steps")
                self.logger.warning(
                    "anomaly guard: step %d SKIPPED (loss=%g grad_norm=%g, "
                    "trailing median %g) — %d consecutive",
                    self.iter, float(loss), float(gnorm), ref,
                    self._consec_anomalies,
                )
        else:
            self.state, loss = self.train_step(self.state, g_img, g_label)
            self._last_step_applied = True
        self._tput_iters += 1

        if self.iter % train_cfg["print_interval"] == 0:
            # loss is already replica-averaged in-graph; this is the only
            # host<->device sync of the steady-state loop (reference :280-284).
            with self._tspan("device_block"):
                loss_val = float(loss)
            last_lr_group = self.scheduler.get_last_lr()
            now = time.monotonic()
            if self.iter == 0:
                # the first window is dominated by XLA compilation — don't
                # pollute the throughput metric with it
                imgs_per_sec = None
            else:
                imgs_per_sec = (
                    self.global_batch * self._tput_iters / max(now - self._tput_t0, 1e-9)
                )
            self._tput_t0, self._tput_iters = now, 0
            if self.current_rank == 0:
                tput_str = (
                    f" ({imgs_per_sec:.1f} img/s, {imgs_per_sec / self.world_size:.1f} img/s/chip)"
                    if imgs_per_sec is not None
                    else ""
                )
                self.logger.info(
                    "Iter [%d/%d] Lr: %s Loss: %.4f%s",
                    self.iter,
                    train_cfg["train_iters"],
                    last_lr_group,
                    loss_val,
                    tput_str,
                )
                if self.tb_writer is not None:
                    self.tb_writer.add_scalar("loss/train", loss_val, self.iter)
                    for gid, lr in enumerate(last_lr_group):
                        self.tb_writer.add_scalar(f"lr_group/{gid}", lr, self.iter)
                    if imgs_per_sec is not None:
                        self.tb_writer.add_scalar(
                            "throughput/images_per_sec", imgs_per_sec, self.iter
                        )
        self.scheduler.step()  # every iteration (:299)

    # ------------------------------------------------------------ validation
    def _eval_state(self):
        # with EMA enabled, validation runs on the averaged weights
        return (
            self.state.replace(params=self.state.ema)
            if getattr(self, "ema_decay", None) is not None
            else self.state
        )

    def _report_validation(self, loss, acc1, acc5):
        if self.current_rank == 0:
            self.logger.info(
                "Acc@1: %.4f, Acc@5: %.4f, Loss: %.5f", acc1, acc5, loss
            )
            if self.tb_writer is not None:
                self.tb_writer.add_scalar("eval/Acc@1", acc1, self.iter)
                self.tb_writer.add_scalar("eval/Acc@5", acc5, self.iter)
                self.tb_writer.add_scalar("eval/loss", loss, self.iter)

    def validate(self):
        if self._exact_eval and not self.is_lm:
            return self._validate_exact()
        if self.current_rank == 0:
            self.logger.info("Start valuation")
        loss_meter = AverageMeter()
        top_1 = AverageMeter()
        top_5 = AverageMeter()
        eval_state = self._eval_state()
        for img, label in tqdm.tqdm(self.val_loader, disable=self.current_rank != 0):
            g_img, g_label = self._put_batch(img, label)
            loss, acc1, acc5 = self.eval_step(eval_state, g_img, g_label)
            # already replica-averaged in-graph (reference :315-321)
            loss_meter.update(float(loss))
            top_1.update(float(acc1))
            top_5.update(float(acc5))
        self._report_validation(loss_meter.value(), top_1.value(), top_5.value())

    def _validate_exact(self):
        """Exact-count eval (``validation.exact``): per-sample sums with a
        validity mask instead of per-batch meter averages — wrap-padded
        tail samples and ragged-batch padding contribute nothing, so the
        metrics equal the unsharded full-set computation exactly
        (tests/test_engine.py::test_exact_eval_matches_unsharded)."""
        from .steps import build_eval_step_exact

        if self.current_rank == 0:
            self.logger.info("Start valuation")
        if self._eval_step_exact is None:
            self._eval_step_exact = build_eval_step_exact(
                self.model, self.mesh, input_norm=self._input_norm
            )
        eval_state = self._eval_state()
        # local position p maps to global sampler slot rank + n_hosts*p;
        # wrap-padded duplicates occupy the slots past the dataset length
        n_real = max(
            0, -(-(self._val_len - self.current_rank) // self._val_n_hosts)
        )
        totals = np.zeros(4, np.float64)
        seen = 0
        for img, label in tqdm.tqdm(self.val_loader, disable=self.current_rank != 0):
            label = np.asarray(label)
            b = len(label)
            # the loader wrap-pads its final chunk to full batch_size
            # (data/loader.py, drop_last=False) — those duplicates occupy
            # positions >= the sampler's local count, so the same position
            # mask that covers sampler wrap-pads masks them too
            assert b == self._host_batch, (b, self._host_batch)
            mask = (np.arange(seen, seen + b) < n_real).astype(np.int32)
            seen += b
            g_img, g_label = self._put_batch(img, label)
            g_mask = jax.make_array_from_process_local_data(
                self._label_sharding, mask
            )
            sums = self._eval_step_exact(eval_state, g_img, g_label, g_mask)
            totals += np.asarray([float(x) for x in sums])
        n = max(totals[3], 1.0)
        self._report_validation(
            totals[0] / n, 100.0 * totals[1] / n, 100.0 * totals[2] / n
        )
