"""Topology/config parsing for the Runner: flags, validation, model build.

Extracted from ``Runner.worker`` (round-3 VERDICT weak #5: the 630-line
method's four-way path selection deserved extraction before a fifth path
lands).  Everything here is pure config -> attributes/raises: the semantics
(and every documented error message the composition-matrix tests pin,
tests/test_composition_matrix.py) are unchanged.

Two stages, called in order by ``Runner.worker``:

  - :func:`parse_topology` — compute dtype, model-section keys
    (``pretrained``, MoE), the parallelism degrees (SP/TP/PP/microbatches/
    schedule/ZeRO) with their cross-constraints, and the constructed model.
  - :func:`parse_batch` — batch division (``local``/``world``,
    SURVEY §7 stage 4), grad accumulation, label smoothing, EMA; returns the
    per-host batch.

The actual mesh/step construction lives in :mod:`.paths` (the strategy
table keyed on the flags this module sets).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models import get_model
from ..parallel import DATA_AXIS
from ..parallel.sequence import SEQUENCE_AXIS

__all__ = [
    "parse_topology",
    "parse_batch",
    "parse_comm",
    "parse_fault_tolerance",
    "parse_elastic",
    "parse_integrity",
    "parse_telemetry",
]


def parse_topology(r, cfg: dict, train_cfg: dict, train_dataset) -> None:
    """Parse model + parallelism config onto Runner ``r`` and build
    ``r.model``.  Raises the documented ``ValueError`` for every unsupported
    combination (the composition matrix's source of truth)."""
    r.compute_dtype = {
        "float32": jnp.float32,
        "bfloat16": jnp.bfloat16,
    }[train_cfg.get("dtype", "float32")]
    # Model section: ``name`` is the reference's only key (:183-186);
    # extra keys are architecture hyperparameters forwarded to the zoo
    # (additive — e.g. embed_dim/depth/num_heads for TransformerLM).
    model_cfg = dict(cfg["model"])
    model_name = model_cfg.pop("name")
    r.model_name = model_name
    # Additive key ``model.pretrained``: initialize the run from a torch
    # ``state_dict`` checkpoint (torchvision layout for the ResNet family,
    # the twin layout of tests/test_torch_port_lm.py for TransformerLM) —
    # the user-facing form of the reference's TORCH_HOME model-zoo
    # weights (/root/reference/train.sh:2).  Ported via models/torch_port
    # at state construction (engine/paths.py); strict shape/name checking
    # raises descriptive errors instead of silently part-loading.
    r.pretrained = model_cfg.pop("pretrained", None)
    # The long-context LM task (beyond the reference, SURVEY.md §5.7):
    # first-class from the config surface — ``model.name: TransformerLM`` +
    # an LM dataset + optional ``training.sequence_parallelism``
    # (ring/Ulysses over a sequence mesh axis, parallel.sequence).
    r.is_lm = model_name.lower() == "transformerlm"
    # MoE (model.moe_experts > 0, ops/moe.py): trains on the GSPMD path
    # whatever the parallelism degrees — the routing einsums and the
    # sown aux loss need the partitioner's global-token view, and under
    # tensor_parallelism the stacked expert weights shard over the
    # model axis (expert parallelism).
    r.is_moe = r.is_lm and int(model_cfg.get("moe_experts", 0) or 0) > 0
    if r.pretrained and r.is_moe:
        # the torch-twin LM layout has no expert tensors — a part-load
        # would silently leave experts at random init
        raise ValueError(
            "model.pretrained does not support MoE models "
            "(no torch-twin layout for expert weights)"
        )
    r.sync_bn = bool(train_cfg["sync_bn"]) and r.distributed and not r.is_lm
    # ResNet-only model keys, validated BEFORE the LM/image split so an LM
    # config with either key gets the curated error, not a raw constructor
    # TypeError (tests/test_space_to_depth.py pins the messages).
    s2d = bool(model_cfg.pop("space_to_depth", False))
    bn_stat = model_cfg.pop("bn_stat_dtype", None)
    if bn_stat is not None and bn_stat not in ("float32", "bfloat16"):
        raise ValueError(
            f"model.bn_stat_dtype must be 'float32' or 'bfloat16', "
            f"got {bn_stat!r}"
        )
    if s2d or bn_stat:
        from ..models.resnet import RESNET_CONFIGS

        if model_name.lower() not in {k.lower() for k in RESNET_CONFIGS}:
            raise ValueError(
                f"model.space_to_depth / bn_stat_dtype are only wired "
                f"for the ResNet family (got model.name: {model_name})"
            )
    r.seq_par = int(train_cfg.get("sequence_parallelism", 1))
    r.tensor_par = int(train_cfg.get("tensor_parallelism", 1))
    # Additive key ``training.pipeline_parallelism``: GPipe microbatch
    # pipeline over a (data, stage) mesh (parallel/pipeline.py,
    # engine/pp_steps.py).  ``training.microbatches`` tunes the schedule
    # (default = stage count; the bubble fraction is (S-1)/(M+S-1)).
    r.pipe_par = int(train_cfg.get("pipeline_parallelism", 1))
    r.microbatches = int(train_cfg.get("microbatches", r.pipe_par))
    if "microbatches" in train_cfg and r.pipe_par <= 1:
        # silently ignoring the key would read as "microbatch streaming
        # enabled" — grad_accumulation is the non-pipelined equivalent
        raise ValueError(
            "training.microbatches requires pipeline_parallelism > 1 "
            "(use training.grad_accumulation for non-pipelined "
            "micro-batching)"
        )
    if (r.seq_par > 1 or r.tensor_par > 1 or r.pipe_par > 1) and not r.is_lm:
        raise ValueError(
            "training.sequence_parallelism / tensor_parallelism / "
            "pipeline_parallelism require model.name: TransformerLM"
        )
    if r.pipe_par > 1 and r.seq_par > 1 and r.tensor_par > 1:
        # the pipeline mesh supports ONE inner axis besides stage:
        # model (PP x TP) or sequence (PP x SP) — a 4-axis composition
        # is not wired (parallel/pipeline.make_pp_mesh)
        raise ValueError(
            "pipeline_parallelism x sequence_parallelism x "
            "tensor_parallelism (three-way) is not wired; pick "
            "PP x SP or PP x TP"
        )
    # Additive key ``training.pp_schedule``: microbatch schedule for the
    # pipeline step — "gpipe" (autodiff backward, O(M) activation
    # residuals) or "1f1b" (manual interleaved backward with per-stage
    # recompute, O(S) buffered microbatch inputs; engine/pp_steps.py).
    r.pp_schedule = str(train_cfg.get("pp_schedule", "gpipe"))
    if r.pp_schedule not in ("gpipe", "1f1b"):
        raise ValueError(
            f"training.pp_schedule must be 'gpipe' or '1f1b', "
            f"got {r.pp_schedule!r}"
        )
    if "pp_schedule" in train_cfg and r.pipe_par <= 1:
        raise ValueError("training.pp_schedule requires pipeline_parallelism > 1")
    if r.pipe_par > 1 and r.is_moe:
        # MoE blocks break the homogeneous stacked-layer layout the
        # pipeline step scans over, and its sown aux loss is discarded
        # by the manual per-stage block apply
        raise ValueError(
            "model.moe_experts does not compose with pipeline_parallelism"
        )
    if r.is_moe and int(model_cfg.get("moe_experts")) % r.tensor_par != 0:
        raise ValueError(
            f"model.moe_experts ({model_cfg.get('moe_experts')}) must be "
            f"divisible by training.tensor_parallelism ({r.tensor_par}) "
            "for an even expert split"
        )
    if r.microbatches < max(r.pipe_par, 1):
        raise ValueError(
            f"training.microbatches ({r.microbatches}) must be >= "
            f"pipeline_parallelism ({r.pipe_par})"
        )
    # Additive key ``training.zero``: ZeRO stage 0|1|2|3 (True = 1) —
    # stage 1 shards optimizer moments over the data axis, stage 2 adds
    # sharded gradient buffers, stage 3 shards the PARAMETERS themselves
    # (FSDP semantics; GSPMD LM path, parallel/tensor.py).  Parsed here
    # because it changes BOTH the path selection and the model's
    # attention mode.
    zero_cfg = train_cfg.get("zero", False)
    if isinstance(zero_cfg, bool):
        r.zero = 1 if zero_cfg else 0  # True = ZeRO-1 (back-compat)
    elif isinstance(zero_cfg, int) and zero_cfg in (0, 1, 2, 3):
        r.zero = zero_cfg
    else:
        raise ValueError(
            f"training.zero must be a bool or a stage in (0, 1, 2, 3), "
            f"got {zero_cfg!r}"
        )
    if r.zero and not r.is_lm:
        raise ValueError(
            "training.zero is only wired for the LM task (GSPMD path)"
        )
    # Additive key ``training.remat``: rematerialization policy for the
    # transformer blocks — ``none`` (default), ``block`` (full recompute,
    # nn.remat with nothing saveable), ``dots`` / ``dots_saveable``
    # (jax.checkpoint_policies: save matmul outputs, recompute
    # elementwise; ``dots_saveable`` additionally saves batch-dim dots
    # like attention scores).  A TRAINING-section alias of the model-level
    # ``model.remat``/``model.remat_policy`` pair so memory/recompute
    # sweeps live next to batch size in the recipe; setting both is a
    # loud conflict rather than a silent precedence rule.
    remat_cfg = train_cfg.get("remat", None)
    if remat_cfg is not None:
        if not r.is_lm:
            raise ValueError(
                "training.remat is only wired for the LM task "
                "(model.name: TransformerLM)"
            )
        if "remat" in model_cfg or "remat_policy" in model_cfg:
            raise ValueError(
                "set either training.remat or model.remat/"
                "model.remat_policy, not both"
            )
        remat_map = {
            "none": (False, "nothing"),
            "block": (True, "nothing"),
            "dots": (True, "dots"),
            "dots_saveable": (True, "dots_saveable"),
        }
        if remat_cfg not in remat_map:
            raise ValueError(
                f"training.remat must be one of {sorted(remat_map)}, "
                f"got {remat_cfg!r}"
            )
        model_cfg["remat"], model_cfg["remat_policy"] = remat_map[remat_cfg]
    if r.zero >= 3 and r.pipe_par > 1:
        # FSDP-scattered params would need a stage-stacked scattered
        # layout inside the manual shard_map — not wired (ZeRO-1/2 do
        # compose with the pipeline)
        raise ValueError(
            f"training.zero: {r.zero} does not compose with "
            "pipeline_parallelism — use zero: 1 or 2 under the pipeline"
        )
    if r.is_lm:
        for key, par in (
            ("sequence_parallelism", r.seq_par),
            ("tensor_parallelism", r.tensor_par),
            ("pipeline_parallelism", r.pipe_par),
        ):
            if par < 1 or jax.local_device_count() % par != 0:
                # the host-batch layout (and
                # make_array_from_process_local_data) assumes each host
                # holds whole shard groups
                raise ValueError(
                    f"training.{key} ({par}) must divide the local "
                    f"device count ({jax.local_device_count()})"
                )
        non_data_par = r.seq_par * r.tensor_par * r.pipe_par
        if jax.local_device_count() % non_data_par != 0:
            # combined: one data shard spans a seq x tensor x pipe
            # device group — the whole group must fit within a host or
            # units_local becomes 0 and the host batch degenerates
            raise ValueError(
                f"sequence_parallelism x tensor_parallelism x "
                f"pipeline_parallelism ({r.seq_par} x {r.tensor_par}"
                f" x {r.pipe_par}) must divide the local device count "
                f"({jax.local_device_count()})"
            )
        sample_inp, _ = train_dataset[0]
        r.seq_len = int(sample_inp.shape[0])
        if r.seq_len % r.seq_par != 0:
            raise ValueError(
                f"dataset.seq_len ({r.seq_len}) must be divisible by "
                f"training.sequence_parallelism ({r.seq_par})"
            )
        model_cfg.setdefault("max_len", r.seq_len)
        if (
            r.seq_par > 1
            and r.tensor_par == 1
            and r.pipe_par == 1
            and not r.zero
            and not r.is_moe
        ):
            # ring-attention path only; the GSPMD path (tensor_par or
            # zero or MoE) keeps seq_axis=None and lets the partitioner
            # distribute, and the PP x SP path builds its own
            # seq_axis'd stage blocks (pp_steps._stage_applies) — a
            # seq_axis model requires shard_map
            model_cfg.setdefault("seq_axis", SEQUENCE_AXIS)
        r.model = get_model(
            model_name,
            num_classes=cfg["dataset"]["n_classes"],
            dtype=r.compute_dtype,
            **model_cfg,
        )
        if r.is_moe and not (1 <= r.model.moe_every <= r.model.depth):
            # read from the CONSTRUCTED model, not re-hardcoded class
            # defaults (r2 review): moe_every 0 would div-by-zero at
            # init; > depth silently trains a fully dense model while
            # every MoE restriction still applies
            raise ValueError(
                f"model.moe_every ({r.model.moe_every}) must be in "
                f"[1, depth={r.model.depth}] (moe_every > depth "
                "would make no block MoE)"
            )
    else:
        # reference behavior: only ``model.name`` is read for the image
        # zoo — extra keys stay ignored (forwarding them would crash
        # ResNet/ViT constructors on e.g. annotation-only keys).  Two
        # sanctioned additive keys (validated above, before the LM split):
        # ``model.space_to_depth`` and ``model.bn_stat_dtype``.
        extra = {}
        if s2d:
            extra["space_to_depth"] = True
        if bn_stat:
            extra["bn_stat_dtype"] = {
                "float32": jnp.float32, "bfloat16": jnp.bfloat16,
            }[bn_stat]
        r.model = get_model(
            model_name,
            num_classes=cfg["dataset"]["n_classes"],
            axis_name=DATA_AXIS if r.sync_bn else None,
            dtype=r.compute_dtype,
            **extra,
        )


def parse_batch(r, train_cfg: dict) -> int:
    """Batch division + per-step micro-batching keys; returns the per-host
    batch size.  Reference parity notes inline (train_distributed.py:194)."""
    batch_size = train_cfg["batch_size"]
    local_devices = jax.local_device_count()
    # SURVEY §7 stage 4 decision, config-gated (additive key, unknown to
    # the reference schema):
    #   batch_division: local  — reference parity (:194): per-device batch
    #       divides by the LOCAL device count, so the global batch scales
    #       with node count (default).
    #   batch_division: world  — divide by the WORLD device count, so cfg
    #       batch_size IS the global batch at any topology.
    division = train_cfg.get("batch_division", "local")
    if division not in ("local", "world"):
        raise ValueError(
            f"training.batch_division must be 'local' or 'world', got {division!r}"
        )
    # Batch rows shard over the DATA axis only; each data shard spans a
    # seq_par x tensor_par device group (either may be 1), so the
    # division unit is a data shard, not a device.
    non_data = r.seq_par * r.tensor_par * r.pipe_par if r.is_lm else 1
    units_local = local_devices // non_data
    units_world = r.world_size // non_data
    # Additive key ``training.grad_accumulation``: per-step micro-batch
    # count (lax.scan inside the compiled step — activation memory / N,
    # identical update math; engine/steps.py).
    r.grad_accum = int(train_cfg.get("grad_accumulation", 1))
    if r.grad_accum < 1:
        raise ValueError(f"grad_accumulation must be >= 1, got {r.grad_accum}")
    if r.grad_accum > 1 and r.pipe_par > 1:
        raise ValueError(
            "grad_accumulation is redundant under pipeline_parallelism — "
            "raise training.microbatches instead (same memory effect, "
            "and it also shrinks the pipeline bubble)"
        )
    # Additive keys: torch-convention label smoothing + params EMA
    # (evaluation runs with the EMA weights when enabled).
    r.label_smoothing = float(train_cfg.get("label_smoothing", 0.0))
    if not (0.0 <= r.label_smoothing < 1.0):
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {r.label_smoothing}"
        )
    ema_cfg = train_cfg.get("ema")
    r.ema_decay = float(ema_cfg["decay"]) if ema_cfg else None
    if r.ema_decay is not None and not (0.0 < r.ema_decay < 1.0):
        raise ValueError(f"ema.decay must be in (0, 1), got {r.ema_decay}")
    if r.ema_decay is not None and r.is_lm:
        raise ValueError("training.ema is only wired for the image task")
    if r.distributed:
        divisor = units_world if division == "world" else units_local
        per_device_batch = batch_size // max(divisor, 1)
        if per_device_batch == 0 or divisor == 0:
            raise ValueError(
                f"batch_size {batch_size} < {division} batch-shard count {divisor}"
            )
        if division == "world" and batch_size % divisor != 0:
            # the mode's whole contract is "cfg batch_size IS the global
            # batch" — a silent floor would break it, so fail loudly
            raise ValueError(
                f"batch_division: world requires batch_size ({batch_size}) "
                f"divisible by the world batch-shard count ({divisor})"
            )
        host_batch = per_device_batch * units_local
    else:
        host_batch = batch_size
        per_device_batch = batch_size
    if per_device_batch % r.grad_accum != 0:
        # fail fast like every other config error, not at jit trace time
        raise ValueError(
            f"per-shard batch ({per_device_batch}) not divisible by "
            f"training.grad_accumulation ({r.grad_accum})"
        )
    if r.pipe_par > 1 and per_device_batch % r.microbatches != 0:
        raise ValueError(
            f"per-shard batch ({per_device_batch}) not divisible by "
            f"training.microbatches ({r.microbatches})"
        )
    return host_batch


def parse_comm(r, train_cfg: dict) -> None:
    """Parse the additive ``training.comm`` section (off by default) onto
    the runner — bucketed, backward-overlapped gradient reduction
    (engine/comm.py):

    .. code-block:: yaml

        training:
            comm:
                overlap: true         # bucketed explicit reduction; false
                                      # compiles the exact legacy step
                bucket_mb: 25         # flat-bucket size bound (MiB)
                reduce_dtype: null    # null | float32 | bfloat16 — cast
                                      # buckets before the collective
                                      # (bfloat16 halves wire bytes; only
                                      # null carries parity oracles)

    ``overlap`` is wired for the image-dp and ring-sp paths (and, with
    ``zero: 1``, selects the manual reduce-scatter ZeRO-1 path); the GSPMD
    and pipeline paths schedule their own communication and raise the
    documented error.
    """
    from .comm import CommConfig

    cm = train_cfg.get("comm") or {}
    unknown = set(cm) - {"overlap", "bucket_mb", "reduce_dtype"}
    if unknown:
        raise ValueError(
            f"training.comm: unknown key(s) {sorted(unknown)} "
            "(want overlap/bucket_mb/reduce_dtype)"
        )
    bucket_mb = float(cm.get("bucket_mb", 25.0))
    if bucket_mb <= 0:
        raise ValueError(
            f"training.comm.bucket_mb must be > 0, got {bucket_mb}"
        )
    reduce_dtype = cm.get("reduce_dtype")
    if reduce_dtype is not None and reduce_dtype not in (
        "float32", "bfloat16",
    ):
        raise ValueError(
            "training.comm.reduce_dtype must be float32 or bfloat16 (or "
            f"null for the gradient dtype), got {reduce_dtype!r}"
        )
    r.comm = CommConfig(
        overlap=bool(cm.get("overlap", False)),
        bucket_mb=bucket_mb,
        reduce_dtype=reduce_dtype,
    )


def parse_fault_tolerance(r, train_cfg: dict) -> None:
    """Parse the additive ``training.fault_tolerance`` section (all off by
    default — reference parity) onto the runner:

    .. code-block:: yaml

        training:
            fault_tolerance:
                anomaly:               # anomaly-step guard (engine/steps.py)
                    enabled: true      # implied by a non-empty section
                    grad_norm_factor: 10.0   # 0 = non-finite-only check
                    window: 64         # trailing-median history length
                    max_consecutive: 5 # then roll back to last checkpoint
                watchdog:              # hung-step watchdog (engine/watchdog.py)
                    enabled: true
                    factor: 10.0       # x trailing-median step time
                    min_seconds: 60.0  # floor (compiles, first steps)
                    poll_seconds: null # default min_seconds / 4
                    checkpoint_and_exit: false  # fire the PreemptionGuard
                fault_spec: null       # injection script (engine/fault.py;
                                       # the PDT_FAULT_SPEC env var wins)
    """
    ft = train_cfg.get("fault_tolerance") or {}
    unknown = set(ft) - {"anomaly", "watchdog", "fault_spec"}
    if unknown:
        raise ValueError(
            f"training.fault_tolerance: unknown key(s) {sorted(unknown)} "
            "(want anomaly/watchdog/fault_spec)"
        )

    an = ft.get("anomaly") or {}
    unknown = set(an) - {"enabled", "grad_norm_factor", "window", "max_consecutive"}
    if unknown:
        raise ValueError(
            f"training.fault_tolerance.anomaly: unknown key(s) "
            f"{sorted(unknown)} (want enabled/grad_norm_factor/window/"
            "max_consecutive)"
        )
    r.anomaly_enabled = bool(an) and bool(an.get("enabled", True))
    r.anomaly_factor = float(an.get("grad_norm_factor", 10.0))
    r.anomaly_window = int(an.get("window", 64))
    r.anomaly_max_consec = int(an.get("max_consecutive", 5))
    if r.anomaly_factor < 0:
        raise ValueError(
            "fault_tolerance.anomaly.grad_norm_factor must be >= 0 "
            f"(0 = non-finite-only), got {r.anomaly_factor}"
        )
    if r.anomaly_window < 1:
        raise ValueError(
            f"fault_tolerance.anomaly.window must be >= 1, got {r.anomaly_window}"
        )
    if r.anomaly_max_consec < 1:
        raise ValueError(
            "fault_tolerance.anomaly.max_consecutive must be >= 1, got "
            f"{r.anomaly_max_consec}"
        )

    wd = ft.get("watchdog") or {}
    unknown = set(wd) - {
        "enabled", "factor", "min_seconds", "poll_seconds", "window",
        "warmup", "checkpoint_and_exit",
    }
    if unknown:
        raise ValueError(
            f"training.fault_tolerance.watchdog: unknown key(s) "
            f"{sorted(unknown)} (want enabled/factor/min_seconds/"
            "poll_seconds/window/warmup/checkpoint_and_exit)"
        )
    r.watchdog_enabled = bool(wd) and bool(wd.get("enabled", True))
    r.watchdog_factor = float(wd.get("factor", 10.0))
    r.watchdog_min_seconds = float(wd.get("min_seconds", 60.0))
    r.watchdog_poll = (
        float(wd["poll_seconds"]) if wd.get("poll_seconds") is not None else None
    )
    r.watchdog_window = int(wd.get("window", 32))
    r.watchdog_warmup = int(wd.get("warmup", 3))
    r.watchdog_exit = bool(wd.get("checkpoint_and_exit", False))
    if r.watchdog_enabled:
        if r.watchdog_factor <= 1.0:
            raise ValueError(
                "fault_tolerance.watchdog.factor must be > 1, got "
                f"{r.watchdog_factor}"
            )
        if r.watchdog_min_seconds <= 0:
            raise ValueError(
                "fault_tolerance.watchdog.min_seconds must be > 0, got "
                f"{r.watchdog_min_seconds}"
            )
        if r.watchdog_poll is not None and r.watchdog_poll <= 0:
            raise ValueError(
                "fault_tolerance.watchdog.poll_seconds must be > 0, got "
                f"{r.watchdog_poll}"
            )
        if r.watchdog_warmup < 1:
            raise ValueError(
                "fault_tolerance.watchdog.warmup must be >= 1, got "
                f"{r.watchdog_warmup}"
            )

    spec = ft.get("fault_spec")
    r.fault_spec = str(spec) if spec else None
    if r.fault_spec:
        # validate the spec HERE, at config-parse time: an unknown kind or
        # malformed entry raises the descriptive ValueError immediately
        # instead of silently never firing (engine/fault.py grammar)
        from .fault import FaultInjector

        FaultInjector(r.fault_spec)


def parse_elastic(r, train_cfg: dict) -> None:
    """Parse the additive ``training.elastic`` section (off by default) onto
    the runner — the multi-host elastic-recovery layer (engine/elastic.py):

    .. code-block:: yaml

        training:
            elastic:
                enabled: true          # implied by a non-empty section
                dir: null              # heartbeat dir (default:
                                       #   <checkpoint.dir>/heartbeats)
                heartbeat_interval: 0.5  # seconds between beats
                timeout: 5.0           # peer presumed dead past this
                startup_grace: null    # allowance for peers that have not
                                       # written a first beat (default
                                       # max(30, 4 x timeout))
    """
    el = train_cfg.get("elastic") or {}
    unknown = set(el) - {
        "enabled", "dir", "heartbeat_interval", "timeout", "startup_grace",
    }
    if unknown:
        raise ValueError(
            f"training.elastic: unknown key(s) {sorted(unknown)} "
            "(want enabled/dir/heartbeat_interval/timeout/startup_grace)"
        )
    r.elastic_enabled = bool(el) and bool(el.get("enabled", True))
    r.elastic_dir = el.get("dir")
    r.elastic_heartbeat_interval = float(el.get("heartbeat_interval", 0.5))
    r.elastic_timeout = float(el.get("timeout", 5.0))
    r.elastic_startup_grace = (
        float(el["startup_grace"]) if el.get("startup_grace") is not None
        else None
    )
    if r.elastic_enabled:
        if r.elastic_heartbeat_interval <= 0:
            raise ValueError(
                "training.elastic.heartbeat_interval must be > 0, got "
                f"{r.elastic_heartbeat_interval}"
            )
        if r.elastic_timeout <= r.elastic_heartbeat_interval:
            raise ValueError(
                f"training.elastic.timeout ({r.elastic_timeout}) must exceed "
                f"heartbeat_interval ({r.elastic_heartbeat_interval})"
            )
        ck = train_cfg.get("checkpoint") or {}
        if not (r.elastic_dir or ck.get("dir")):
            # without either dir there is nowhere to put heartbeats, and
            # without a checkpoint the detected peer loss has nothing to
            # save — the layer would detect and then lose the run anyway
            raise ValueError(
                "training.elastic requires training.checkpoint.dir (the "
                "heartbeat dir defaults to <checkpoint.dir>/heartbeats and "
                "peer loss triggers a checkpoint-and-exit), or an explicit "
                "training.elastic.dir"
            )


def parse_integrity(r, train_cfg: dict) -> None:
    """Parse the additive ``training.integrity`` section (off by default)
    onto the runner — the silent-data-corruption sentinel
    (engine/integrity.py):

    .. code-block:: yaml

        training:
            integrity:
                enabled: true         # implied by a non-empty section
                check_interval: 100   # steps between fingerprint votes
                replicas: null        # voters; null = real process count,
                                      # > process count simulates peers
                                      # (the 1-device injection/test path)
                max_consecutive: 2    # diverged checks before a replica is
                                      # PERSISTENTLY corrupt (quarantine)
    """
    ig = train_cfg.get("integrity") or {}
    unknown = set(ig) - {
        "enabled", "check_interval", "replicas", "max_consecutive",
    }
    if unknown:
        raise ValueError(
            f"training.integrity: unknown key(s) {sorted(unknown)} "
            "(want enabled/check_interval/replicas/max_consecutive)"
        )
    r.integrity_enabled = bool(ig) and bool(ig.get("enabled", True))
    r.integrity_check_interval = int(ig.get("check_interval", 100))
    r.integrity_replicas = (
        int(ig["replicas"]) if ig.get("replicas") is not None else None
    )
    r.integrity_max_consecutive = int(ig.get("max_consecutive", 2))
    if r.integrity_enabled:
        if r.integrity_check_interval < 1:
            raise ValueError(
                "training.integrity.check_interval must be >= 1, got "
                f"{r.integrity_check_interval}"
            )
        if r.integrity_replicas is not None and r.integrity_replicas < 1:
            raise ValueError(
                "training.integrity.replicas must be >= 1, got "
                f"{r.integrity_replicas}"
            )
        if r.integrity_max_consecutive < 1:
            raise ValueError(
                "training.integrity.max_consecutive must be >= 1, got "
                f"{r.integrity_max_consecutive}"
            )


def parse_telemetry(r, train_cfg: dict) -> None:
    """Parse the additive ``training.telemetry`` section (ON by default —
    the in-memory registry/goodput/retrace layer is near-free and files are
    only written when ``dir`` is set) onto the runner (telemetry/):

    .. code-block:: yaml

        training:
            telemetry:
                enabled: true          # in-memory instruments + summary
                dir: null              # spans_rank<k>.jsonl, snapshots.jsonl,
                                       # profile/ captures land here
                snapshot_interval: 100 # steps between JSONL/TB snapshots
                span_ring: 256         # in-memory spans kept for diagnostics
                tensorboard: true      # mirror snapshots into the TB writer
                retrace_warn: 3        # compiles per fn before the storm warn
                capture:               # on-demand jax.profiler window
                    signal: SIGUSR2    # arm via kill -USR2 <pid> (null = off)
                    n_iters: 5         # window length in steps
                    at_iter: null      # config-triggered arm at this step
                    dir: null          # default <telemetry.dir>/profile
    """
    tl = train_cfg.get("telemetry") or {}
    unknown = set(tl) - {
        "enabled", "dir", "snapshot_interval", "span_ring", "tensorboard",
        "retrace_warn", "capture",
    }
    if unknown:
        raise ValueError(
            f"training.telemetry: unknown key(s) {sorted(unknown)} "
            "(want enabled/dir/snapshot_interval/span_ring/tensorboard/"
            "retrace_warn/capture)"
        )
    r.telemetry_enabled = bool(tl.get("enabled", True))
    r.telemetry_dir = tl.get("dir")
    r.telemetry_interval = int(tl.get("snapshot_interval", 100))
    r.telemetry_span_ring = int(tl.get("span_ring", 256))
    r.telemetry_tensorboard = bool(tl.get("tensorboard", True))
    r.telemetry_retrace_warn = int(tl.get("retrace_warn", 3))
    if r.telemetry_interval < 1:
        raise ValueError(
            "training.telemetry.snapshot_interval must be >= 1, got "
            f"{r.telemetry_interval}"
        )
    if r.telemetry_span_ring < 1:
        raise ValueError(
            "training.telemetry.span_ring must be >= 1, got "
            f"{r.telemetry_span_ring}"
        )
    if r.telemetry_retrace_warn < 1:
        raise ValueError(
            "training.telemetry.retrace_warn must be >= 1, got "
            f"{r.telemetry_retrace_warn}"
        )

    cap = tl.get("capture") or {}
    unknown = set(cap) - {"signal", "n_iters", "at_iter", "dir"}
    if unknown:
        raise ValueError(
            f"training.telemetry.capture: unknown key(s) {sorted(unknown)} "
            "(want signal/n_iters/at_iter/dir)"
        )
    from ..telemetry.capture import parse_signal

    # an explicit capture section arms the signal path by default; without
    # one nothing is installed (signal handlers are process-global state)
    r.telemetry_capture_signal = (
        parse_signal(cap.get("signal", "SIGUSR2")) if cap else None
    )
    r.telemetry_capture_iters = int(cap.get("n_iters", 5))
    r.telemetry_capture_at_iter = (
        int(cap["at_iter"]) if cap.get("at_iter") is not None else None
    )
    r.telemetry_capture_dir = cap.get("dir")
    if r.telemetry_capture_iters < 1:
        raise ValueError(
            "training.telemetry.capture.n_iters must be >= 1, got "
            f"{r.telemetry_capture_iters}"
        )
    if cap and not (
        r.telemetry_capture_dir or r.telemetry_dir
    ):
        raise ValueError(
            "training.telemetry.capture needs somewhere to write traces: "
            "set training.telemetry.dir or training.telemetry.capture.dir"
        )
