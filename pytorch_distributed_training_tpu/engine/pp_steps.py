"""Compiled pipeline-parallel (DP x PP) LM training step.

GPipe microbatch schedule as ONE ``shard_map``-ed XLA program on a
``(data, stage)`` mesh — see :mod:`..parallel.pipeline` for the layout and
the exactness argument.  The reference has no pipeline axis at all
(SURVEY.md §2.4); this composes with data parallelism the same way the SP
and TP steps do and plugs into the same ``Runner`` contract.

Design notes (TPU/XLA):
  - the tick loop is a ``lax.scan`` (static trip count ``M + S - 1``), so
    the whole schedule — including the bubble — compiles once; no Python
    per-tick dispatch.
  - inter-stage transfer is a single ``ppermute`` per tick over the
    ``stage`` axis (nearest-neighbor ICI DMA), which XLA overlaps with the
    next tick's compute where the dependence allows.
  - under SPMD every stage runs the same program TEXT, but embedding and
    head math are gated by ``lax.cond`` on the (device-varying) stage
    index, so only stage 0 executes the embed and only the last stage
    executes the head+loss (in the gpipe scan and the eval step the head
    gate additionally folds in tick validity; the 1F1B slots gate on the
    stage index alone and mask the results per slot) — XLA's conditional
    runs just the taken branch at runtime.  The head is NOT negligible at large vocab
    (at the shipped TransformerLM-pp.yml scale it is ~40% of a stage's
    per-tick FLOPs): before round 5 every stage computed embed+head and
    masked the results, putting embed+blocks+head on the lockstep critical
    path; the conds cut that to max(embed+blocks, blocks+head) and
    interior stages run blocks only.  The AD hazard and its resolution
    (shared params pcast to stage-varying so the cotangent stage-psum
    cannot land inside a single-stage branch) are documented at the cond
    sites.  The blocks were never duplicated — each stage applies only
    its own layer shard.
  - tick inputs are index-clipped to real microbatches (never garbage), so
    bubble ticks compute on valid data and masking alone guarantees
    correctness — no NaN-through-``where`` hazards.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer_lm import DecoderBlock
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS
from ..parallel.pipeline import STAGE_AXIS, pp_param_specs
from ..parallel.tensor import mirror_opt_fields
from ..telemetry.retrace import register_compiled
from ..utils.vma import mark_varying
from .sp_steps import lm_loss_local
from .steps import TrainState

__all__ = ["build_pp_lm_train_step", "build_pp_lm_eval_step"]

# Step-family label for the static collective-order oracle (see
# analysis/collectives.py and PERF.md).
PDT_COLLECTIVE_FAMILY = "pp"


def _stage_applies(model, seq_axis=None):
    """(embed, blocks, head) closures over a TransformerLM's hyperparams.

    Reuses the model's own flax modules for the shared pieces so the math is
    bit-identical to ``TransformerLM.__call__`` (models/transformer_lm.py).
    With ``seq_axis`` set (PP x SP), each stage's blocks run ring attention
    over that mesh axis and the positional embedding is sliced to the
    sequence shard — the same construction TransformerLM applies when its
    own ``seq_axis`` is set (models/transformer_lm.py:119-130).
    """
    block = DecoderBlock(
        num_heads=model.num_heads,
        mlp_ratio=model.mlp_ratio,
        seq_axis=seq_axis,
        seq_impl=model.seq_impl,
        dtype=model.dtype,
    )
    ln = nn.LayerNorm(dtype=model.dtype)
    head = nn.Dense(model.vocab_size, dtype=jnp.float32)

    def embed(shared, tokens):
        x = jnp.take(shared["tok_embedding"], tokens, axis=0).astype(model.dtype)
        s = tokens.shape[1]
        if seq_axis is None:
            pe = shared["pos_embedding"][:s]
        else:
            off = jax.lax.axis_index(seq_axis) * s
            pe = jax.lax.dynamic_slice_in_dim(
                shared["pos_embedding"], off, s, axis=0
            )
        return x + pe[None].astype(model.dtype)

    def apply_blocks(blocks_local, x):
        def layer(x, p):
            return block.apply({"params": p}, x), None

        if model.remat:
            from ..models.transformer_lm import resolve_remat_policy

            f = jax.checkpoint(
                layer, policy=resolve_remat_policy(model.remat_policy)
            )
        else:
            f = layer
        x, _ = jax.lax.scan(f, x, blocks_local)
        return x

    def apply_head(shared, x):
        h = ln.apply({"params": shared["ln"]}, x)
        return head.apply({"params": shared["head"]}, h)

    return embed, apply_blocks, apply_head


def _sim_1f1b(n_micro: int, n_stages: int):
    """Static 1F1B (PipeDream-Flush) tick schedule, event-simulated.

    Every tick each stage has one F slot and one B slot (the compiled tick
    body always executes both, masked — SPMD lockstep).  A stage runs its
    next forward when the previous stage finished that microbatch at a
    strictly earlier tick AND its in-flight count is under the 1F1B window
    ``n_stages - s`` (the property that caps activation memory at O(S)
    microbatches instead of GPipe's O(M)); it runs its next backward when
    its own forward and the next stage's backward for that microbatch are
    done.  Greedy earliest-tick scheduling of those dependencies IS 1F1B:
    the window forces backwards to interleave as soon as they unblock.

    Returns ``(f_mb, f_on, b_mb, b_on, depth)``: [T, S] int/bool arrays
    (tick t, stage s) plus the ring-buffer depth the activation buffers
    need (max concurrently-live intervals measured on the simulated
    schedule — FIFO per stage, so ``mb % depth`` slots cannot collide).
    """
    M, S = int(n_micro), int(n_stages)
    fwd_done = [[-1] * M for _ in range(S)]
    bwd_done = [[-1] * M for _ in range(S)]
    next_f, next_b = [0] * S, [0] * S
    rows_f, rows_b = [], []
    t = 0
    while any(nb < M for nb in next_b):
        f_row, b_row = [], []
        for s in range(S):
            m = next_f[s]
            can_f = (
                m < M
                and (s == 0 or (0 <= fwd_done[s - 1][m] < t))
                and (next_f[s] - next_b[s]) < (S - s)
            )
            mb = next_b[s]
            can_b = (
                mb < M
                and 0 <= fwd_done[s][mb] < t
                and (s == S - 1 or (0 <= bwd_done[s + 1][mb] < t))
            )
            f_row.append((m if can_f else 0, can_f))
            b_row.append((mb if can_b else 0, can_b))
        for s in range(S):
            m, on = f_row[s]
            if on:
                fwd_done[s][m] = t
                next_f[s] += 1
            m, on = b_row[s]
            if on:
                bwd_done[s][m] = t
                next_b[s] += 1
        rows_f.append(f_row)
        rows_b.append(b_row)
        t += 1
        if t > 4 * (M + S) + 8:
            raise AssertionError("1F1B schedule simulation did not converge")

    T = t

    def max_overlap(intervals):
        """Max number of [a, c] intervals alive at any tick."""
        best = 0
        for tick in range(T + 1):
            best = max(best, sum(1 for a, c in intervals if a <= tick <= c))
        return best

    depth = 1
    for s in range(S):
        # x arrival (prev stage's fwd) .. consumed by this stage's bwd
        arr = [
            ((fwd_done[s - 1][m] if s else fwd_done[s][m]), bwd_done[s][m])
            for m in range(M)
        ]
        # dy arrival (next stage's bwd) .. consumed by this stage's bwd
        dy = (
            [(bwd_done[s + 1][m], bwd_done[s][m]) for m in range(M)]
            if s < S - 1
            else []
        )
        # saved x_in: written at this stage's fwd .. read at its bwd
        sav = [(fwd_done[s][m], bwd_done[s][m]) for m in range(M)]
        depth = max(depth, max_overlap(arr), max_overlap(dy), max_overlap(sav))

    f_mb = np.array([[r[s][0] for s in range(S)] for r in rows_f], np.int32)
    f_on = np.array([[r[s][1] for s in range(S)] for r in rows_f], bool)
    b_mb = np.array([[r[s][0] for s in range(S)] for r in rows_b], np.int32)
    b_on = np.array([[r[s][1] for s in range(S)] for r in rows_b], bool)
    return f_mb, f_on, b_mb, b_on, depth


def _schedule(n_micro: int, n_stages: int):
    """Static GPipe tick schedule: (feed index, feed mask, emit index,
    emit mask).

    Tick ``t``: stage 0 ingests microbatch ``t`` (clipped — the index stays
    in range during drain ticks, but the feed mask goes false there so the
    embed cond is skipped entirely rather than recomputed and discarded),
    the last stage finishes microbatch ``t - (S-1)``; its loss only counts
    once ``t`` has passed the fill bubble.
    """
    ticks = np.arange(n_micro + n_stages - 1)
    feed_idx = np.clip(ticks, 0, n_micro - 1)
    feed_valid = ticks < n_micro
    emit_idx = np.clip(ticks - (n_stages - 1), 0, n_micro - 1)
    emit_valid = ticks >= n_stages - 1
    return (
        jnp.asarray(feed_idx, jnp.int32),
        jnp.asarray(feed_valid),
        jnp.asarray(emit_idx, jnp.int32),
        jnp.asarray(emit_valid),
    )


def build_pp_lm_train_step(
    model,
    optimizer,
    lr_fn: Callable,
    mesh: Mesh,
    num_microbatches: int,
    donate: bool = True,
    label_smoothing: float = 0.0,
    schedule: str = "gpipe",
    seq_axis=None,
    zero: int = 0,
):
    """Compile one DP x PP (optionally x TP) LM iteration.

    ``model``: a :class:`TransformerLM` (``seq_axis=None``); its params must
    be in the pipeline layout (:func:`..parallel.pipeline.pp_stack_params`).
    The optimizer must be elementwise per-leaf (SGD / AdamW — LARS computes
    per-parameter norms, which would span the stacked layer axis and change
    semantics; the Runner rejects that combination).

    ``schedule``:
      - ``"gpipe"``: forward scan differentiated by autodiff (module
        docstring) — activation residuals for all M+S-1 ticks stay live
        through the backward, O(M) microbatch activations per stage.
      - ``"1f1b"``: manual interleaved schedule (:func:`_sim_1f1b`) with a
        hand-written backward: each tick runs one masked forward slot and
        one masked backward slot; the backward slot re-runs its stage's
        forward under ``jax.vjp`` at the saved stage INPUT (recompute —
        only O(S) microbatch inputs are ever buffered, 1F1B's memory
        property) and pulls the activation cotangent backwards along the
        reverse ring.  Same update math as gpipe to float tolerance
        (tests/test_pipeline_parallel.py pins both against the single-chip
        oracle).

    If ``mesh`` also carries a ``model`` axis (size > 1), the step runs
    shard_map-manual over (data, stage) only and leaves ``model`` to the
    GSPMD partitioner — Megatron tensor parallelism INSIDE each pipeline
    stage, from the same sharding rules as the pure-TP path
    (parallel/tensor.py); see :func:`..parallel.pipeline.pp_tp_state_shardings`.

    Returns ``compile_for(state)`` pinning the state's stage shardings,
    mirroring :func:`..engine.tp_steps.build_tp_lm_train_step`.
    """
    n_stages = mesh.shape[STAGE_AXIS]
    n_data = mesh.shape[DATA_AXIS]
    n_seq = mesh.shape[seq_axis] if seq_axis else 1
    loss_axes = (DATA_AXIS, STAGE_AXIS) + ((seq_axis,) if seq_axis else ())
    M = int(num_microbatches)
    if M < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M}")
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    embed, apply_blocks, apply_head = _stage_applies(model, seq_axis)
    feed_idx, feed_valid, emit_idx, emit_valid = _schedule(M, n_stages)

    def grads_gpipe(params, tokens, labels):
        b_local, seq = tokens.shape
        if b_local % M != 0:
            raise ValueError(
                f"per-shard batch {b_local} not divisible by "
                f"num_microbatches {M}"
            )
        mb = b_local // M
        if seq * n_seq > model.max_len:
            raise ValueError(
                f"global sequence {seq * n_seq} exceeds max_len {model.max_len}"
            )
        global_tokens = b_local * seq * n_data * n_seq
        stage = jax.lax.axis_index(STAGE_AXIS)
        tok = tokens.reshape(M, mb, seq)
        lab = labels.reshape(M, mb, seq)
        perm = [(s, (s + 1) % n_stages) for s in range(n_stages)]

        def loss_fn(p):
            # Shared params are promoted to stage-varying BEFORE the conds
            # below.  Without this, AD would place the stage-psum of their
            # cotangent inside the cond branch (only the predicate-true
            # stage executes it -> the other stages never join the
            # all-reduce: deadlock).  After the pcast, only data/seq
            # reductions remain inside the branches — safe, because every
            # peer along those axes shares the same stage coordinate and
            # takes the same branch — and the stage-psum runs once at the
            # pcast transpose, outside the scan entirely.
            shared = mark_varying(p["shared"], (STAGE_AXIS,))

            def tick(carry, xs):
                x, loss_acc = carry
                f_i, f_valid, e_i, valid = xs
                is_last = stage == n_stages - 1
                # embed only on stage 0's feed ticks, head+loss only on the
                # last stage's valid ticks: lax.cond with a device-varying
                # predicate SKIPS the untaken branch at runtime, so interior
                # stages run blocks only — the per-tick critical path drops
                # from embed+blocks+head on every stage (the round-4 ~40%
                # duplication) to max(embed+blocks, blocks+head).  Folding
                # feed validity in drops the S-1 drain-tick embeds whose
                # output the clipped re-feed previously computed and threw
                # away (their loss contribution was already masked, so
                # gradients are unchanged).
                x_in = jax.lax.cond(
                    (stage == 0) & f_valid,
                    lambda: mark_varying(embed(shared, tok[f_i]), loss_axes),
                    lambda: x,
                )
                y = apply_blocks(p["blocks"], x_in)

                def head_loss():
                    logits = apply_head(shared, y)
                    return mark_varying(
                        lm_loss_local(
                            logits, lab[e_i], global_tokens, label_smoothing
                        ),
                        loss_axes,
                    )

                part = jax.lax.cond(
                    valid & is_last,
                    head_loss,
                    lambda: mark_varying(jnp.float32(0.0), loss_axes),
                )
                loss_acc = loss_acc + part
                x_next = jax.lax.ppermute(y, STAGE_AXIS, perm)
                return (x_next, loss_acc), None

            # the carry is device-varying (each stage holds a different
            # activation), so the constant initial carry must be promoted
            x0, l0 = mark_varying(
                (jnp.zeros((mb, seq, model.embed_dim), model.dtype),
                 jnp.float32(0.0)),
                loss_axes,
            )
            (_, loss_sum), _ = jax.lax.scan(
                tick, (x0, l0), (feed_idx, feed_valid, emit_idx, emit_valid)
            )
            # global mean CE as a replicated scalar: only the last stage
            # holds nonzero partials, the psum both totals them over data
            # (and sequence, under PP x SP) and broadcasts over stage —
            # differentiating THIS is what makes the pipeline backward
            # exact (module docstring)
            return jax.lax.psum(loss_sum, loss_axes)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return grads, loss

    def grads_1f1b(params, tokens, labels):
        b_local, seq = tokens.shape
        if b_local % M != 0:
            raise ValueError(
                f"per-shard batch {b_local} not divisible by "
                f"num_microbatches {M}"
            )
        mb = b_local // M
        if seq * n_seq > model.max_len:
            raise ValueError(
                f"global sequence {seq * n_seq} exceeds max_len {model.max_len}"
            )
        global_tokens = b_local * seq * n_data * n_seq
        stage = jax.lax.axis_index(STAGE_AXIS)
        is_last = stage == n_stages - 1
        tok = tokens.reshape(M, mb, seq)
        lab = labels.reshape(M, mb, seq)
        perm_f = [(s, (s + 1) % n_stages) for s in range(n_stages)]
        perm_b = [(s, (s - 1) % n_stages) for s in range(n_stages)]

        f_mb, f_on, b_mb, b_on, W = _sim_1f1b(M, n_stages)
        # receive-side schedules: what arrives THIS tick is whatever the
        # neighbor's slot ran this tick (the ppermute happens in-tick);
        # stage 0 never receives activations, the last never receives dy
        fr_mb = np.roll(f_mb, 1, axis=1)
        fr_on = np.roll(f_on, 1, axis=1)
        fr_on[:, 0] = False
        br_mb = np.roll(b_mb, -1, axis=1)
        br_on = np.roll(b_on, -1, axis=1)
        br_on[:, -1] = False
        sched = jax.tree.map(
            jnp.asarray, (f_mb, f_on, b_mb, b_on, fr_mb, fr_on, br_mb, br_on)
        )

        def stage_fn(p, tok_mb, lab_mb, x_recv):
            # same cond-gating construction as grads_gpipe (see the comment
            # there): shared params pcast to stage-varying FIRST so the
            # AD-inserted stage-psum of their cotangent runs at the pcast
            # transpose (every tick, all stages — the per-tick vjp below
            # differentiates this whole function) instead of inside a
            # branch only one stage takes
            shared = mark_varying(p["shared"], (STAGE_AXIS,))
            x_in = jax.lax.cond(
                stage == 0,
                lambda: mark_varying(embed(shared, tok_mb), loss_axes),
                lambda: x_recv,
            )
            y = apply_blocks(p["blocks"], x_in)

            def head_loss():
                logits = apply_head(shared, y)
                return mark_varying(
                    lm_loss_local(
                        logits, lab_mb, global_tokens, label_smoothing
                    ),
                    loss_axes,
                )

            part = jax.lax.cond(
                is_last,
                head_loss,
                lambda: mark_varying(jnp.float32(0.0), loss_axes),
            )
            return y, part

        def sel(row):
            return jnp.take(row, stage, axis=0)

        def tick(carry, xs):
            x_buf, dy_buf, x_saved, gacc, loss_acc = carry
            fm, fo, bm, bo, frm, fro, brm, bro = (sel(r) for r in xs)

            # ---- forward slot (masked by fo) ----
            x_in = x_buf[fm % W]
            x_saved = jnp.where(fo, x_saved.at[fm % W].set(x_in), x_saved)
            y, lo = stage_fn(params, tok[fm], lab[fm], x_in)
            loss_acc = loss_acc + jnp.where(fo & is_last, lo, 0.0)
            y_recv = jax.lax.ppermute(y, STAGE_AXIS, perm_f)
            x_buf = jnp.where(fro, x_buf.at[frm % W].set(y_recv), x_buf)

            # ---- backward slot (masked by bo): recompute-vjp at the saved
            # stage input, seed (dy from the next stage, dloss = 1).
            # MASKING GOES INTO THE SEEDS, not onto dp: shard_map AD psums
            # the cotangent of any mesh-invariant primal (shared params are
            # (data, stage)-invariant, block params data-invariant), so dp
            # comes back ALREADY reduced across devices each tick — an
            # after-the-fact `where(bo, dp, 0)` would keep other stages'
            # garbage and re-psumming would double-count.  A zero seed on an
            # inactive stage zeroes its contribution inside the transpose,
            # which is exactly the per-stage mask.
            xs_in = x_saved[bm % W]
            dy_in = jnp.where(
                is_last | ~bo, jnp.zeros_like(xs_in), dy_buf[bm % W]
            )
            _, vjp_fn = jax.vjp(
                lambda p_, xr: stage_fn(p_, tok[bm], lab[bm], xr), params, xs_in
            )
            cts = mark_varying(
                (
                    dy_in.astype(model.dtype),
                    jnp.where(bo, jnp.float32(1.0), jnp.float32(0.0)),
                ),
                loss_axes,
            )
            dp, dx = vjp_fn(cts)
            gacc = jax.tree.map(jnp.add, gacc, dp)
            dx_recv = jax.lax.ppermute(dx, STAGE_AXIS, perm_b)
            dy_buf = jnp.where(bro, dy_buf.at[brm % W].set(dx_recv), dy_buf)
            return (x_buf, dy_buf, x_saved, gacc, loss_acc), None

        act = (W, mb, seq, model.embed_dim)
        # gacc's vma must mirror what the vjp hands back (see seed-masking
        # comment): block grads come back data-psummed (varying over stage
        # only), shared grads fully reduced (invariant) — the activation
        # buffers and the loss are genuinely per-device
        gacc0 = {
            "blocks": mark_varying(
                jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params["blocks"]
                ),
                (STAGE_AXIS,),
            ),
            "shared": jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params["shared"]
            ),
        }
        carry0 = (
            *mark_varying(
                (
                    jnp.zeros(act, model.dtype),
                    jnp.zeros(act, model.dtype),
                    jnp.zeros(act, model.dtype),
                ),
                loss_axes,
            ),
            gacc0,
            mark_varying(jnp.float32(0.0), loss_axes),
        )
        (_, _, _, gacc, loss_sum), _ = jax.lax.scan(tick, carry0, sched)

        # no explicit grad collectives: the per-tick vjp transpose already
        # psummed each cotangent to its primal's invariance (blocks over
        # data, shared over data AND stage — see the seed-masking comment),
        # so gacc IS the fully-reduced gradient after the scan
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), gacc, params)
        loss = jax.lax.psum(loss_sum, loss_axes)
        return grads, loss

    grads_fn = grads_gpipe if schedule == "gpipe" else grads_1f1b

    def step_body(params, opt_state, tokens, labels):
        grads, loss = grads_fn(params, tokens, labels)
        lr = lr_fn(opt_state.step)
        new_params, new_opt = optimizer.update(grads, opt_state, params, lr)
        return new_params, new_opt, loss

    def compile_for(state: TrainState):
        param_spec = pp_param_specs(state.params)
        opt_spec = _opt_specs(state, param_spec)
        tok_spec = P(DATA_AXIS, seq_axis) if seq_axis else P(DATA_AXIS, None)
        # PP x TP: leave the 'model' axis to the GSPMD partitioner (manual
        # over data/stage only) — Megatron splits inside each stage, from
        # the sharded params' own NamedShardings
        manual = {}
        if MODEL_AXIS in mesh.axis_names and mesh.shape[MODEL_AXIS] > 1:
            manual = dict(axis_names=frozenset({DATA_AXIS, STAGE_AXIS}))
        if zero:
            # ZeRO x PP: only the GRADIENT computation runs in the
            # manual shard_map (data-sharded moments must not enter it —
            # the manual in_specs would gather them, defeating the
            # sharding).  The elementwise update runs outside under GSPMD:
            # the data-sharded moment shardings (pp_state_shardings
            # zero=True) make the partitioner reduce-scatter the grads
            # into the moment update and gather the fresh stage-sharded
            # params — the same construction as the GSPMD TP ZeRO path.
            # Stage 2 additionally pins the grads themselves to the moment
            # layout right at the shard_map boundary, so each device holds
            # a 1/N_data gradient slice instead of the data-replicated
            # stage-sharded tree (the PP analog of tp_steps' shard_grads).
            sharded_grads = jax.shard_map(
                grads_fn,
                mesh=mesh,
                in_specs=(param_spec, tok_spec, tok_spec),
                out_specs=(param_spec, P()),
                **manual,
            )
            param_sh = jax.tree.map(lambda x: x.sharding, state.params)
            moment_sh = None
            if int(zero) >= 2:
                from ..parallel.tensor import param_mirror_fields

                mirrors = param_mirror_fields(state.opt_state, state.params)
                if mirrors:
                    moment_sh = jax.tree.map(
                        lambda x: x.sharding,
                        getattr(state.opt_state, mirrors[0]),
                    )
                    # the grad pin below assumes ONE moment layout; ZeRO
                    # sharding applies uniformly to every params-mirroring
                    # field (parallel/zero.py), so any disagreement means
                    # the opt state was built inconsistently — fail loudly
                    # here rather than pin grads to the wrong layout
                    for m in mirrors[1:]:
                        other = jax.tree.map(
                            lambda x: x.sharding, getattr(state.opt_state, m)
                        )
                        if other != moment_sh:
                            raise ValueError(
                                f"ZeRO-2 x PP: opt-state field {m!r} is laid"
                                f" out differently from {mirrors[0]!r}; all"
                                " params-mirroring moment fields must share"
                                " one ZeRO shard layout"
                            )

            def step(state: TrainState, tokens, labels):
                grads, loss = sharded_grads(state.params, tokens, labels)
                if moment_sh is not None:
                    grads = jax.lax.with_sharding_constraint(grads, moment_sh)
                lr = lr_fn(state.opt_state.step)
                new_params, new_opt = optimizer.update(
                    grads, state.opt_state, state.params, lr
                )
                new_params = jax.lax.with_sharding_constraint(
                    new_params, param_sh
                )
                return (
                    TrainState(
                        params=new_params, batch_stats=state.batch_stats,
                        opt_state=new_opt, ema=state.ema,
                    ),
                    loss,
                )

            return register_compiled(
                "lm_train_step/pp_gspmd",
                jax.jit(step, donate_argnums=(0,) if donate else ()),
            )

        sharded = jax.shard_map(
            step_body,
            mesh=mesh,
            in_specs=(param_spec, opt_spec, tok_spec, tok_spec),
            out_specs=(param_spec, opt_spec, P()),
            **manual,
        )

        def step(state: TrainState, tokens, labels):
            new_params, new_opt, loss = sharded(
                state.params, state.opt_state, tokens, labels
            )
            return (
                TrainState(
                    params=new_params, batch_stats=state.batch_stats,
                    opt_state=new_opt, ema=state.ema,
                ),
                loss,
            )

        return register_compiled(
            "lm_train_step/pp",
            jax.jit(step, donate_argnums=(0,) if donate else ()),
        )

    return compile_for


def _opt_specs(state: TrainState, param_spec):
    """Spec pytree for the optimizer state: params-shaped moment fields
    mirror the param specs, scalars replicate."""
    return mirror_opt_fields(state.opt_state, state.params, param_spec, P())


def build_pp_lm_eval_step(model, mesh: Mesh, num_microbatches: int, seq_axis=None):
    """Compile the DP x PP LM validation step.

    Same replicated ``(loss, acc1, acc5)`` contract as every other eval step
    (mean CE per token + next-token top-1/top-5), so ``Runner.validate``
    drives it unchanged.  Runs the same microbatch schedule forward-only.
    """
    import math

    n_stages = mesh.shape[STAGE_AXIS]
    n_data = mesh.shape[DATA_AXIS]
    n_seq = mesh.shape[seq_axis] if seq_axis else 1
    red_axes = (DATA_AXIS, STAGE_AXIS) + ((seq_axis,) if seq_axis else ())
    M_cfg = int(num_microbatches)
    embed, apply_blocks, apply_head = _stage_applies(model, seq_axis)

    def body(params, tokens, labels):
        b_local, seq = tokens.shape
        # the val loader keeps its ragged tail batch (drop_last=False,
        # reference :219-222), so unlike the train step this must accept
        # any per-shard batch: fall back to the largest microbatch count
        # that divides it (a tail batch recompiles anyway — new shape)
        M = math.gcd(M_cfg, b_local)
        if M != M_cfg:
            # a tail batch coprime with M_cfg degenerates to M=1 (one
            # whole-batch microbatch: an activation-memory spike and a
            # fully serial pipeline tick pattern) — surface it (trace-time,
            # once per distinct tail shape; round-2 ADVICE)
            import logging

            logging.getLogger(__name__).warning(
                "pp eval: per-shard tail batch %d not divisible by "
                "microbatches %d; falling back to M=%d for this batch",
                b_local, M_cfg, M,
            )
        feed_idx, feed_valid, emit_idx, emit_valid = _schedule(M, n_stages)
        mb = b_local // M
        if seq * n_seq > model.max_len:
            # same guard as the train bodies: beyond the table,
            # dynamic_slice would CLAMP and silently reuse position rows
            raise ValueError(
                f"global sequence {seq * n_seq} exceeds max_len {model.max_len}"
            )
        global_tokens = b_local * seq * n_data * n_seq
        stage = jax.lax.axis_index(STAGE_AXIS)
        tok = tokens.reshape(M, mb, seq)
        lab = labels.reshape(M, mb, seq)
        perm = [(s, (s + 1) % n_stages) for s in range(n_stages)]

        def tick(carry, xs):
            x, loss_acc, c1, c5 = carry
            f_i, f_valid, e_i, valid = xs
            # same stage-gating as the train step (module docstring):
            # forward-only, so no cotangent-psum hazard — plain conds
            x_in = jax.lax.cond(
                (stage == 0) & f_valid,
                lambda: mark_varying(embed(params["shared"], tok[f_i]), red_axes),
                lambda: x,
            )
            y = apply_blocks(params["blocks"], x_in)

            def head_metrics():
                logits = apply_head(params["shared"], y)
                part = lm_loss_local(logits, lab[e_i], global_tokens)
                flat = logits.reshape(-1, logits.shape[-1])
                flab = lab[e_i].reshape(-1)
                top5 = jax.lax.top_k(flat, 5)[1]
                hit1 = jnp.sum(top5[:, 0] == flab)
                hit5 = jnp.sum(jnp.any(top5 == flab[:, None], axis=1))
                return mark_varying((part, hit1, hit5), red_axes)

            emit_mask = valid & (stage == n_stages - 1)
            part, hit1, hit5 = jax.lax.cond(
                emit_mask,
                head_metrics,
                lambda: mark_varying(
                    (jnp.float32(0.0), jnp.int32(0), jnp.int32(0)), red_axes
                ),
            )
            loss_acc = loss_acc + part
            c1 = c1 + hit1
            c5 = c5 + hit5
            x_next = jax.lax.ppermute(y, STAGE_AXIS, perm)
            return (x_next, loss_acc, c1, c5), None

        carry0 = mark_varying(
            (jnp.zeros((mb, seq, model.embed_dim), model.dtype),
             jnp.float32(0.0), jnp.int32(0), jnp.int32(0)),
            red_axes,
        )
        (_, loss_sum, c1, c5), _ = jax.lax.scan(
            tick, carry0, (feed_idx, feed_valid, emit_idx, emit_valid)
        )
        axes = red_axes
        loss = jax.lax.psum(loss_sum, axes)
        total = jnp.float32(global_tokens)
        acc1 = jax.lax.psum(c1, axes).astype(jnp.float32) / total * 100.0
        acc5 = jax.lax.psum(c5, axes).astype(jnp.float32) / total * 100.0
        return loss, acc1, acc5

    def compile_for(state: TrainState):
        param_spec = pp_param_specs(state.params)
        tok_spec = P(DATA_AXIS, seq_axis) if seq_axis else P(DATA_AXIS, None)
        manual = {}
        if MODEL_AXIS in mesh.axis_names and mesh.shape[MODEL_AXIS] > 1:
            manual = dict(axis_names=frozenset({DATA_AXIS, STAGE_AXIS}))
        sharded = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(param_spec, tok_spec, tok_spec),
            out_specs=(P(), P(), P()),
            **manual,
        )

        @jax.jit
        def eval_step(state: TrainState, tokens, labels):
            return sharded(state.params, tokens, labels)

        return eval_step

    return compile_for
