"""Compiled pipeline-parallel (DP x PP) LM training step.

GPipe microbatch schedule as ONE ``shard_map``-ed XLA program on a
``(data, stage)`` mesh — see :mod:`..parallel.pipeline` for the layout and
the exactness argument.  The reference has no pipeline axis at all
(SURVEY.md §2.4); this composes with data parallelism the same way the SP
and TP steps do and plugs into the same ``Runner`` contract.

Design notes (TPU/XLA):
  - the tick loop is a ``lax.scan`` (static trip count ``M + S - 1``), so
    the whole schedule — including the bubble — compiles once; no Python
    per-tick dispatch.
  - inter-stage transfer is a single ``ppermute`` per tick over the
    ``stage`` axis (nearest-neighbor ICI DMA), which XLA overlaps with the
    next tick's compute where the dependence allows.
  - under SPMD every stage runs the same program, so embedding and head
    math execute on all stages each tick and the unused results are masked
    out.  The head is NOT negligible at large vocab (at the shipped
    TransformerLM-pp.yml scale it is ~40% of a stage's per-tick FLOPs) —
    but because stages advance in lockstep (each tick ends at the
    ppermute), per-tick wall time is set by the last stage, which must pay
    the head anyway; the redundant copies burn energy, not time.  The
    standard remedy when it matters is rebalancing (fewer blocks on the
    last stage), which the stacked-layer layout does not support yet.
    What is never duplicated: the blocks — each stage applies only its own
    layer shard.
  - tick inputs are index-clipped to real microbatches (never garbage), so
    bubble ticks compute on valid data and masking alone guarantees
    correctness — no NaN-through-``where`` hazards.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer_lm import DecoderBlock
from ..parallel.mesh import DATA_AXIS
from ..parallel.pipeline import STAGE_AXIS, pp_param_specs
from ..parallel.tensor import mirror_opt_fields
from ..utils.vma import mark_varying
from .sp_steps import lm_loss_local
from .steps import TrainState

__all__ = ["build_pp_lm_train_step", "build_pp_lm_eval_step"]


def _stage_applies(model):
    """(embed, blocks, head) closures over a TransformerLM's hyperparams.

    Reuses the model's own flax modules for the shared pieces so the math is
    bit-identical to ``TransformerLM.__call__`` (models/transformer_lm.py).
    """
    block = DecoderBlock(
        num_heads=model.num_heads,
        mlp_ratio=model.mlp_ratio,
        seq_axis=None,
        seq_impl=model.seq_impl,
        dtype=model.dtype,
    )
    ln = nn.LayerNorm(dtype=model.dtype)
    head = nn.Dense(model.vocab_size, dtype=jnp.float32)

    def embed(shared, tokens):
        x = jnp.take(shared["tok_embedding"], tokens, axis=0).astype(model.dtype)
        pe = shared["pos_embedding"][: tokens.shape[1]]
        return x + pe[None].astype(model.dtype)

    def apply_blocks(blocks_local, x):
        def layer(x, p):
            return block.apply({"params": p}, x), None

        f = jax.checkpoint(layer) if model.remat else layer
        x, _ = jax.lax.scan(f, x, blocks_local)
        return x

    def apply_head(shared, x):
        h = ln.apply({"params": shared["ln"]}, x)
        return head.apply({"params": shared["head"]}, h)

    return embed, apply_blocks, apply_head


def _schedule(n_micro: int, n_stages: int):
    """Static GPipe tick schedule: (feed index, emit index, emit mask).

    Tick ``t``: stage 0 ingests microbatch ``t`` (clipped — re-feeding the
    last microbatch during drain ticks keeps the data real), the last stage
    finishes microbatch ``t - (S-1)``; its loss only counts once ``t`` has
    passed the fill bubble.
    """
    ticks = np.arange(n_micro + n_stages - 1)
    feed_idx = np.clip(ticks, 0, n_micro - 1)
    emit_idx = np.clip(ticks - (n_stages - 1), 0, n_micro - 1)
    emit_valid = ticks >= n_stages - 1
    return (
        jnp.asarray(feed_idx, jnp.int32),
        jnp.asarray(emit_idx, jnp.int32),
        jnp.asarray(emit_valid),
    )


def build_pp_lm_train_step(
    model,
    optimizer,
    lr_fn: Callable,
    mesh: Mesh,
    num_microbatches: int,
    donate: bool = True,
    label_smoothing: float = 0.0,
):
    """Compile one DP x PP LM iteration.

    ``model``: a :class:`TransformerLM` (``seq_axis=None``); its params must
    be in the pipeline layout (:func:`..parallel.pipeline.pp_stack_params`).
    The optimizer must be elementwise per-leaf (SGD / AdamW — LARS computes
    per-parameter norms, which would span the stacked layer axis and change
    semantics; the Runner rejects that combination).

    Returns ``compile_for(state)`` pinning the state's stage shardings,
    mirroring :func:`..engine.tp_steps.build_tp_lm_train_step`.
    """
    n_stages = mesh.shape[STAGE_AXIS]
    n_data = mesh.shape[DATA_AXIS]
    M = int(num_microbatches)
    if M < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M}")
    embed, apply_blocks, apply_head = _stage_applies(model)
    feed_idx, emit_idx, emit_valid = _schedule(M, n_stages)

    def body(params, opt_state, tokens, labels):
        b_local, seq = tokens.shape
        if b_local % M != 0:
            raise ValueError(
                f"per-shard batch {b_local} not divisible by "
                f"num_microbatches {M}"
            )
        mb = b_local // M
        global_tokens = b_local * seq * n_data
        stage = jax.lax.axis_index(STAGE_AXIS)
        tok = tokens.reshape(M, mb, seq)
        lab = labels.reshape(M, mb, seq)
        perm = [(s, (s + 1) % n_stages) for s in range(n_stages)]

        def loss_fn(p):
            def tick(carry, xs):
                x, loss_acc = carry
                f_i, e_i, valid = xs
                inj = embed(p["shared"], tok[f_i])
                x_in = jnp.where(stage == 0, inj, x)
                y = apply_blocks(p["blocks"], x_in)
                logits = apply_head(p["shared"], y)
                part = lm_loss_local(
                    logits, lab[e_i], global_tokens, label_smoothing
                )
                is_last = stage == n_stages - 1
                loss_acc = loss_acc + jnp.where(valid & is_last, part, 0.0)
                x_next = jax.lax.ppermute(y, STAGE_AXIS, perm)
                return (x_next, loss_acc), None

            # the carry is device-varying (each stage holds a different
            # activation), so the constant initial carry must be promoted
            x0, l0 = mark_varying(
                (jnp.zeros((mb, seq, model.embed_dim), model.dtype),
                 jnp.float32(0.0)),
                (DATA_AXIS, STAGE_AXIS),
            )
            (_, loss_sum), _ = jax.lax.scan(
                tick, (x0, l0), (feed_idx, emit_idx, emit_valid)
            )
            # global mean CE as a replicated scalar: only the last stage
            # holds nonzero partials, the psum both totals them over data
            # and broadcasts over stage — differentiating THIS is what makes
            # the pipeline backward exact (module docstring)
            return jax.lax.psum(loss_sum, (DATA_AXIS, STAGE_AXIS))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        lr = lr_fn(opt_state.step)
        new_params, new_opt = optimizer.update(grads, opt_state, params, lr)
        return new_params, new_opt, loss

    def compile_for(state: TrainState):
        param_spec = pp_param_specs(state.params)
        opt_spec = _opt_specs(state, param_spec)
        tok_spec = P(DATA_AXIS, None)
        sharded = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(param_spec, opt_spec, tok_spec, tok_spec),
            out_specs=(param_spec, opt_spec, P()),
        )

        def step(state: TrainState, tokens, labels):
            new_params, new_opt, loss = sharded(
                state.params, state.opt_state, tokens, labels
            )
            return (
                TrainState(
                    params=new_params, batch_stats=state.batch_stats,
                    opt_state=new_opt, ema=state.ema,
                ),
                loss,
            )

        return jax.jit(step, donate_argnums=(0,) if donate else ())

    return compile_for


def _opt_specs(state: TrainState, param_spec):
    """Spec pytree for the optimizer state: params-shaped moment fields
    mirror the param specs, scalars replicate."""
    return mirror_opt_fields(state.opt_state, state.params, param_spec, P())


def build_pp_lm_eval_step(model, mesh: Mesh, num_microbatches: int):
    """Compile the DP x PP LM validation step.

    Same replicated ``(loss, acc1, acc5)`` contract as every other eval step
    (mean CE per token + next-token top-1/top-5), so ``Runner.validate``
    drives it unchanged.  Runs the same microbatch schedule forward-only.
    """
    import math

    n_stages = mesh.shape[STAGE_AXIS]
    n_data = mesh.shape[DATA_AXIS]
    M_cfg = int(num_microbatches)
    embed, apply_blocks, apply_head = _stage_applies(model)

    def body(params, tokens, labels):
        b_local, seq = tokens.shape
        # the val loader keeps its ragged tail batch (drop_last=False,
        # reference :219-222), so unlike the train step this must accept
        # any per-shard batch: fall back to the largest microbatch count
        # that divides it (a tail batch recompiles anyway — new shape)
        M = math.gcd(M_cfg, b_local)
        feed_idx, emit_idx, emit_valid = _schedule(M, n_stages)
        mb = b_local // M
        global_tokens = b_local * seq * n_data
        stage = jax.lax.axis_index(STAGE_AXIS)
        tok = tokens.reshape(M, mb, seq)
        lab = labels.reshape(M, mb, seq)
        perm = [(s, (s + 1) % n_stages) for s in range(n_stages)]

        def tick(carry, xs):
            x, loss_acc, c1, c5 = carry
            f_i, e_i, valid = xs
            inj = embed(params["shared"], tok[f_i])
            x_in = jnp.where(stage == 0, inj, x)
            y = apply_blocks(params["blocks"], x_in)
            logits = apply_head(params["shared"], y)
            part = lm_loss_local(logits, lab[e_i], global_tokens)
            flat = logits.reshape(-1, logits.shape[-1])
            flab = lab[e_i].reshape(-1)
            top5 = jax.lax.top_k(flat, 5)[1]
            hit1 = jnp.sum(top5[:, 0] == flab)
            hit5 = jnp.sum(jnp.any(top5 == flab[:, None], axis=1))
            emit_mask = valid & (stage == n_stages - 1)
            loss_acc = loss_acc + jnp.where(emit_mask, part, 0.0)
            c1 = c1 + jnp.where(emit_mask, hit1, 0)
            c5 = c5 + jnp.where(emit_mask, hit5, 0)
            x_next = jax.lax.ppermute(y, STAGE_AXIS, perm)
            return (x_next, loss_acc, c1, c5), None

        carry0 = mark_varying(
            (jnp.zeros((mb, seq, model.embed_dim), model.dtype),
             jnp.float32(0.0), jnp.int32(0), jnp.int32(0)),
            (DATA_AXIS, STAGE_AXIS),
        )
        (_, loss_sum, c1, c5), _ = jax.lax.scan(
            tick, carry0, (feed_idx, emit_idx, emit_valid)
        )
        axes = (DATA_AXIS, STAGE_AXIS)
        loss = jax.lax.psum(loss_sum, axes)
        total = jnp.float32(global_tokens)
        acc1 = jax.lax.psum(c1, axes).astype(jnp.float32) / total * 100.0
        acc5 = jax.lax.psum(c5, axes).astype(jnp.float32) / total * 100.0
        return loss, acc1, acc5

    def compile_for(state: TrainState):
        param_spec = pp_param_specs(state.params)
        tok_spec = P(DATA_AXIS, None)
        sharded = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(param_spec, tok_spec, tok_spec),
            out_specs=(P(), P(), P()),
        )

        @jax.jit
        def eval_step(state: TrainState, tokens, labels):
            return sharded(state.params, tokens, labels)

        return eval_step

    return compile_for
