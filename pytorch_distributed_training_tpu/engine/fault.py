"""Deterministic fault injection + recovery counters (the chaos harness).

Every recovery path in the fault-tolerance layer (anomaly-step guard,
retrying checkpoint I/O, worker respawn, hung-step watchdog) is proved by
injecting its failure deterministically and asserting the recovery — not by
hoping production reproduces it.  The injector is a process-global registry
parsed from the ``PDT_FAULT_SPEC`` environment variable (or the
``training.fault_tolerance.fault_spec`` config key; env wins so a chaos
wrapper can override any config).

Spec grammar — a list of entries separated by ``;`` or ``,`` (both
accepted so shell-quoted comma lists like
``PDT_FAULT_SPEC="kill_peer@8,sdc_flip@9:0"`` compose multiple concurrent
faults), each entry ``kind@step[:arg]``.  The whole list is validated at
parse time: any malformed entry, unknown kind, or duplicate ``kind@step``
pair rejects the entire spec — a chaos scenario must fail loudly at
install, never silently drop one of its faults:

    nan_batch@K        poison the training batch fed to step K with NaNs
                       (float image pipelines; the anomaly guard must skip
                       the step)
    kill_worker@K[:W]  SIGKILL loader pool worker W (default 0) at step K
                       (the pool must respawn it, no batch lost)
    stall_step@K[:SEC] sleep SEC (default 1.0) inside step K's host window
                       (the watchdog must fire)
    kill_peer@K[:R]    SIGKILL THIS training process at step K when its
                       process index is R (default -1 = any rank) — the
                       multi-host peer-death scenario: surviving ranks must
                       detect the silence via the elastic heartbeat layer
                       (engine/elastic.py) instead of hanging in the next
                       collective
    sdc_flip@K[:R]     silently flip one parameter bit on replica R
                       (default 0; -1 = whichever rank parses it) at step K
                       — no raise, no NaN: the integrity sentinel
                       (engine/integrity.py) must detect the divergence at
                       its next fingerprint vote, attribute it to rank R,
                       and restore the healthy-majority state
    ckpt_corrupt@K     flip one bit in the payload of the checkpoint SAVED
                       at step K (after its checksum manifest is computed)
                       — the save commits cleanly and orbax restores it
                       without error; only the manifest verification at
                       restore time can reject it in favor of the newest
                       verified earlier step
    ckpt_fail@A[:N]    fail checkpoint-save attempts A..A+N-1 (0-based
                       attempt ordinal across the process; the retry policy
                       must absorb them)
    restore_fail@A[:N] same for checkpoint-restore attempts
    ckpt_async_fail@A[:N]
                       same for ASYNC checkpoint-write attempts — fires on
                       the background writer thread (the ``ckpt_async_write``
                       fail point), so the chaos harness can kill an
                       in-flight overlapped save deterministically and prove
                       the deferred-error + restore-fallback contract

Serving-side kinds (the ``step`` is the continuous scheduler's TICK
index, 1-based — serving/scheduler.py consults the injector once per
tick):

    serve_nan@T[:S]    corrupt the KV-pool rows of the request occupying
                       slot S (default 0) at tick T with NaNs — the
                       on-device output guard must evict exactly that
                       request, bit-exact for every other slot
    serve_raise@T[:S]  the request in slot S (default 0) raises from the
                       decode dispatch at tick T — the poison-bisect path
                       must isolate it without failing the world
    serve_device_lost@T
                       raise :class:`DeviceLostError` from tick T's decode
                       dispatch — the supervisor must hot-restart the
                       engine and replay every in-flight request
                       token-identically
    serve_hang@T[:SEC] sleep SEC (default 1.0) inside tick T — the tick
                       watchdog must fire and convert the stall into a
                       diagnosed restart

Lagged guard semantics under the async decode pipeline
(``serving.scheduler.async_depth > 0``): the injection still lands at
tick T's DISPATCH, but its observable consequence moves to the drain of
that step — up to ``async_depth`` ticks later.  ``serve_nan``'s
non-finite flag is read at drain time (eviction one-or-more ticks late,
attribution unchanged); ``serve_raise`` surfaces when the dispatch
itself runs, and the supervisor drains the in-flight ring
(``flush_async``) before poison-bisecting so the sync probe sees a
state-consistent pool.  The isolation contract is identical either way:
exactly the faulted request fails, survivors stay bit-exact.

Fleet-side kinds (the ``step`` is the fleet router's monitor POLL index,
1-based — serving/router.py consults the injector once per health sweep):

    replica_down@P[:R] hard-kill replica R (default 0) at router poll P:
                       its in-flight requests fail with
                       ``ReplicaDownError`` and the router must fail them
                       over to a survivor with token-identical replay
    replica_hang@P[:SEC]
                       wedge replica 0's scheduler thread for SEC
                       (default 1.0) seconds at router poll P — no Python
                       progress, so only the heartbeat-staleness check
                       can see it; the router must mark the replica
                       unhealthy and hedge/fail over around it

Autoscaler-level kinds (P = autoscaler poll index, 1-based —
serving/autoscaler.py consults the injector once per control-loop poll):

    autoscale_hang@P[:SEC]
                       wedge the autoscaler's decision path for SEC
                       (default 1.0) seconds at its poll P — the world
                       keeps moving (flash crowd grows, replicas die)
                       while the controller sleeps; recovery contract is
                       that signals are re-read fresh AFTER the hang, so
                       a stale pre-hang view never drives a scale action

Disaggregation-level kinds (N = the disagg coordinator's KV TRANSFER
ordinal, 1-based — serving/disagg.py consults the injector once per
transfer attempt):

    kv_transfer_stall@N[:SEC]
                       sleep SEC (default 1.0) inside transfer N's
                       export on the source scheduler — the
                       coordinator's bounded deadline must trip and
                       degrade the request to the colocated path
    kv_transfer_corrupt@N
                       flip one byte of transfer N's first block payload
                       after its CRC-32 manifest is computed — the
                       importing scheduler must reject the block and the
                       request recomputes the suffix locally
    prefill_replica_down@N[:R]
                       hard-kill prefill replica R (default 0) as
                       transfer N begins, so the in-flight export dies —
                       the decode side must recompute locally and the
                       request never fails

Step-keyed faults (``nan_batch``/``kill_worker``/``stall_step``/
``sdc_flip``/``ckpt_corrupt``/the ``serve_*``, ``replica_*``, and
``kv_transfer_*``/``prefill_*`` families) are one-shot:
consumed when they fire, so a rollback replay of the same step index does
not re-trip them (the recovery itself must converge).

This module is import-light on purpose (stdlib only): the data pipeline and
serving stack consult it without pulling the JAX engine in.  The recovery
counters every subsystem bumps (``skipped_steps``, ``rollbacks``,
``ckpt_retries``, ``worker_respawns``, ``watchdog_fires``, ...) live in the
process-global telemetry registry (``telemetry/registry.py`` — also
stdlib-only); ``bump``/``counters``/``reset_counters`` here are the
stable API the fault layer and its tests were built on, now thin views of
that one ledger so ``bench.py --chaos`` and the telemetry snapshot read
the same numbers.
"""
from __future__ import annotations

import os
import re
import threading
from collections import Counter
from typing import Dict, List, Optional, Tuple

__all__ = [
    "ENV_VAR",
    "DeviceLostError",
    "FaultInjectionError",
    "FaultInjector",
    "get_injector",
    "install",
    "bump",
    "counters",
    "reset_counters",
]

ENV_VAR = "PDT_FAULT_SPEC"

_STEP_KINDS = (
    "nan_batch", "kill_worker", "stall_step", "kill_peer",
    "sdc_flip", "ckpt_corrupt",
    "serve_nan", "serve_raise", "serve_device_lost", "serve_hang",
    "replica_down", "replica_hang", "autoscale_hang",
    "kv_transfer_stall", "kv_transfer_corrupt", "prefill_replica_down",
)
_POINT_KINDS = {
    "ckpt_fail": "ckpt_save",
    "restore_fail": "ckpt_restore",
    "ckpt_async_fail": "ckpt_async_write",
}


class FaultInjectionError(OSError):
    """An injected I/O failure.

    Subclasses ``OSError`` so it lands in the default retry allowlist
    (``utils.retry.Retry``) exactly like the transient filesystem errors it
    stands in for.
    """


class DeviceLostError(FaultInjectionError):
    """Injected stand-in for losing the accelerator mid-dispatch.

    The serving supervisor classifies it (and real ``XlaRuntimeError``s)
    as non-attributable: no single request caused it, so the recovery is
    hot-restart + replay rather than poison-bisect.
    """


class FaultInjector:
    """Parsed fault spec, queryable by the instrumented call sites."""

    def __init__(self, spec: str = ""):
        self.spec = (spec or "").strip()
        # kind -> {step: arg}; one-shot entries popped when taken
        self._step_faults: Dict[str, Dict[int, float]] = {k: {} for k in _STEP_KINDS}
        # fail point -> [(first_attempt, n_failures)]
        self._fail_windows: Dict[str, List[Tuple[int, int]]] = {}
        self._attempts: Counter = Counter()
        # kind -> number of injected faults that actually FIRED (one-shot
        # takes and fail-point window hits); the soak oracle balances this
        # against pending() to prove no armed fault silently leaked
        self._fired: Counter = Counter()
        self._lock = threading.Lock()
        for raw in re.split(r"[;,]", self.spec):
            entry = raw.strip()
            if not entry:
                continue
            self._parse_entry(entry)

    def _parse_entry(self, entry: str) -> None:
        try:
            kind, rest = entry.split("@", 1)
            parts = rest.split(":", 1)
            step = int(parts[0])
            arg = parts[1] if len(parts) > 1 else None
        except ValueError:
            raise ValueError(
                f"bad {ENV_VAR} entry {entry!r}: want kind@step[:arg]"
            ) from None
        kind = kind.strip()
        if step < 0:
            raise ValueError(f"bad {ENV_VAR} entry {entry!r}: step must be >= 0")
        if kind in _POINT_KINDS:
            n = int(arg) if arg is not None else 1
            if n < 1:
                raise ValueError(
                    f"bad {ENV_VAR} entry {entry!r}: failure count must be >= 1"
                )
            self._fail_windows.setdefault(_POINT_KINDS[kind], []).append((step, n))
        elif kind in _STEP_KINDS:
            if kind in (
                "kill_worker", "serve_nan", "serve_raise", "sdc_flip",
                "replica_down", "prefill_replica_down",
            ):
                # arg = worker index / scheduler slot index / replica rank
                # / fleet replica index / prefill replica index (default 0)
                val = float(int(arg)) if arg is not None else 0.0
            elif kind == "kill_peer":
                # arg = target process index; -1 = whichever rank parses it
                val = float(int(arg)) if arg is not None else -1.0
            elif kind in ("stall_step", "serve_hang", "replica_hang",
                          "autoscale_hang", "kv_transfer_stall"):
                val = float(arg) if arg is not None else 1.0
            else:
                # nan_batch / serve_device_lost / ckpt_corrupt /
                # kv_transfer_corrupt take no arg
                if arg is not None:
                    raise ValueError(
                        f"bad {ENV_VAR} entry {entry!r}: {kind} takes no arg"
                    )
                val = 1.0
            if step in self._step_faults[kind]:
                raise ValueError(
                    f"bad {ENV_VAR} entry {entry!r}: duplicate {kind}@{step} "
                    f"(each kind@step pair may appear once per spec)"
                )
            self._step_faults[kind][step] = val
        else:
            raise ValueError(
                f"bad {ENV_VAR} entry {entry!r}: unknown kind {kind!r} "
                f"(want one of {sorted(_STEP_KINDS) + sorted(_POINT_KINDS)})"
            )

    @property
    def active(self) -> bool:
        return bool(self.spec)

    def take(self, kind: str, step: int) -> Optional[float]:
        """Consume the one-shot fault ``kind@step``; None when absent.

        Returns the entry's arg (worker index for ``kill_worker``, slot
        index for ``serve_nan``/``serve_raise``, stall seconds for
        ``stall_step``/``serve_hang``, 1.0 for the no-arg kinds).
        """
        with self._lock:
            val = self._step_faults[kind].pop(int(step), None)
            if val is not None:
                self._fired[kind] += 1
        if val is not None:
            bump(f"fault_fired_{kind}")
        return val

    def check_fail_point(self, point: str) -> None:
        """Raise :class:`FaultInjectionError` when this attempt ordinal of
        ``point`` (e.g. ``ckpt_save``) falls in an injected failure window."""
        with self._lock:
            ordinal = self._attempts[point]
            self._attempts[point] += 1
            windows = self._fail_windows.get(point, ())
        for first, n in windows:
            if first <= ordinal < first + n:
                with self._lock:
                    self._fired[point] += 1
                bump(f"injected_{point}_failures")
                raise FaultInjectionError(
                    f"injected {point} failure (attempt ordinal {ordinal}, "
                    f"window {first}+{n})"
                )

    def pending(self) -> Dict[str, List[int]]:
        """Armed faults that have NOT fired yet, ``kind -> sorted steps``.

        One-shot entries are listed by step index; fail-point windows by the
        attempt ordinals the process never reached.  A fault armed for a
        step/tick/attempt that never happens (engine drained or closed
        first) would otherwise vanish without a trace — the chaos soak
        oracle balances this against :meth:`fired` so every injected fault
        is accounted for as exactly one of fired-and-recovered or
        reported-unfired.
        """
        with self._lock:
            out: Dict[str, List[int]] = {
                kind: sorted(steps)
                for kind, steps in self._step_faults.items()
                if steps
            }
            for point, windows in self._fail_windows.items():
                seen = self._attempts[point]
                left = sorted(
                    o for first, n in windows
                    for o in range(first, first + n) if o >= seen
                )
                if left:
                    out[point] = left
        return out

    def fired(self) -> Dict[str, int]:
        """Counts of injected faults that actually fired, by kind/point."""
        with self._lock:
            return dict(self._fired)


# ---------------------------------------------------------------- process-global
_INJECTOR: Optional[FaultInjector] = None


def get_injector() -> FaultInjector:
    """The process injector; lazily parsed from ``PDT_FAULT_SPEC`` (inert
    when the variable is unset)."""
    global _INJECTOR
    if _INJECTOR is None:
        _INJECTOR = FaultInjector(os.environ.get(ENV_VAR, ""))
    return _INJECTOR


def install(spec: Optional[str]) -> FaultInjector:
    """Replace the process injector with one parsed from ``spec`` (the
    config-key path, and the test/bench hook).  ``install(None)`` resets to
    inert."""
    global _INJECTOR
    _INJECTOR = FaultInjector(spec or "")
    return _INJECTOR


def bump(name: str, n: int = 1) -> None:
    """Increment a process-global recovery counter (thread-safe)."""
    from ..telemetry.registry import get_registry

    get_registry().counter(name).inc(n)


def counters() -> Dict[str, int]:
    """Snapshot of all process counters (the shared telemetry ledger)."""
    from ..telemetry.registry import get_registry

    return {k: v for k, v in get_registry().counters().items() if v}


def reset_counters() -> None:
    from ..telemetry.registry import reset_registry

    reset_registry()


def poison_batches(host_iter, injector: FaultInjector, start_iter: int = 0,
                   logger=None):
    """Wrap a training batch iterator, applying ``nan_batch`` faults.

    Yields batches unchanged except at injected step indices, where the
    (float) image/token-input half is replaced with NaNs — the on-device
    anomaly guard must then skip the step.  Counting starts at
    ``start_iter`` and stays aligned with the step index because the
    training stream is strictly ordered (``device_prefetch`` preserves
    order; a rebuilt stream passes its new start iter).
    """
    import numpy as np

    step = start_iter
    for img, label in host_iter:
        if injector.take("nan_batch", step) is not None:
            img = np.asarray(img)
            if np.issubdtype(img.dtype, np.floating):
                img = np.full(img.shape, np.nan, dtype=img.dtype)
                bump("injected_nan_batches")
                if logger is not None:
                    logger.warning("fault injection: NaN batch at step %d", step)
            elif logger is not None:
                logger.warning(
                    "fault injection: nan_batch@%d skipped — batch dtype %s "
                    "cannot carry NaN (float pipelines only)", step, img.dtype
                )
        step += 1
        yield img, label
